"""
Symbolic linear/nonlinear operators (reference: dedalus/core/operators.py).

Design: every linear operator is described by a list of **terms**; each term
is (tensor_factor, [axis_descriptor ...]) with one descriptor per distributor
axis. Descriptors:

  None                   identity on that axis
  ('full', A)            dense matrix applied along the (coupled/constant) axis
  ('blocks', B)          per-group blocks B[g] (gs_out, gs_in) on a separable
                         axis (group-diagonal action)

One descriptor set drives BOTH
  * host-side pencil matrix assembly (`subproblem_matrix`: kron of factors
    per group; reference: core/operators.py:900 subproblem_matrix), and
  * device-side evaluation (`ev_impl`: jnp reshape/einsum application).

This mirrors the reference's SpectralOperator1D group-matrix machinery
(core/operators.py:873-947) in a TPU-batched form.
"""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from .field import Operand, Field, transform_to_grid
from .future import Future, EvalContext, ev
from .domain import Domain
from .basis import Jacobi, FourierBase, RealFourier, ComplexFourier
from .coords import Coordinate, CartesianCoordinates
from ..tools.array import (kron as sparse_kron, sparsify, apply_matrix_jax,
                            match_precision)
from ..tools.exceptions import NonlinearOperatorError

# Registry of names injected into problem parsing namespaces
# (reference: core/operators.py:61-83 aliases/parseables).
parseables = {}


def parseable(*names):
    def register(obj):
        for name in names:
            parseables[name] = obj
        return obj
    return register


# ----------------------------------------------------------------------
# Shared helpers

def tensor_identity(tshape):
    n = int(np.prod(tshape, dtype=int)) if tshape else 1
    return sp.identity(n, format="csr")


def _axis_identity(basis, sep_width=None, sub_axis=0):
    """
    Identity factor for an untouched axis. On problem-separable axes the
    uniform pencil slot width (`sep_width` = group_shape) is used even when
    the operand is constant along the axis (its dummy slots are masked by
    validity later); any other axis carries its full coefficient size
    (including separable-capable bases the LAYOUT coupled, e.g. a Fourier
    axis an LHS NCC varies along).
    """
    if sep_width is not None:
        return sp.identity(sep_width, format="csr")
    if basis is None:
        return sp.identity(1, format="csr")
    return sp.identity(basis.coeff_size(sub_axis), format="csr")


def assemble_group_matrix(terms, operand_domain, tshape_in, tshape_out,
                          subproblem, out_domain=None):
    """
    Kron-assemble the pencil matrix of one operator at one group.
    `subproblem.group` is a full-length per-axis tuple (group index on
    separable axes, None elsewhere). `out_domain` (when given) marks axes
    the OUTPUT is constant along — on layout-coupled axes, per-group
    "blocks" reduce (hstack) instead of block-diagonalizing there.
    """
    group = subproblem.group
    sep_widths = subproblem.layout.sep_widths  # {axis: group_shape}
    total = None
    for tensor_factor, axis_descrs in terms:
        if tensor_factor is None:
            factors = [tensor_identity(tshape_in)]
        else:
            factors = [sparsify(tensor_factor)]
        # gblocks whose selector axis the LAYOUT coupled (e.g. radial
        # stacks selected by ell when a theta-dependent NCC couples ell):
        # the (selector x this) joint factor is the block diagonal of the
        # stack in selector-group order, consuming the selector axis's
        # identity slot (valid only for an adjacent, otherwise-untouched
        # selector axis — the kron ordering then matches block_diag's).
        joint_consumed = set()
        for axis, descr in enumerate(axis_descrs):
            if (descr is not None and descr[0] == "gblocks"
                    and group[descr[1]] is None):
                group_axis = descr[1]
                if group_axis != axis - 1 or axis_descrs[group_axis] is not None:
                    raise NotImplementedError(
                        "Layout-coupled gblocks selector must be the "
                        "adjacent untouched axis.")
                joint_consumed.add(group_axis)
        for axis, descr in enumerate(axis_descrs):
            basis = operand_domain.bases[axis]
            sub = 0 if basis is None else axis - basis.first_axis
            if axis in joint_consumed:
                continue  # replaced by the adjacent joint block factor
            if descr is None:
                factors.append(_axis_identity(basis, sep_widths.get(axis), sub))
            else:
                kind = descr[0]
                if kind == "full":
                    factors.append(sparsify(descr[1]))
                elif kind == "blocks":
                    if group[axis] is None:
                        # layout-coupled separable basis (e.g. a Fourier
                        # axis an LHS NCC varies along): the whole-axis
                        # matrix is the block diagonal of the per-group
                        # blocks in group order — except embeddings FROM a
                        # constant axis (operand basis None: stack the
                        # per-group columns) and reductions TO a constant
                        # axis (output basis None: concatenate the
                        # per-group rows)
                        out_const = (out_domain is not None
                                     and out_domain.bases[axis] is None)
                        if basis is None:
                            factors.append(sp.vstack(
                                [sparsify(b) for b in descr[1]],
                                format="csr"))
                        elif out_const:
                            factors.append(sp.hstack(
                                [sparsify(b) for b in descr[1]],
                                format="csr"))
                        else:
                            factors.append(sp.block_diag(
                                [sparsify(b) for b in descr[1]],
                                format="csr"))
                    else:
                        factors.append(sparsify(descr[1][group[axis]]))
                elif kind == "gblocks":
                    # per-group blocks on a coupled axis, group read from a
                    # different (separable) axis
                    _, group_axis, stack = descr
                    if group[group_axis] is None:
                        # selector axis layout-coupled: each group's block
                        # acts identically on that group's pair slots
                        # (e.g. the real (cos, sin) azimuth pair), so the
                        # joint factor is blockdiag_g(I_gs (x) B_g)
                        gb = operand_domain.bases[group_axis]
                        gsub = group_axis - gb.first_axis
                        gw = gb.sub_group_shape(gsub)
                        eye_g = sp.identity(gw, format="csr")
                        factors.append(sp.block_diag(
                            [sp.kron(eye_g, sparsify(b), format="csr")
                             for b in stack], format="csr"))
                    else:
                        factors.append(sparsify(stack[group[group_axis]]))
                else:
                    raise ValueError(kind)
        mat = sparse_kron(*factors)
        total = mat if total is None else total + mat
    return total


def apply_axis_blocks(data, blocks, axis):
    """Apply per-group blocks (G, so, si) along an axis of size G*si."""
    blocks = match_precision(blocks, data.dtype)
    G, so, si = blocks.shape
    moved = jnp.moveaxis(data, axis, -1)
    moved = moved.reshape(moved.shape[:-1] + (G, si))
    out = jnp.einsum("gij,...gj->...gi", blocks, moved)
    out = out.reshape(out.shape[:-2] + (G * so,))
    return jnp.moveaxis(out, -1, axis)


def apply_tensor_factor(data, factor, tshape_in, tshape_out):
    """Apply a (ncomp_out, ncomp_in) factor to the flattened tensor axes."""
    factor = match_precision(factor, data.dtype)
    tdim_in = len(tshape_in)
    spatial = data.shape[tdim_in:]
    flat = data.reshape((int(np.prod(tshape_in, dtype=int)) if tshape_in else 1,) + spatial)
    out = jnp.tensordot(factor, flat, axes=(1, 0))
    return out.reshape(tuple(tshape_out) + spatial)


def apply_term(data, tensor_factor, axis_descrs, tshape_in, tshape_out, tdim_out):
    """Device-side application of one operator term to coeff data."""
    from .curvilinear import apply_group_stack
    out = data
    tdim_in = len(tshape_in)
    for axis, descr in enumerate(axis_descrs):
        if descr is None:
            continue
        kind = descr[0]
        if kind == "full":
            # host numpy/scipy reaches match_precision raw so large
            # matrices are lifted to program arguments, interned by the
            # producer-cached object's identity (tools/jitlift.py)
            out = apply_matrix_jax(descr[1], out, tdim_in + axis)
        elif kind == "blocks":
            out = apply_axis_blocks(out, descr[1], tdim_in + axis)
        elif kind == "gblocks":
            _, group_axis, stack = descr
            gaxis = tdim_in + group_axis
            width = out.shape[gaxis] // stack.shape[0]
            out = apply_group_stack(out, stack, gaxis, tdim_in + axis, width)
    if tensor_factor is not None:
        out = apply_tensor_factor(out, tensor_factor, tshape_in, tshape_out)
    elif tshape_in != tuple(tshape_out):
        raise ValueError("Tensor shape change requires a tensor factor.")
    return out


def operand_expression_matrices(operand, subproblem, vars, **kw):
    """Dispatch expression_matrices for Field leaves and Future nodes."""
    if isinstance(operand, Field):
        if operand in vars:
            size = subproblem.field_size(operand)
            return {operand: sp.identity(size, format="csr")}
        raise NonlinearOperatorError(
            f"Field {operand} on LHS outside an NCC product is not a problem variable.")
    if isinstance(operand, Future):
        return operand.expression_matrices(subproblem, vars, **kw)
    raise NonlinearOperatorError(f"Cannot build matrices for operand {operand!r}")


# ----------------------------------------------------------------------
# Linear operator base

class LinearOperator(Future):
    """Base: single-operand linear spectral operator
    (reference: core/operators.py:591 LinearOperator)."""

    natural_layout = "c"

    @property
    def operand(self):
        return self.args[0]

    def terms(self):
        """[(tensor_factor_or_None, [axis_descr ...]), ...]"""
        raise NotImplementedError

    def device_terms(self):
        """Descriptors for device evaluation (defaults to terms())."""
        return self.terms()

    def expression_matrices(self, subproblem, vars, **kw):
        op_mats = operand_expression_matrices(self.operand, subproblem, vars, **kw)
        M = self.subproblem_matrix(subproblem)
        return {var: M @ mat for var, mat in op_mats.items()}

    def subproblem_matrix(self, subproblem):
        return assemble_group_matrix(
            self.terms(), self.operand.domain,
            self.operand.tshape, self.tshape, subproblem,
            out_domain=self.domain)

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "c")
        total = None
        for tensor_factor, axis_descrs in self.device_terms():
            term = apply_term(data, tensor_factor, axis_descrs,
                              self.operand.tshape, self.tshape, self.tdim)
            total = term if total is None else total + term
        return total

    def ev(self, ctx, layout):
        # fused grid evaluation (core/fusedstep.py FUSED_TRANSFORMS): a
        # registered node's coupled-axis operator chain + dealiased
        # backward transform run as one precomposed composite GEMM,
        # skipping the intermediate coefficient layout. Nodes outside
        # the plan (or contexts without one) take the generic path.
        if layout == "g" and ctx.fusion is not None:
            key = (id(self), layout)
            if key in ctx.memo:
                return ctx.memo[key]
            out = ctx.fusion.grid_eval(self, ctx)
            if out is not None:
                ctx.memo[key] = out
                return out
        return super().ev(ctx, layout)


# ----------------------------------------------------------------------
# Differentiate

class DifferentiateCartesian(LinearOperator):
    """d/dx_i (reference: core/operators.py:1319 Differentiate)."""

    name = "Diff"

    def __init__(self, operand, coord):
        self.coord = coord
        super().__init__(operand, coord)
        self.axis = operand.dist.get_axis(coord)

    def rebuild(self, new_args):
        return DifferentiateCartesian(new_args[0], self.coord)

    def _build_metadata(self):
        operand = self.args[0]
        axis = operand.dist.get_axis(self.coord)
        basis = operand.domain.bases[axis]
        if basis is None:
            raise ValueError("Differentiate along a constant axis; use the factory.")
        bases = list(operand.domain.bases)
        bases[axis] = basis.derivative_basis(1)
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = operand.domain.bases[self.axis]
        descrs = [None] * operand.domain.dim
        if basis.separable:
            descrs[self.axis] = ("blocks", basis.differentiation_blocks())
        else:
            descrs[self.axis] = ("full", basis.differentiation_matrix())
        return [(None, descrs)]


def _resolve_coord(operand, coord):
    """Resolve a coordinate given by NAME to the distributor's Coordinate
    object (strings otherwise fail get_basis identity checks silently)."""
    if not isinstance(coord, str):
        return coord
    return operand.dist.get_coord(coord)


def _resolve_coords(operand, coords):
    """Normalize a coords spec (None, name, Coordinate, coordinate system,
    or sequence of these) to a list of Coordinate objects, or None for
    'all axes'. Resolution happens BEFORE any selection logic so names and
    objects take identical paths."""
    if coords is None:
        return None
    if isinstance(coords, str):
        coords = (coords,)
    expanded = getattr(coords, "coords", None)
    if expanded is not None:
        coords = expanded
    elif not isinstance(coords, (tuple, list)):
        coords = (coords,)
    return [_resolve_coord(operand, c) for c in coords]


@parseable("d", "Differentiate")
def Differentiate(operand, coord):
    if np.isscalar(operand):
        return 0
    if isinstance(coord, CartesianCoordinates):
        raise ValueError("Differentiate needs a single coordinate.")
    coord = _resolve_coord(operand, coord)
    if operand.domain.get_basis(coord) is None:
        return 0
    return DifferentiateCartesian(operand, coord)


# ----------------------------------------------------------------------
# Convert (basis conversion / constant embedding)

class ConvertNode(LinearOperator):
    """
    Convert operand coefficients to target bases: Jacobi derivative-level
    lifts and constant->basis embeddings (reference: core/operators.py:1506
    Convert).
    """

    name = "Convert"

    def __init__(self, operand, target_bases):
        self.target_bases = tuple(target_bases)
        super().__init__(operand)

    def rebuild(self, new_args):
        return ConvertNode(new_args[0], self.target_bases)

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = Domain(operand.dist, self.target_bases)
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def _axis_pairs(self):
        return zip(self.operand.domain.bases, self.target_bases)

    def _build_terms(self, device):
        """
        Cross-combine per-basis conversion terms. Multi-axis bases may emit
        several component-structured terms (e.g. per-spin conversion stacks);
        1D bases emit a single descriptor.
        """
        dim = self.operand.domain.dim
        base_descrs = [None] * dim
        multi_terms = None
        handled = set()
        for axis, (b_in, b_out) in enumerate(self._axis_pairs()):
            if b_in is not None and b_in.dim > 1 and b_in is b_out:
                continue
            if b_in is not None and b_in.dim > 1:
                if id(b_in) in handled:
                    continue
                handled.add(id(b_in))
                terms = b_in.conversion_terms(b_out, self.operand.tensorsig,
                                              self.operand.tshape)
                if multi_terms is not None:
                    raise NotImplementedError("Multiple curvilinear conversions.")
                multi_terms = terms
            elif b_in is None and b_out is not None and b_out.dim > 1:
                # constant -> multi-axis (curvilinear) basis embedding
                sub = axis - b_out.first_axis
                base_descrs[axis] = b_out.constant_component_descr(sub, device)
            else:
                base_descrs[axis] = _conversion_descr(b_in, b_out, device=device)
        if multi_terms is None:
            return [(None, base_descrs)]
        out = []
        for factor, dmap in multi_terms:
            descrs = list(base_descrs)
            for axis, d in dmap.items():
                descrs[axis] = d
            out.append((factor, descrs))
        return out

    def terms(self):
        return self._build_terms(device=False)

    def device_terms(self):
        return self._build_terms(device=True)


def _conversion_descr(b_in, b_out, device):
    if b_in is b_out or b_in == b_out:
        return None
    if b_in is None and b_out is None:
        return None
    if b_in is None:
        # constant -> basis embedding
        if b_out.separable:
            if device:
                col = np.zeros((b_out.size, 1))
                col[0, 0] = 1.0  # k=0 cos / k=0 complex mode slot
                return ("full", col)
            return ("blocks", b_out.constant_blocks())
        return ("full", b_out.constant_column())
    if b_out is None:
        raise ValueError("Cannot convert a basis to a constant.")
    if isinstance(b_in, Jacobi) and isinstance(b_out, Jacobi):
        dk = b_out.k - b_in.k
        if dk == 0:
            return None
        if dk < 0:
            raise ValueError("Cannot convert to a lower derivative basis.")
        return ("full", b_in.conversion_matrix(dk))
    raise ValueError(f"No conversion from {b_in} to {b_out}.")


@parseable("convert", "Convert")
def Convert(operand, target_bases, dist=None):
    if np.isscalar(operand):
        raise ValueError("Wrap scalars in constant fields before converting.")
    target_bases = tuple(target_bases)
    if tuple(operand.domain.bases) == target_bases:
        return operand
    return ConvertNode(operand, target_bases)


def convert_to_domain(operand, domain):
    return Convert(operand, domain.bases)


# ----------------------------------------------------------------------
# Interpolate

class InterpolateCartesian(LinearOperator):
    """Pointwise interpolation along one axis
    (reference: core/operators.py:1037 Interpolate)."""

    name = "interp"

    def __init__(self, operand, coord, position):
        self.coord = coord
        self.position = position
        super().__init__(operand)
        self.axis = operand.dist.get_axis(coord)

    def rebuild(self, new_args):
        return InterpolateCartesian(new_args[0], self.coord, self.position)

    def _build_metadata(self):
        operand = self.args[0]
        axis = operand.dist.get_axis(self.coord)
        bases = list(operand.domain.bases)
        self.basis_in = bases[axis]
        bases[axis] = None
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def terms(self):
        basis = self.basis_in
        descrs = [None] * self.operand.domain.dim
        if basis.separable:
            raise NonlinearOperatorError(
                "Interpolation along a separable (Fourier) axis is not "
                "group-diagonal; it cannot appear on equation LHS.")
        descrs[self.axis] = ("full", basis.interpolation_vector(self.position))
        return [(None, descrs)]

    def device_terms(self):
        basis = self.basis_in
        descrs = [None] * self.operand.domain.dim
        if basis.separable:
            rows = basis.interpolation_rows(self.position).reshape(1, -1)
            descrs[self.axis] = ("full", rows)
        else:
            descrs[self.axis] = ("full", basis.interpolation_vector(self.position))
        return [(None, descrs)]


class AzimuthalInterpolate(Future):
    """
    Interpolation at phi = position on a curvilinear basis (disk, annulus,
    sphere, shell, ball), evaluated in GRID space: the uniform azimuth
    grid is contracted with the exact trigonometric interpolation row and
    the result is broadcast back as a phi-CONSTANT field on the same
    domain — this framework's meridional representation (meridional_basis
    aliases the full basis; a phi-constant field transforms to m=0 modes
    only). Tensor components come out in the coordinate frame at
    phi = position.

    Parity note (reference: core/operators.py:1037 Interpolate): the
    reference also admits azimuthal interpolation in equation LHS
    matrices; here the m-mixing has no per-group pencil matrix, so this
    operator is RHS/output-only (expression_matrices raises).
    """

    name = "interp"
    natural_layout = "g"

    _row_cache = {}

    def __init__(self, operand, basis, position):
        self.basis = basis
        self.position = float(position)
        super().__init__(operand)

    @property
    def operand(self):
        return self.args[0]

    def rebuild(self, new_args):
        return AzimuthalInterpolate(new_args[0], self.basis, self.position)

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def __repr__(self):
        return f"interp({self.args[0]}, phi={self.position})"

    @classmethod
    def _interp_row(cls, Ng, phi0, complex_dtype):
        """Exact trig-interpolation row over Ng uniform azimuth samples
        (closed-form Dirichlet kernel, O(Ng)): row @ samples = f(phi0)
        for any f band-limited to the grid. Even Ng carries a half-weight
        (cosine-only) Nyquist mode, matching real-DFT storage."""
        key = (Ng, round(phi0, 15), complex_dtype)
        if key not in cls._row_cache:
            phis = 2 * np.pi * np.arange(Ng) / Ng
            delta = phi0 - phis
            if complex_dtype:
                ms = np.fft.fftfreq(Ng, d=1.0 / Ng)
                row = np.exp(1j * ms[None, :] * delta[:, None]).sum(1) / Ng
            else:
                if Ng % 2 == 0:
                    M = Ng // 2
                    row = (1.0 + 2.0 * sum(np.cos(m * delta)
                                           for m in range(1, M))
                           + np.cos(M * delta)) / Ng
                else:
                    M = (Ng - 1) // 2
                    row = (1.0 + 2.0 * sum(np.cos(m * delta)
                                           for m in range(1, M + 1))) / Ng
            cls._row_cache[key] = np.ascontiguousarray(row)
        return cls._row_cache[key]

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "g")
        ax = self.tdim + self.basis.first_axis
        Ng = data.shape[ax]
        row = self._interp_row(Ng, self.position,
                               np.iscomplexobj(np.zeros(0, self.dtype)))
        from ..tools.jitlift import device_constant
        r = device_constant(row, dtype=data.dtype)
        val = jnp.tensordot(data, r, axes=[[ax], [0]])
        val = jnp.expand_dims(val, ax)
        return jnp.broadcast_to(val, data.shape)

    def expression_matrices(self, subproblem, vars, **kw):
        raise NotImplementedError(
            "Azimuthal interpolation mixes azimuthal groups and has no "
            "per-pencil matrix; use it on the RHS or in output tasks.")


@parseable("interp", "Interpolate")
def Interpolate(operand, coord, position):
    if np.isscalar(operand):
        return operand
    coord = _resolve_coord(operand, coord)
    basis = operand.domain.get_basis(coord)
    if basis is None:
        return operand
    from .coords import AzimuthalCoordinate
    if getattr(basis, "regularity", False):
        from .spherical3d import SphericalInterpolate
        if isinstance(coord, AzimuthalCoordinate):
            return AzimuthalInterpolate(operand, basis, position)
        if coord != basis.coordsystem.radius:
            raise NotImplementedError(
                "Colatitude interpolation is not supported on shell/ball "
                "bases (radial and azimuthal are).")
        return SphericalInterpolate(operand, position)
    from .polar import PolarInterpolate
    from .curvilinear import SpinBasisMixin
    if isinstance(basis, SpinBasisMixin):
        if isinstance(coord, AzimuthalCoordinate):
            return AzimuthalInterpolate(operand, basis, position)
        return PolarInterpolate(operand, position)
    return InterpolateCartesian(operand, coord, position)


# ----------------------------------------------------------------------
# Integrate / Average

class IntegrateCartesian(LinearOperator):
    """Definite integral along one axis
    (reference: core/operators.py:1120 Integrate)."""

    name = "integ"

    def __init__(self, operand, coord):
        self.coord = coord
        super().__init__(operand)
        self.axis = operand.dist.get_axis(coord)

    def rebuild(self, new_args):
        return IntegrateCartesian(new_args[0], self.coord)

    def _build_metadata(self):
        operand = self.args[0]
        axis = operand.dist.get_axis(self.coord)
        bases = list(operand.domain.bases)
        self.basis_in = bases[axis]
        bases[axis] = None
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def terms(self):
        basis = self.basis_in
        descrs = [None] * self.operand.domain.dim
        if basis.separable:
            descrs[self.axis] = ("blocks", basis.integration_blocks())
        else:
            descrs[self.axis] = ("full", basis.integration_vector())
        return [(None, descrs)]

    def device_terms(self):
        basis = self.basis_in
        descrs = [None] * self.operand.domain.dim
        if basis.separable:
            row = np.zeros((1, basis.size))
            row[0, 0] = basis.length
            descrs[self.axis] = ("full", row)
        else:
            descrs[self.axis] = ("full", basis.integration_vector())
        return [(None, descrs)]


def _curv_selected(curv, coords):
    """Does an explicit coords spec include the curvilinear system's axes?"""
    if coords is None:
        return True
    specs = coords if isinstance(coords, (tuple, list)) else (coords,)
    cs_coords = getattr(curv.coordsystem, "coords", ())
    selected = [spec for spec in specs
                if spec is curv.coordsystem or spec in cs_coords]
    if not selected:
        return False
    # Partial reductions over a coupled 2D basis (e.g. azimuth-only on a
    # sphere) are not supported; reject rather than silently reduce both axes.
    full = any(spec is curv.coordsystem for spec in selected)
    if not full and len([s for s in selected if s in cs_coords]) < len(cs_coords):
        raise NotImplementedError(
            f"Partial integration over a single coordinate of {curv!r} is "
            "not supported; integrate over the full coordinate system.")
    return True


@parseable("integ", "Integrate")
def Integrate(operand, coords=None):
    if np.isscalar(operand):
        return operand
    coords = _resolve_coords(operand, coords)
    out = operand
    curv = _curvilinear_basis(operand)
    if curv is not None and _curv_selected(curv, coords):
        out = _curv_integrate(out, curv)
    if coords is None:
        coords = [b.coord for b in out.domain.bases if b is not None]
    for coord in coords:
        if out.domain.get_basis(coord) is not None:
            out = IntegrateCartesian(out, coord)
    return out


class AzimuthalAverage(LinearOperator):
    """
    Average over the azimuth of a curvilinear basis: the m = 0 projection
    (reference: core/basis.py:5202 AzimuthalAverage family — identity on
    the m = 0 group, zero elsewhere). Output is phi-constant on the same
    domain (this framework's meridional representation; transforms to
    m = 0 content only). LHS-capable: per-m blocks are constant.
    """

    name = "azavg"

    def __init__(self, operand, basis):
        self.basis = basis
        super().__init__(operand)

    @property
    def operand(self):
        return self.args[0]

    def rebuild(self, new_args):
        return AzimuthalAverage(new_args[0], self.basis)

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def terms(self):
        basis = self.basis
        if hasattr(basis, "group_m"):
            ms = np.asarray(basis.group_m())
            gs = basis.sub_group_shape(0)
        else:
            # 1-D azimuthal basis (S1 edge fields): group 0 is m = 0
            ms = np.arange(basis.n_groups)
            gs = basis.group_shape
        blocks = np.zeros((len(ms), gs, gs))
        blocks[ms == 0] = np.eye(gs)
        descrs = [None] * self.operand.domain.dim
        descrs[basis.first_axis] = ("blocks", blocks)
        return [(None, descrs)]


@parseable("azavg", "AzimuthalAverage")
def AzimuthalAverageFactory(operand, coord=None):
    if np.isscalar(operand):
        return operand
    from .coords import AzimuthalCoordinate
    if coord is not None:
        coord = _resolve_coord(operand, coord)
        if not isinstance(coord, AzimuthalCoordinate):
            raise ValueError("AzimuthalAverage requires an azimuthal "
                             "coordinate.")
        basis = operand.domain.get_basis(coord)
    else:
        def is_azimuthal(b):
            if b.dim >= 2:
                return isinstance(b.coordsystem.coords[0],
                                  AzimuthalCoordinate)
            return isinstance(getattr(b, "coord", None), AzimuthalCoordinate)
        basis = next((b for b in operand.domain.bases
                      if b is not None and is_azimuthal(b)), None)
    if basis is None:
        raise ValueError("Operand has no azimuthal basis.")
    return AzimuthalAverage(operand, basis)


@parseable("ave", "Average")
def Average(operand, coords=None):
    if np.isscalar(operand):
        return operand
    coords = _resolve_coords(operand, coords)
    volume = 1.0
    out = operand
    curv = _curvilinear_basis(operand)
    if curv is not None and _curv_selected(curv, coords):
        volume *= curv.volume
        out = _curv_integrate(out, curv)
    if coords is None:
        coords = [b.coord for b in out.domain.bases if b is not None]
    for coord in coords:
        basis = out.domain.get_basis(coord)
        if basis is not None:
            volume *= (basis.bounds[1] - basis.bounds[0])
            out = IntegrateCartesian(out, coord)
    return out / volume


# ----------------------------------------------------------------------
# Lift (tau terms)

class Lift(LinearOperator):
    """
    Embed a lower-dimensional tau field into `basis` via mode `n`
    (reference: core/operators.py:4228 Lift).
    """

    name = "Lift"

    def __init__(self, operand, basis, n):
        self.basis = basis
        self.n = n
        super().__init__(operand)
        self.axis = operand.dist.get_axis(basis.coord)

    def rebuild(self, new_args):
        return Lift(new_args[0], self.basis, self.n)

    def _build_metadata(self):
        operand = self.args[0]
        axis = operand.dist.get_axis(self.basis.coord)
        if operand.domain.bases[axis] is not None:
            raise ValueError("Lift operand must be constant along the lift axis.")
        bases = list(operand.domain.bases)
        bases[axis] = self.basis
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def terms(self):
        index = self.n if self.n >= 0 else self.basis.size + self.n
        descrs = [None] * self.operand.domain.dim
        descrs[self.axis] = ("full", self.basis.lift_column(index))
        return [(None, descrs)]


_CartesianLift = Lift


def LiftFactory(operand, basis, n):
    from .polar import DiskBasis, AnnulusBasis, PolarLift
    if getattr(basis, "regularity", False):
        from .spherical3d import SphericalLift
        return SphericalLift(operand, basis, n)
    if isinstance(basis, (DiskBasis, AnnulusBasis)):
        return PolarLift(operand, basis, n)
    return _CartesianLift(operand, basis, n)


LiftTau = LiftFactory  # deprecated alias (reference: core/operators.py:4271)
parseables["lift"] = LiftFactory


# ----------------------------------------------------------------------
# TimeDerivative (marker)

class TimeDerivative(LinearOperator):
    """Marker for dt in IVPs (reference: core/operators.py:974)."""

    name = "dt"

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def terms(self):
        return [(None, [None] * self.operand.domain.dim)]

    def ev_impl(self, ctx):
        raise NonlinearOperatorError("TimeDerivative cannot be evaluated explicitly.")


def dt(operand):
    if np.isscalar(operand):
        return 0
    return TimeDerivative(operand)


parseables["dt"] = dt
parseables["TimeDerivative"] = dt


# ----------------------------------------------------------------------
# Vector calculus (Cartesian)

def _coupled_lift_terms(operand, per_axis_terms, dist):
    """
    Combine per-axis derivative terms to a common output basis: each term's
    coupled-axis bases are lifted (via conversion factors) to the maximum
    derivative level across terms. Returns (terms, output_bases).
    """
    dim = operand.domain.dim
    bases_in = operand.domain.bases
    # Determine output bases: max derivative level per coupled axis.
    out_bases = list(bases_in)
    for _, descrs, d_levels in per_axis_terms:
        for axis in range(dim):
            if isinstance(bases_in[axis], Jacobi):
                lvl = d_levels.get(axis, 0)
                cur = out_bases[axis]
                tgt = bases_in[axis].derivative_basis(lvl)
                if tgt.k > cur.k:
                    out_bases[axis] = tgt
    # Add conversion factors where a term is below the output level.
    terms = []
    for tensor_factor, descrs, d_levels in per_axis_terms:
        descrs = list(descrs)
        for axis in range(dim):
            if isinstance(bases_in[axis], Jacobi):
                lvl = d_levels.get(axis, 0)
                src = bases_in[axis].derivative_basis(lvl)
                dk = out_bases[axis].k - src.k
                if dk > 0:
                    C = src.conversion_matrix(dk)
                    if descrs[axis] is None:
                        descrs[axis] = ("full", C)
                    else:
                        kind, mat = descrs[axis]
                        assert kind == "full"
                        descrs[axis] = ("full", C @ mat)
        terms.append((tensor_factor, descrs))
    return terms, tuple(out_bases)


def _diff_descr(basis):
    if basis.separable:
        return ("blocks", basis.differentiation_blocks())
    return ("full", basis.differentiation_matrix())


class CartesianVectorOperator(LinearOperator):
    """Shared machinery for grad/div/lap/curl over CartesianCoordinates."""

    def _vector_terms(self):
        """Subclasses return [(tensor_factor, descrs, d_levels)] raw terms."""
        raise NotImplementedError

    def terms(self):
        terms, out_bases = _coupled_lift_terms(self.operand, self._vector_terms(),
                                               self.dist)
        return terms

    def _build_metadata_common(self, operand, cs, tensorsig):
        _, out_bases = _coupled_lift_terms(operand, self._vector_terms_for(operand, cs),
                                           operand.dist)
        self.domain = Domain(operand.dist, out_bases)
        self.tensorsig = tensorsig
        self.dtype = operand.dtype


class CartesianGradient(CartesianVectorOperator):
    """grad: prepend a vector index of partial derivatives
    (reference: core/operators.py:2310 CartesianGradient)."""

    name = "Grad"

    def __init__(self, operand, cs):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return CartesianGradient(new_args[0], self.cs)

    def _vector_terms_for(self, operand, cs):
        dim = cs.dim
        ncomp_in = int(np.prod(operand.tshape, dtype=int)) if operand.tshape else 1
        raw = []
        for i, coord in enumerate(cs.coords):
            axis = operand.dist.get_axis(coord)
            basis = operand.domain.bases[axis]
            e_col = np.zeros((dim, 1))
            e_col[i, 0] = 1.0
            tensor_factor = np.kron(e_col, np.identity(ncomp_in))
            if basis is None:
                continue  # derivative of constant axis = 0
            descrs = [None] * operand.domain.dim
            descrs[axis] = _diff_descr(basis)
            d_levels = {axis: 1} if isinstance(basis, Jacobi) else {}
            raw.append((tensor_factor, descrs, d_levels))
        return raw

    def _vector_terms(self):
        return self._vector_terms_for(self.operand, self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        self._build_metadata_common(operand, self.cs,
                                    (self.cs,) + tuple(operand.tensorsig))


class CartesianDivergence(CartesianVectorOperator):
    """div: contract the leading vector index with partial derivatives
    (reference: core/operators.py:3385 Divergence)."""

    name = "Div"

    def __init__(self, operand, index=0):
        self.index = index
        if index != 0:
            raise NotImplementedError("Divergence only supports index=0.")
        self.cs = operand.tensorsig[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return CartesianDivergence(new_args[0], self.index)

    def _vector_terms_for(self, operand, cs):
        dim = cs.dim
        rest = operand.tshape[1:]
        ncomp_rest = int(np.prod(rest, dtype=int)) if rest else 1
        raw = []
        for i, coord in enumerate(cs.coords):
            axis = operand.dist.get_axis(coord)
            basis = operand.domain.bases[axis]
            if basis is None:
                continue
            e_row = np.zeros((1, dim))
            e_row[0, i] = 1.0
            tensor_factor = np.kron(e_row, np.identity(ncomp_rest))
            descrs = [None] * operand.domain.dim
            descrs[axis] = _diff_descr(basis)
            d_levels = {axis: 1} if isinstance(basis, Jacobi) else {}
            raw.append((tensor_factor, descrs, d_levels))
        return raw

    def _vector_terms(self):
        return self._vector_terms_for(self.operand, self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        self._build_metadata_common(operand, self.cs, tuple(operand.tensorsig[1:]))


class CartesianLaplacian(CartesianVectorOperator):
    """lap = sum_i d_i^2 (reference: core/operators.py:3952 Laplacian)."""

    name = "Lap"

    def __init__(self, operand, cs=None):
        self.cs = cs or operand.dist.coordsystems[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return CartesianLaplacian(new_args[0], self.cs)

    def _vector_terms_for(self, operand, cs):
        raw = []
        for coord in cs.coords:
            axis = operand.dist.get_axis(coord)
            basis = operand.domain.bases[axis]
            if basis is None:
                continue
            descrs = [None] * operand.domain.dim
            if basis.separable:
                B = basis.differentiation_blocks()
                descrs[axis] = ("blocks", np.einsum("gij,gjk->gik", B, B))
                d_levels = {}
            else:
                D1 = basis.differentiation_matrix()
                D2 = basis.derivative_basis(1).differentiation_matrix()
                descrs[axis] = ("full", D2 @ D1)
                d_levels = {axis: 2}
            raw.append((None, descrs, d_levels))
        return raw

    def _vector_terms(self):
        return self._vector_terms_for(self.operand, self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        self._build_metadata_common(operand, self.cs, tuple(operand.tensorsig))


class CartesianCurl(CartesianVectorOperator):
    """
    curl for 3D vectors; 2D vectors get the scalar curl
    (reference: core/operators.py:3637 Curl).
    """

    name = "Curl"

    def __init__(self, operand):
        self.cs = operand.tensorsig[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return CartesianCurl(new_args[0])

    def _vector_terms_for(self, operand, cs):
        dim = cs.dim
        raw = []
        if dim == 3:
            eps = np.zeros((3, 3, 3))
            for i, j, k in [(0, 1, 2), (1, 2, 0), (2, 0, 1)]:
                eps[i, j, k] = 1.0
                eps[i, k, j] = -1.0
            for j, coord in enumerate(cs.coords):
                axis = operand.dist.get_axis(coord)
                basis = operand.domain.bases[axis]
                if basis is None:
                    continue
                tensor_factor = eps[:, j, :]  # (out_i, in_k)
                descrs = [None] * operand.domain.dim
                descrs[axis] = _diff_descr(basis)
                d_levels = {axis: 1} if isinstance(basis, Jacobi) else {}
                raw.append((tensor_factor, descrs, d_levels))
        elif dim == 2:
            # scalar curl: d_x u_y - d_y u_x
            for j, coord, sign, k in [(0, cs.coords[0], 1.0, 1), (1, cs.coords[1], -1.0, 0)]:
                axis = operand.dist.get_axis(coord)
                basis = operand.domain.bases[axis]
                if basis is None:
                    continue
                tensor_factor = np.zeros((1, 2))
                tensor_factor[0, k] = sign
                descrs = [None] * operand.domain.dim
                descrs[axis] = _diff_descr(basis)
                d_levels = {axis: 1} if isinstance(basis, Jacobi) else {}
                raw.append((tensor_factor, descrs, d_levels))
        else:
            raise ValueError("Curl requires 2D or 3D vectors.")
        return raw

    def _vector_terms(self):
        return self._vector_terms_for(self.operand, self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        cs = self.cs
        if cs.dim == 3:
            tensorsig = tuple(operand.tensorsig)
        else:
            tensorsig = tuple(operand.tensorsig[1:])
        self._build_metadata_common(operand, cs, tensorsig)


def _curvilinear_basis(operand):
    from .curvilinear import SpinBasisMixin
    for b in operand.domain.bases:
        if isinstance(b, SpinBasisMixin) or getattr(b, "regularity", False):
            return b
    return None


def _curv_integrate(operand, curv):
    if getattr(curv, "regularity", False):
        from .spherical3d import SphericalIntegrate
        return SphericalIntegrate(operand)
    from .polar import PolarIntegrate
    return PolarIntegrate(operand)


def _spin_cs(cs):
    from .coords import PolarCoordinates, S2Coordinates
    return isinstance(cs, (PolarCoordinates, S2Coordinates))


def _spherical_cs(cs):
    from .coords import SphericalCoordinates
    return isinstance(cs, SphericalCoordinates)


def _product_cs(cs):
    from .coords import DirectProduct
    return isinstance(cs, DirectProduct) and cs.curvilinear


@parseable("grad", "Gradient")
def Gradient(operand, cs=None):
    if np.isscalar(operand):
        return 0
    cs = cs or operand.dist.coordsystems[0]
    if _spherical_cs(cs):
        from .spherical3d import SphericalGradient
        return SphericalGradient(operand, cs)
    if _spin_cs(cs):
        from .polar import PolarGradient
        return PolarGradient(operand, cs)
    if _product_cs(cs):
        from .cylinder import CylinderGradient
        return CylinderGradient(operand, cs)
    return CartesianGradient(operand, cs)


@parseable("div", "Divergence")
def Divergence(operand, index=0):
    if np.isscalar(operand):
        return 0
    if _spherical_cs(operand.tensorsig[index]):
        from .spherical3d import SphericalDivergence
        return SphericalDivergence(operand, index)
    if _spin_cs(operand.tensorsig[index]):
        from .polar import PolarDivergence
        return PolarDivergence(operand, index)
    if _product_cs(operand.tensorsig[index]):
        from .cylinder import CylinderDivergence
        return CylinderDivergence(operand, index)
    return CartesianDivergence(operand, index)


@parseable("lap", "Laplacian")
def Laplacian(operand, cs=None):
    if np.isscalar(operand):
        return 0
    cs2 = cs or operand.dist.coordsystems[0]
    if _spherical_cs(cs2):
        from .spherical3d import SphericalLaplacian
        return SphericalLaplacian(operand, cs2)
    if _spin_cs(cs2):
        from .polar import PolarLaplacian
        return PolarLaplacian(operand, cs2)
    if _product_cs(cs2):
        from .cylinder import CylinderLaplacian
        return CylinderLaplacian(operand, cs2)
    return CartesianLaplacian(operand, cs)


@parseable("curl", "Curl")
def Curl(operand):
    if np.isscalar(operand):
        return 0
    if operand.tensorsig and _spherical_cs(operand.tensorsig[0]):
        from .spherical3d import SphericalCurl
        return SphericalCurl(operand)
    if operand.tensorsig and _product_cs(operand.tensorsig[0]):
        from .cylinder import CylinderCurl
        return CylinderCurl(operand)
    return CartesianCurl(operand)


# ----------------------------------------------------------------------
# Tensor-index operators

class TraceOperator(LinearOperator):
    """Contract the first two tensor indices with the coordinate delta
    (valid for Cartesian component storage;
    reference: core/operators.py:1693)."""

    name = "Trace"

    def _build_metadata(self):
        operand = self.args[0]
        if len(operand.tensorsig) < 2 or operand.tensorsig[0].dim != operand.tensorsig[1].dim:
            raise ValueError("Trace requires two leading indices of equal dimension.")
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig[2:])
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        d = operand.tensorsig[0].dim
        rest = int(np.prod(operand.tshape[2:], dtype=int)) if operand.tshape[2:] else 1
        row = np.zeros((1, d * d))
        for i in range(d):
            row[0, i * d + i] = 1.0
        tensor_factor = np.kron(row, np.identity(rest))
        return [(tensor_factor, [None] * operand.domain.dim)]


def TransposeComponents(operand, indices=(0, 1)):
    """Swap two tensor indices (reference: core/operators.py:1849).
    Spherical regularity-component bases need the per-ell intertwined
    transpose; everywhere else the coefficient components are a kron over
    indices and a plain permutation is exact."""
    if any(getattr(b, "regularity", False) for b in operand.domain.bases):
        from .spherical3d import SphericalTransposeComponents
        return SphericalTransposeComponents(operand, indices)
    return CartesianTransposeComponents(operand, indices)


class CartesianTransposeComponents(LinearOperator):
    """Swap two tensor indices (reference: core/operators.py:1849)."""

    name = "TransposeComponents"

    def __init__(self, operand, indices=(0, 1)):
        self.indices = indices
        super().__init__(operand)

    def rebuild(self, new_args):
        return CartesianTransposeComponents(new_args[0], self.indices)

    def _build_metadata(self):
        operand = self.args[0]
        i, j = self.indices
        ts = list(operand.tensorsig)
        ts[i], ts[j] = ts[j], ts[i]
        self.domain = operand.domain
        self.tensorsig = tuple(ts)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        tshape = operand.tshape
        n = int(np.prod(tshape, dtype=int))
        perm = np.arange(n).reshape(tshape)
        perm = np.swapaxes(perm, *self.indices).ravel()
        P = np.zeros((n, n))
        P[np.arange(n), perm] = 1.0
        return [(P, [None] * operand.domain.dim)]


class Skew(LinearOperator):
    """2D skew: (u, v) -> (-v, u) (reference: core/operators.py:2019)."""

    name = "Skew"

    def _build_metadata(self):
        operand = self.args[0]
        if operand.tensorsig[0].dim != 2:
            raise ValueError("Skew requires a 2D vector.")
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        rest = int(np.prod(operand.tshape[1:], dtype=int)) if operand.tshape[1:] else 1
        R = np.array([[0.0, -1.0], [1.0, 0.0]])
        return [(np.kron(R, np.identity(rest)), [None] * operand.domain.dim)]


def SkewFactory(operand):
    from .curvilinear import SpinBasisMixin
    if any(isinstance(b, SpinBasisMixin) for b in operand.domain.bases):
        from .polar import PolarSkew
        return PolarSkew(operand)
    return Skew(operand)


def Radial(operand, index=0):
    if _spherical_cs(operand.tensorsig[index]):
        from .spherical3d import SphericalComponent
        return SphericalComponent(operand, "radial", index)
    from .polar import PolarComponent
    return PolarComponent(operand, "radial", index)


def Azimuthal(operand, index=0):
    if _spherical_cs(operand.tensorsig[index]):
        from .spherical3d import SphericalComponent
        return SphericalComponent(operand, "azimuthal", index)
    from .polar import PolarComponent
    return PolarComponent(operand, "azimuthal", index)


def Trace(operand):
    """Trace factory: dispatches on the storage frame of the contracted
    indices (coordinate / spin / regularity components)."""
    if np.isscalar(operand):
        return 0
    ts = operand.tensorsig
    if len(ts) >= 2 and _spherical_cs(ts[0]):
        from .spherical3d import (SphericalTrace, SphericalSpinTrace,
                                  spherical_basis_of)
        if spherical_basis_of(operand) is not None:
            return SphericalTrace(operand)
        # S2 boundary fields store 3D spin components: constant spin metric.
        return SphericalSpinTrace(operand)
    if len(ts) >= 2 and _spin_cs(ts[0]):
        from .curvilinear import SpinBasisMixin
        from .polar import SpinTrace, S1SpinTransformMixin
        # Disk/annulus interiors AND their S1 edge bases store spin
        # components, so the trace contracts the spin metric (-,+)+(+,-),
        # not the coordinate delta.
        if any(isinstance(b, (SpinBasisMixin, S1SpinTransformMixin))
               for b in operand.domain.bases):
            return SpinTrace(operand)
    return TraceOperator(operand)


def Angular(operand, index=0):
    if _spherical_cs(operand.tensorsig[index]):
        from .spherical3d import SphericalComponent
        return SphericalComponent(operand, "angular", index)
    from .polar import PolarComponent
    return PolarComponent(operand, "azimuthal", index)


parseables["trace"] = parseables["Trace"] = Trace
parseables["transpose"] = parseables["TransposeComponents"] = TransposeComponents
parseables["skew"] = parseables["Skew"] = SkewFactory
parseables["radial"] = Radial
parseables["azimuthal"] = Azimuthal
parseables["angular"] = Angular


class SphericalEllProduct(LinearOperator):
    """
    Multiplication by a function of the spherical-harmonic degree:
    out(ell) = ell_func(ell) * in(ell), ell-diagonal on sphere/shell/ball
    bases (reference: core/operators.py:4119 SphericalEllProduct — used
    e.g. for degree-dependent hyperdiffusion).
    """

    name = "SphericalEllProduct"

    def __init__(self, operand, cs, ell_func):
        self.cs = cs
        self.ell_func = ell_func
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalEllProduct(new_args[0], self.cs, self.ell_func)

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def _sph_basis(self):
        from .sphere import SphereBasis
        for b in self.operand.domain.bases:
            if b is not None and (isinstance(b, SphereBasis)
                                  or getattr(b, "regularity", False)):
                return b
        raise ValueError("SphericalEllProduct requires a sphere/shell/"
                         "ball basis.")

    def terms(self):
        basis = self._sph_basis()
        colat = basis.first_axis + 1
        dim = self.operand.domain.dim
        vals = np.array([float(self.ell_func(ell))
                         for ell in range(basis.Ntheta)])
        descrs = [None] * dim
        descrs[colat] = ("blocks", vals.reshape(-1, 1, 1))
        return [(None, descrs)]


parseables["SphericalEllProduct"] = SphericalEllProduct


# ----------------------------------------------------------------------
# Grid-space nonlinear operators

def _jnp_ufunc(np_ufunc):
    name = np_ufunc.__name__
    jfn = getattr(jnp, name, None)
    if jfn is None:
        raise ValueError(f"No jnp equivalent for ufunc {name}")
    return jfn


@parseable("advective_cfl", "AdvectiveCFL")
class AdvectiveCFL(Future):
    """
    Advective CFL frequency of a velocity field: sum over components of
    |u_i| / (local grid spacing), with per-geometry spacings — uniform
    Fourier, sin-theta Chebyshev, r/mmax azimuth on disk/annulus,
    r/sqrt(Lmax(Lmax+1)) angular on sphere/ball/shell (reference:
    core/operators.py:4306 AdvectiveCFL + core/basis.py:6086-6215
    cfl_spacing subclasses). Produces a scalar grid field; CFL flow tools
    reduce it to a timestep.
    """

    name = "AdvectiveCFL"
    natural_layout = "g"

    def __init__(self, operand, coords=None):
        if not operand.tensorsig:
            raise ValueError("AdvectiveCFL requires a vector (velocity) field.")
        super().__init__(operand)

    def rebuild(self, new_args):
        return AdvectiveCFL(new_args[0])

    @property
    def operand(self):
        return self.args[0]

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = ()
        self.dtype = operand.dtype

    def ev_impl(self, ctx):
        from ..extras.flow_tools import advective_cfl_frequency
        ug = ev(self.operand, ctx, "g")
        return advective_cfl_frequency(self.operand, ug, xp=jnp)


class UnaryGridFunction(Future):
    """Pointwise grid-space function (reference: core/operators.py:504)."""

    name = "UnaryGridFunction"
    natural_layout = "g"

    def __init__(self, func, operand):
        self.func = func
        super().__init__(operand)

    def rebuild(self, new_args):
        return UnaryGridFunction(self.func, new_args[0])

    @property
    def operand(self):
        return self.args[0]

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def __repr__(self):
        return f"{self.func.__name__}({self.args[0]})"

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "g")
        return _jnp_ufunc(self.func)(data)

    def frechet_differential(self, variables, perturbations):
        deriv_map = {
            np.exp: lambda x: UnaryGridFunction(np.exp, x),
            np.sin: lambda x: UnaryGridFunction(np.cos, x),
            np.cos: lambda x: -1 * UnaryGridFunction(np.sin, x),
            np.sinh: lambda x: UnaryGridFunction(np.cosh, x),
            np.cosh: lambda x: UnaryGridFunction(np.sinh, x),
            np.tanh: lambda x: 1 - UnaryGridFunction(np.tanh, x)**2,
            np.log: lambda x: x**(-1),
            np.sqrt: lambda x: (1 / 2) * x**(-1 / 2),
        }
        op = self.operand
        d_op = op.frechet_differential(variables, perturbations)
        if np.isscalar(d_op) and d_op == 0:
            return 0
        if self.func not in deriv_map:
            raise NotImplementedError(f"No derivative rule for {self.func.__name__}")
        return deriv_map[self.func](op) * d_op


def _tracing_active():
    """True when called under a jax trace (jit/vmap/grad); the shared
    hardened probe in tools/jitlift (public API first, guarded private
    fallback). When the probe DEGRADED (every trace-state API failed),
    report True: an argless impure callback evaluated at trace time has
    no tracer arguments for the call-site scan to catch, so unknown must
    keep the io_callback path — the same conservative default the local
    jax._src probe had before it moved to jitlift."""
    from ..tools.jitlift import tracing_active, tracing_state_known
    if not tracing_state_known():
        return True
    return tracing_active()


class GeneralFunction(Future):
    """
    Arbitrary user callback producing grid data
    (reference: core/operators.py:429).

    pure=True: the callback must be jax-traceable (jnp operations on the
    supplied operand arrays); it is inlined into compiled programs.
    pure=False (default, reference semantics): arbitrary host code,
    re-executed on every evaluation via io_callback — works inside the
    jitted RHS/analysis programs (e.g. stochastic forcing).
    """

    name = "GeneralFunction"
    natural_layout = "g"

    def __init__(self, dist, domain, tensorsig, dtype, layout, func, args=(),
                 pure=False):
        # Bypass Future.__init__: metadata is supplied, not inferred.
        self.dist = dist
        self.domain = domain
        self.tensorsig = tuple(tensorsig)
        self.dtype = dtype
        self.func = func
        self.layout_pref = layout
        self.args = list(args)
        self.pure = bool(pure)

    def rebuild(self, new_args):
        return GeneralFunction(self.dist, self.domain, self.tensorsig,
                               self.dtype, self.layout_pref, self.func,
                               new_args, pure=self.pure)

    def ev_impl(self, ctx):
        import jax
        arg_data = [ev(a, ctx, "g") if isinstance(a, (Field, Future)) else a
                    for a in self.args]
        if self.pure:
            return self.func(*arg_data)
        # Outside a trace, call the host function directly: no callback
        # machinery needed, and backends without host send/recv support
        # (e.g. tunneled PJRT plugins) stay usable via eager evaluation.
        if not _tracing_active() and \
                not any(isinstance(a, jax.core.Tracer) for a in arg_data):
            return jnp.asarray(self.func(*[np.asarray(a) for a in arg_data]))
        shape = self.tshape + self.domain.grid_shape(self.domain.dealias)
        spec = jax.ShapeDtypeStruct(shape, np.dtype(self.dtype))
        # io_callback (not pure_callback): host side effects / RNG state are
        # legal and calls are neither elided nor deduplicated by XLA
        from jax.experimental import io_callback
        host = lambda *a: np.broadcast_to(
            np.asarray(self.func(*a), dtype=spec.dtype), shape)
        return io_callback(host, spec, *arg_data)


class GridWrapper(Future):
    """Layout-pinning pass-through (reference: core/operators.py:762 Grid/Coeff)."""

    name = "Grid"
    natural_layout = "g"

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def ev_impl(self, ctx):
        return ev(self.args[0], ctx, "g")


class CoeffWrapper(Future):
    name = "Coeff"
    natural_layout = "c"

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = operand.dtype

    def ev_impl(self, ctx):
        return ev(self.args[0], ctx, "c")


parseables["Grid"] = GridWrapper
parseables["Coeff"] = CoeffWrapper
