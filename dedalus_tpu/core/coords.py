"""
Coordinate systems (reference: dedalus/core/coords.py).

Coordinates are pure metadata: axis names and ordering, plus (for curvilinear
systems, added with those geometries) the small unitary intertwiners mapping
tensor components to spin/regularity components.
"""

import numpy as np


class CoordinateSystem:
    """Base class for coordinate systems."""

    def __eq__(self, other):
        return (type(self) is type(other)) and (self.names == other.names)

    def __hash__(self):
        return hash((type(self).__name__,) + tuple(self.names))

    def __getitem__(self, key):
        if isinstance(key, str):
            return self.coords[self.names.index(key)]
        return self.coords[key]

    @property
    def first_axis(self):
        return self.coords[0].axis

    @property
    def _cache_token(self):
        """Interning key for CachedClass arguments (tools/cache.serialize):
        name-equality PLUS the distributor-assigned axes, so equal-named
        systems at different axis positions (a standalone disk vs a
        cylinder's disk factor) never alias cached bases."""
        return (type(self).__name__, self.names,
                tuple(getattr(c, "axis", None) for c in self.coords))

    def set_distributor(self, dist):
        self.dist = dist
        for coord in self.coords:
            coord.dist = dist

    def unit_vector_fields(self, dist):
        """Constant component-space unit vector fields e_1 .. e_dim (for
        curvilinear components: constant in component representation,
        position-dependent in the embedding)."""
        fields = []
        for i, name in enumerate(self.names):
            ei = dist.VectorField(self, name=f"e{name}")
            data = np.zeros(self.dim)
            data[i] = 1.0
            ei["g"] = data.reshape((self.dim,) + (1,) * dist.dim)
            fields.append(ei)
        return tuple(fields)


class Coordinate(CoordinateSystem):
    """A single named coordinate (reference: core/coords.py:66)."""

    dim = 1

    def __init__(self, name, cs=None):
        self.name = name
        self.names = (name,)
        self.cs = cs
        self.coords = (self,)
        self.dist = None
        self.axis = None  # set by Distributor

    def __repr__(self):
        return f"Coordinate({self.name!r})"

    def __eq__(self, other):
        return isinstance(other, Coordinate) and self.name == other.name and self.cs == other.cs

    def __hash__(self):
        return hash(("Coordinate", self.name))

    @property
    def _cache_token(self):
        # mirror __eq__ (name + owning system) plus the assigned axis
        cs_token = None
        if self.cs is not None:
            cs_token = (type(self.cs).__name__, tuple(self.cs.names))
        return ("Coordinate", self.name, getattr(self, "axis", None), cs_token)

    def set_distributor(self, dist):
        self.dist = dist


class CartesianCoordinates(CoordinateSystem):
    """
    Cartesian coordinate system of any dimension
    (reference: core/coords.py:159).
    """

    def __init__(self, *names, right_handed=True):
        if len(set(names)) != len(names):
            raise ValueError("Coordinate names must be unique.")
        self.names = tuple(names)
        self.dim = len(names)
        self.right_handed = right_handed
        self.coords = tuple(Coordinate(name, cs=self) for name in names)
        self.dist = None

    def __repr__(self):
        return f"CartesianCoordinates{self.names}"


class AzimuthalCoordinate(Coordinate):
    """Periodic azimuthal coordinate of a curvilinear system
    (reference: core/coords.py AzimuthalCoordinate)."""


class CurvilinearCoordinateSystem(CoordinateSystem):
    """Base for curvilinear systems: defines spin/regularity intertwiners
    (reference: core/coords.py CurvilinearCoordinateSystem)."""

    def spin_weights(self, indices):
        """Total spin weight of a flat tensor-component index tuple."""
        raise NotImplementedError


def _nkron(U, order):
    out = np.array([[1.0]])
    for _ in range(order):
        out = np.kron(out, U)
    return out


class DirectProduct(CoordinateSystem):
    """
    Direct product of coordinate systems — the cylinder geometry's
    coordinate container, e.g. DirectProduct(Coordinate('z'),
    PolarCoordinates('phi', 'r')) (reference: core/coords.py:99
    DirectProduct).

    Tensor components over the product concatenate the sub-systems'
    components in order; the coordinate->spin intertwiner is the block
    diagonal of the sub-systems' intertwiners (identity on non-curvilinear
    blocks), so e.g. a cylinder vector stores (z, spin-, spin+) components
    in coefficient space.
    """

    def __init__(self, *coordsystems, right_handed=None):
        self.coordsystems = tuple(coordsystems)
        coords = []
        for cs in coordsystems:
            coords.extend(cs.coords)
        names = tuple(c.name for c in coords)
        if len(set(names)) != len(names):
            raise ValueError("Cannot repeat coordinate names in DirectProduct.")
        self.coords = tuple(coords)
        self.names = names
        self.dim = sum(cs.dim for cs in coordsystems)
        if right_handed is None:
            # 3D products with a curvilinear factor default left-handed
            # (z, phi, r ordering), matching the reference convention
            right_handed = not (self.dim == 3 and self.curvilinear)
        self.right_handed = right_handed
        self.dist = None

    def __repr__(self):
        return f"DirectProduct{self.names}"

    def __eq__(self, other):
        # structural: same factor systems in the same order (name-only
        # equality would alias distinct products with matching flattened
        # names and poison the lru-cached intertwiners)
        return (isinstance(other, DirectProduct)
                and self.coordsystems == other.coordsystems)

    def __hash__(self):
        return hash(("DirectProduct",)
                    + tuple((type(cs).__name__,) + tuple(cs.names)
                            for cs in self.coordsystems))

    @property
    def _cache_token(self):
        # structural (per-factor tokens) + assigned axes
        return ("DirectProduct",
                tuple(cs._cache_token for cs in self.coordsystems))

    @property
    def curvilinear(self):
        return any(isinstance(cs, CurvilinearCoordinateSystem)
                   for cs in self.coordsystems)

    @property
    def spin_ordering(self):
        """Concatenated spin labels of the product's spin components
        (zeros on non-curvilinear blocks)."""
        out = []
        for cs in self.coordsystems:
            sub = getattr(cs, "spin_ordering", None)
            out.extend(sub if sub is not None else (0,) * cs.dim)
        return tuple(out)

    def set_distributor(self, dist):
        self.dist = dist
        for cs in self.coordsystems:
            cs.set_distributor(dist)

    def sub_slice(self, sub_cs):
        """Component slice of one factor inside the product's component
        space (by coordinate-system equality)."""
        start = 0
        for cs in self.coordsystems:
            if cs == sub_cs:
                return slice(start, start + cs.dim)
            start += cs.dim
        raise ValueError(f"{sub_cs} is not a factor of {self}.")

    def curvilinear_sub(self):
        """The (single) curvilinear factor, or None."""
        subs = [cs for cs in self.coordsystems
                if isinstance(cs, CurvilinearCoordinateSystem)]
        if len(subs) > 1:
            raise NotImplementedError(
                "Products of multiple curvilinear systems.")
        return subs[0] if subs else None

    def U_forward(self, order=1):
        """Block-diagonal coordinate->spin unitary over the product
        components (kron over tensor order)."""
        import scipy.linalg
        blocks = []
        for cs in self.coordsystems:
            if hasattr(cs, "U_forward"):
                blocks.append(cs.U_forward(1))
            else:
                blocks.append(np.eye(cs.dim))
        U = scipy.linalg.block_diag(*blocks)
        return _nkron(U, order)

    def U_backward(self, order=1):
        return self.U_forward(order).T.conj()


class PolarCoordinates(CurvilinearCoordinateSystem):
    """
    Polar coordinates (azimuth, radius); spin ordering (-, +)
    (reference: core/coords.py:255 PolarCoordinates).
    """

    spin_ordering = (-1, +1)
    dim = 2
    right_handed = True

    def __init__(self, azimuth, radius):
        self.names = (azimuth, radius)
        self.azimuth = AzimuthalCoordinate(azimuth, cs=self)
        self.radius = Coordinate(radius, cs=self)
        self.coords = (self.azimuth, self.radius)
        self.dist = None

    def __repr__(self):
        return f"PolarCoordinates{self.names}"

    @classmethod
    def U_forward(cls, order=1):
        """Unitary coord->spin map: u[+-] = (u[r] +- 1j u[phi]) / sqrt(2)
        (reference: core/coords.py:282 _U_forward). Rows ordered (-, +),
        columns (azimuth, radius)."""
        Ui = {+1: np.array([+1j, 1]) / np.sqrt(2),
              -1: np.array([-1j, 1]) / np.sqrt(2)}
        U = np.array([Ui[spin] for spin in cls.spin_ordering])
        return _nkron(U, order)

    @classmethod
    def U_backward(cls, order=1):
        return cls.U_forward(order).T.conj()


class S2Coordinates(CurvilinearCoordinateSystem):
    """
    Two-sphere coordinates (azimuth, colatitude); spin ordering (-, +)
    (reference: core/coords.py:201 S2Coordinates).
    """

    spin_ordering = (-1, +1)
    dim = 2
    right_handed = True

    def __init__(self, azimuth, colatitude):
        self.names = (azimuth, colatitude)
        self.azimuth = AzimuthalCoordinate(azimuth, cs=self)
        self.colatitude = Coordinate(colatitude, cs=self)
        self.coords = (self.azimuth, self.colatitude)
        self.dist = None
        # Set when this S2 is the angular part of SphericalCoordinates:
        # sphere bases then sit inside 3D problems with the colatitude as a
        # separable (ell-group) axis.
        self.radius_coord = None

    def __repr__(self):
        return f"S2Coordinates{self.names}"

    @classmethod
    def U_forward(cls, order=1):
        """u[+-] = (u[theta] +- 1j u[phi]) / sqrt(2)
        (reference: core/coords.py:216)."""
        Ui = {+1: np.array([+1j, 1]) / np.sqrt(2),
              -1: np.array([-1j, 1]) / np.sqrt(2)}
        U = np.array([Ui[spin] for spin in cls.spin_ordering])
        return _nkron(U, order)

    @classmethod
    def U_backward(cls, order=1):
        return cls.U_forward(order).T.conj()


class SphericalCoordinates(CurvilinearCoordinateSystem):
    """
    Spherical coordinates (azimuth, colatitude, radius); spin and regularity
    ordering (-, +, 0) (reference: core/coords.py:315 SphericalCoordinates).
    """

    spin_ordering = (-1, +1, 0)
    reg_ordering = (-1, +1, 0)
    dim = 3
    right_handed = False

    def __init__(self, azimuth, colatitude, radius):
        self.names = (azimuth, colatitude, radius)
        # Share the angular coordinate objects with the embedded S2 system so
        # sphere bases built from S2coordsys see the distributor-assigned axes.
        self.S2coordsys = S2Coordinates(azimuth, colatitude)
        self.azimuth = self.S2coordsys.azimuth
        self.colatitude = self.S2coordsys.colatitude
        self.radius = Coordinate(radius, cs=self)
        self.S2coordsys.radius_coord = self.radius
        self.coords = (self.azimuth, self.colatitude, self.radius)
        self.dist = None

    def __repr__(self):
        return f"SphericalCoordinates{self.names}"

    @classmethod
    def U_forward(cls, order=1):
        """u[+-] = (u[theta] +- 1j u[phi]) / sqrt(2); u[0] = u[r]
        (reference: core/coords.py:337)."""
        Ui = {+1: np.array([+1j, 1, 0]) / np.sqrt(2),
              -1: np.array([-1j, 1, 0]) / np.sqrt(2),
              0:  np.array([0, 0, 1.0])}
        U = np.array([Ui[spin] for spin in cls.spin_ordering])
        return _nkron(U, order)

    @classmethod
    def U_backward(cls, order=1):
        return cls.U_forward(order).T.conj()

    @classmethod
    def Q_backward(cls, ell, order):
        """Regularity -> spin orthogonal map at harmonic degree ell
        (reference: core/coords.py:359 _Q_backward)."""
        from ..libraries.spin_intertwiners import regularity_to_spin
        return regularity_to_spin(ell, order, cls.reg_ordering)

    @classmethod
    def Q_forward(cls, ell, order):
        return cls.Q_backward(ell, order).T
