"""
Coordinate systems (reference: dedalus/core/coords.py).

Coordinates are pure metadata: axis names and ordering, plus (for curvilinear
systems, added with those geometries) the small unitary intertwiners mapping
tensor components to spin/regularity components.
"""

import numpy as np


class CoordinateSystem:
    """Base class for coordinate systems."""

    def __eq__(self, other):
        return (type(self) is type(other)) and (self.names == other.names)

    def __hash__(self):
        return hash((type(self).__name__,) + tuple(self.names))

    def __getitem__(self, key):
        if isinstance(key, str):
            return self.coords[self.names.index(key)]
        return self.coords[key]

    @property
    def first_axis(self):
        return self.coords[0].axis

    def set_distributor(self, dist):
        for coord in self.coords:
            coord.dist = dist


class Coordinate(CoordinateSystem):
    """A single named coordinate (reference: core/coords.py:66)."""

    dim = 1

    def __init__(self, name, cs=None):
        self.name = name
        self.names = (name,)
        self.cs = cs
        self.coords = (self,)
        self.dist = None
        self.axis = None  # set by Distributor

    def __repr__(self):
        return f"Coordinate({self.name!r})"

    def __eq__(self, other):
        return isinstance(other, Coordinate) and self.name == other.name and self.cs == other.cs

    def __hash__(self):
        return hash(("Coordinate", self.name))

    def set_distributor(self, dist):
        self.dist = dist


class CartesianCoordinates(CoordinateSystem):
    """
    Cartesian coordinate system of any dimension
    (reference: core/coords.py:159).
    """

    def __init__(self, *names, right_handed=True):
        if len(set(names)) != len(names):
            raise ValueError("Coordinate names must be unique.")
        self.names = tuple(names)
        self.dim = len(names)
        self.right_handed = right_handed
        self.coords = tuple(Coordinate(name, cs=self) for name in names)
        self.dist = None

    def __repr__(self):
        return f"CartesianCoordinates{self.names}"

    def set_distributor(self, dist):
        self.dist = dist
        for coord in self.coords:
            coord.dist = dist

    def unit_vector_fields(self, dist):
        """Constant unit vector fields e_1 .. e_dim (reference API)."""
        fields = []
        for i, name in enumerate(self.names):
            ei = dist.VectorField(self, name=f"e{name}")
            data = np.zeros(self.dim)
            data[i] = 1.0
            ei["g"] = data.reshape((self.dim,) + (1,) * dist.dim)
            fields.append(ei)
        return tuple(fields)
