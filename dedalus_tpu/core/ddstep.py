"""
Emulated-float64 IVP stepping on accelerators without native f64 speed.

The reference framework runs float64/complex128 end-to-end (SURVEY.md §7
hard part 7). On TPU, XLA's native F64 is software-emulated on the scalar
units and the MXU has no f64 path at all, so a straight f64 build loses
the batched-matmul design's entire advantage. `DDIVPRunner` wraps a built
`InitialValueSolver` and advances its state in double-double (f32 x 2)
arithmetic (libraries/doubledouble.py):

  * M/L matvecs and the residual matvec of the implicit solve run as
    Ozaki int8 slice matmuls on the MXU (exact int32 accumulation);
  * the implicit solve is the existing f32 factorization plus dd-residual
    iterative refinement sweeps (mixed-precision IR: f64-grade solutions
    for cond(A) well below 1/eps32);
  * the RHS expression tree is evaluated by a dd interpreter mirroring
    the Future.ev protocol: linear operators via their host descriptor
    matrices, Add / pointwise products elementwise, grid<->coeff
    transforms through each basis's MMT ("matrix" library) plan.

Selection: `InitialValueSolver` auto-wires a runner for float64 pencils
on a TPU backend under `[execution] EMULATED_F64 = auto`, falling back
to native XLA f64 when construction raises `DDUnsupportedError`
(non-dense pencil paths, RHS nodes outside the dd set — validated by an
abstract trace at construction). Multistep AND Runge-Kutta IMEX schemes
are covered; the dd interpreter handles linear operators (full/blocks
descriptor terms and tensor factors), Add, pointwise and dot products —
enough for Cartesian scalar/vector problems through full 2-D
Rayleigh-Benard (tests/test_ddstep.py tracks native f64 at ~1e-10).
`maybe_dd_runner(solver)` is the explicit hook with the same rules.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..libraries.doubledouble import (
    DD, dd_from_f64, dd_to_f64, dd_split_host, dd_add, dd_sub, dd_neg,
    dd_mul, dd_mul_f32, dd_matmul, dd_slices_from_f64, dd_zeros)
from ..tools.jitlift import lifted_jit, device_constant

logger = logging.getLogger(__name__)

__all__ = ["DDIVPRunner", "DDUnsupportedError", "maybe_dd_runner"]


class DDUnsupportedError(NotImplementedError):
    """Raised when an expression node has no double-double evaluation."""


def _dd_scalar(x):
    """Host float -> dd scalar constant (exact two-term f32 split)."""
    x = float(x)
    hi = np.float32(x)
    lo = np.float32(x - float(hi))
    return DD(jnp.float32(hi), jnp.float32(lo))


def _dd_vector(xs):
    """Host float sequence -> DD of f32 vectors (exact per-entry split);
    dynamic program inputs, one per-entry scalar via dd indexing."""
    return dd_from_f64(xs)


# ------------------------------------------------------------- dd kernels

class _HostConstCache:
    """Per-host-array caches keyed by object id, so repeated traces reuse
    one slice decomposition / dd split and the jitlift registry interns
    one copy. Entries are evicted when the SOURCE array is collected (a
    weakref finalizer) — holding it strongly would pin every pencil /
    transform matrix ever decomposed for the process lifetime."""

    def __init__(self):
        self.slices = {}
        self.pairs = {}

    def _register(self, store, key, M):
        import weakref
        try:
            weakref.finalize(M, store.pop, key, None)
        except TypeError:
            pass  # not weakref-able: entry lives as long as the process

    def matrix_slices(self, M):
        key = id(M)
        if key not in self.slices:
            A = M.toarray() if hasattr(M, "toarray") else np.asarray(M)
            self.slices[key] = dd_slices_from_f64(
                np.asarray(A, dtype=np.float64), axis=-1)
            self._register(self.slices, key, M)
        return self.slices[key]

    def dd_pair(self, M):
        key = id(M)
        if key not in self.pairs:
            A = np.asarray(M.toarray() if hasattr(M, "toarray") else M,
                           dtype=np.float64)
            self.pairs[key] = dd_split_host(A)
            self._register(self.pairs, key, M)
        return self.pairs[key]


_consts = _HostConstCache()


def dd_apply_matrix(M, X, axis):
    """apply_matrix_jax mirror: contract host matrix M (m, k) with DD X
    along `axis` via the cached int8 slice decomposition."""
    planes_np, inv_np = _consts.matrix_slices(M)
    planes = device_constant(planes_np)
    inv = device_constant(inv_np)
    hi = jnp.moveaxis(X.hi, axis, -1)
    lo = jnp.moveaxis(X.lo, axis, -1)
    batch = hi.shape[:-1]
    k = hi.shape[-1]
    B = DD(hi.reshape(-1, k).T, lo.reshape(-1, k).T)        # (k, n)
    C = dd_matmul(None, B, a_planes=(planes, inv))           # (m, n)
    m = C.hi.shape[0]
    out_hi = jnp.moveaxis(C.hi.T.reshape(batch + (m,)), -1, axis)
    out_lo = jnp.moveaxis(C.lo.T.reshape(batch + (m,)), -1, axis)
    return DD(out_hi, out_lo)


def dd_apply_axis_blocks(blocks, X, axis):
    """apply_axis_blocks mirror: per-group (G, so, si) blocks along an
    axis of size G*si, in dd (blocks enter as exact f32-pair constants;
    si/so are small — Fourier derivative blocks are 2x2)."""
    bh_np, bl_np = _consts.dd_pair(blocks)
    bh = device_constant(bh_np)
    bl = device_constant(bl_np)
    G, so, si = bh_np.shape
    hi = jnp.moveaxis(X.hi, axis, -1)
    lo = jnp.moveaxis(X.lo, axis, -1)
    lead = hi.shape[:-1]
    hi = hi.reshape(lead + (G, si))
    lo = lo.reshape(lead + (G, si))
    outs = []
    for i in range(so):
        tot = None
        for j in range(si):
            b = DD(bh[:, i, j], bl[:, i, j])                 # (G,)
            term = dd_mul(DD(hi[..., j], lo[..., j]), b)
            tot = term if tot is None else dd_add(tot, term)
        outs.append(tot)
    out_hi = jnp.stack([o.hi for o in outs], axis=-1)        # (..., G, so)
    out_lo = jnp.stack([o.lo for o in outs], axis=-1)
    out_hi = out_hi.reshape(lead + (G * so,))
    out_lo = out_lo.reshape(lead + (G * so,))
    return DD(jnp.moveaxis(out_hi, -1, axis),
              jnp.moveaxis(out_lo, -1, axis))


def dd_apply_term(data, tensor_factor, axis_descrs, tshape_in, tshape_out):
    """apply_term mirror for the supported descriptor kinds."""
    out = data
    tdim_in = len(tshape_in)
    for axis, descr in enumerate(axis_descrs):
        if descr is None:
            continue
        kind = descr[0]
        if kind == "full":
            out = dd_apply_matrix(descr[1], out, tdim_in + axis)
        elif kind == "blocks":
            out = dd_apply_axis_blocks(descr[1], out, tdim_in + axis)
        else:
            raise DDUnsupportedError(
                f"dd evaluation of '{kind}' operator terms (curvilinear "
                "group stacks) is not supported.")
    if tensor_factor is not None:
        # (ncomp_out, ncomp_in) host factor on the flattened tensor axes;
        # small and exact in f64 value space
        from ..libraries.doubledouble import _to64, _from64
        factor = np.asarray(tensor_factor, dtype=np.float64)
        spatial = out.hi.shape[tdim_in:]
        nin = int(np.prod(tshape_in, dtype=int)) if tshape_in else 1
        v = _to64(out).reshape((nin,) + spatial)
        w = jnp.tensordot(jnp.asarray(factor), v, axes=(1, 0))
        return _from64(w.reshape(tuple(tshape_out) + spatial))
    if tuple(tshape_in) != tuple(tshape_out):
        raise DDUnsupportedError("dd tensor shape change")
    return out


# --------------------------------------------------------- dd transforms

def dd_transform_axis(basis, data, axis, scale, forward):
    """One-axis grid<->coeff dd transform through the basis's MMT plan."""
    plan = basis.transform_plan(scale, library="matrix")
    M = plan.forward_mat if forward else plan.backward_mat
    return dd_apply_matrix(M, data, axis)


def dd_to_layout(data, domain, scales, tdim, layout):
    """Full-domain dd transform walk (single-process; mirrors
    field.transform_to_grid/_to_coeff axis ordering)."""
    if layout == "g":
        for axis in range(domain.dim - 1, -1, -1):
            basis = domain.bases[axis]
            if basis is None:
                continue
            data = dd_transform_axis(basis, data, tdim + axis,
                                     scales[axis], forward=False)
    else:
        for axis in range(domain.dim):
            basis = domain.bases[axis]
            if basis is None:
                continue
            data = dd_transform_axis(basis, data, tdim + axis,
                                     scales[axis], forward=True)
    return data


# ------------------------------------------------------- dd tree evaluator

class DDEvalContext:
    """Substitutions (Field -> DD coeff data) and the per-trace memo."""

    def __init__(self, subs):
        self.subs = subs
        self.memo = {}

    def field_data(self, field, layout):
        key = (id(field), layout)
        if key in self.memo:
            return self.memo[key]
        if field in self.subs:
            coeff = self.subs[field]
        else:
            # non-variable input (parameter/forcing): exact host split
            hi, lo = dd_split_host(np.asarray(field.require_coeff_space()))
            coeff = DD(device_constant(hi), device_constant(lo))
        if layout == "c":
            out = coeff
        else:
            out = dd_to_layout(coeff, field.domain, field.domain.dealias,
                               field.tdim, "g")
        self.memo[key] = out
        return out


def dd_ev(node, ctx, layout):
    from .field import Field
    from .future import Future
    if isinstance(node, Field):
        return ctx.field_data(node, layout)
    if not isinstance(node, Future):     # plain number
        return node
    key = (id(node), layout)
    if key in ctx.memo:
        return ctx.memo[key]
    from .arithmetic import ScalarMultiply
    if isinstance(node, ScalarMultiply):
        # layout-agnostic (mirrors ScalarMultiply.ev): scale in the
        # requested layout, no extra transform roundtrip
        out = dd_mul(dd_ev(node.operand, ctx, layout),
                     _dd_scalar(node.scalar))
        ctx.memo[key] = out
        return out
    if layout == node.natural_layout:
        out = _dd_ev_impl(node, ctx)
    elif layout == "g":
        out = dd_to_layout(dd_ev(node, ctx, "c"), node.domain,
                           node.domain.dealias, node.tdim, "g")
    else:
        out = dd_to_layout(dd_ev(node, ctx, "g"), node.domain,
                           node.domain.dealias, node.tdim, "c")
    ctx.memo[key] = out
    return out


def _dd_ev_impl(node, ctx):
    from .arithmetic import Add, MultiplyFields
    from .field import Field
    from .future import Future
    from .operators import LinearOperator

    if isinstance(node, Add):
        total = None
        for a in node.args:
            if isinstance(a, (Field, Future)):
                d = dd_ev(a, ctx, "g")
            elif np.isscalar(a):
                d = dd_from_f64(np.float64(a))
            else:
                raise DDUnsupportedError(f"dd Add operand {a!r}")
            total = d if total is None else dd_add(total, d)
        return total

    if isinstance(node, MultiplyFields):
        a, b = node.args
        da = dd_ev(a, ctx, "g")
        db = dd_ev(b, ctx, "g")
        ta, tb = a.tdim, b.tdim
        sh = da.hi.shape[:ta] + (1,) * tb + da.hi.shape[ta:]
        return dd_mul(DD(da.hi.reshape(sh), da.lo.reshape(sh)), db)

    from .arithmetic import DotProduct
    if isinstance(node, DotProduct):
        # grid-space contraction over one tensor index; the contraction
        # dim is tiny (coordinate dimension), exact in f64 value space
        from ..libraries.doubledouble import _to64, _from64
        a, b = node.args
        da = dd_ev(a, ctx, "g")
        db = dd_ev(b, ctx, "g")
        l_sub, r_sub, o_sub = DotProduct.contraction_subscripts(
            a.tdim, b.tdim)
        return _from64(jnp.einsum(f"{l_sub},{r_sub}->{o_sub}",
                                  _to64(da), _to64(db)))

    if isinstance(node, LinearOperator):
        data = dd_ev(node.operand, ctx, "c")
        total = None
        for tensor_factor, axis_descrs in node.device_terms():
            term = dd_apply_term(data, tensor_factor, axis_descrs,
                                 node.operand.tshape, node.tshape)
            total = term if total is None else dd_add(total, term)
        return total

    # scalar multiples arrive as Multiply dispatch products; anything else
    # is out of the supported dd set
    raise DDUnsupportedError(
        f"dd evaluation of {type(node).__name__} nodes; supported: linear "
        "operators (full/blocks terms), Add, pointwise products.")


# --------------------------------------------------------------- runner

class DDIVPRunner:
    """Advance an InitialValueSolver's IVP in emulated f64 (see module
    docstring). Usage:

        solver = problem.build_solver(d3.SBDF2)
        runner = DDIVPRunner(solver)        # or maybe_dd_runner(solver)
        for _ in range(n):
            runner.step(dt)
        runner.push_state()                 # write dd state back to fields

    Supports MultistepIMEX and RungeKuttaIMEX schemes (the scheme class
    is taken from the solver's timestepper). The wrapped solver is left
    untouched except by push_state().
    """

    def __init__(self, solver, refine=2):
        from .timesteppers import MultistepIMEX, RungeKuttaIMEX
        self.solver = solver
        self.refine = int(refine)
        ts = solver.timestepper
        if isinstance(ts, MultistepIMEX):
            self.kind = "multistep"
            self.steps = ts.steps
        elif isinstance(ts, RungeKuttaIMEX):
            self.kind = "rk"
            self.steps = 1
        else:
            raise DDUnsupportedError(
                "DDIVPRunner supports multistep and Runge-Kutta IMEX "
                f"schemes (got {type(ts).__name__}).")
        self.scheme = ts
        ops = solver.ops
        if getattr(ops, "kind", "dense") != "dense":
            raise DDUnsupportedError(
                "DDIVPRunner currently requires the dense pencil path "
                "(set MATRIX_SOLVER='dense' for emulated-f64 runs).")
        # host f64 pencil matrices
        self.M_host = np.asarray(solver._matrices["M"], dtype=np.float64)
        self.L_host = np.asarray(solver._matrices["L"], dtype=np.float64)
        G, S = solver.pencil_shape
        self.shape = (G, S)
        self.mask_np = np.asarray(solver.valid_row_mask, dtype=np.float32)
        self.X = self._gather_dd()
        zero = (dd_zeros((self.steps, G, S)) if self.kind == "multistep"
                else None)
        self.F_hist = zero
        self.MX_hist = zero
        self.LX_hist = zero
        self.dt_hist = []
        self.iteration = 0
        self.sim_time = 0.0
        self._lhs_key = None
        self._lhs = None
        self._build_programs()

    # ------------------------------------------------------------ state io

    def _gather_dd(self):
        from .solvers import gather_state, state_key
        layout, variables = self.solver.layout, self.solver.variables
        his, los = {}, {}
        for v in variables:
            hi, lo = dd_split_host(np.asarray(v.require_coeff_space()))
            his[state_key(v)] = jnp.asarray(hi)
            los[state_key(v)] = jnp.asarray(lo)
        # gather_state is pure data movement: exact componentwise
        return DD(gather_state(layout, variables, his),
                  gather_state(layout, variables, los))

    def push_state(self):
        """Write the dd state back into the solver's fields (f64 host)."""
        from .solvers import scatter_state, state_key
        layout, variables = self.solver.layout, self.solver.variables
        his = scatter_state(layout, variables, self.X.hi)
        los = scatter_state(layout, variables, self.X.lo)
        for v in variables:
            data = (np.asarray(his[state_key(v)], dtype=np.float64)
                    + np.asarray(los[state_key(v)], dtype=np.float64))
            v.preset_coeff(jnp.asarray(data) if v.dtype == np.float64
                           else jnp.asarray(data, dtype=v.dtype))
            v.mark_modified()

    def state_f64(self):
        return dd_to_f64(self.X)

    def sync_state(self):
        """Re-gather the dd state from the solver's fields (call after
        setting initial conditions or editing fields when stepping the
        runner directly; solver.step() does this automatically via its
        dirty tracking)."""
        self.X = self._gather_dd()

    def reset_history(self, sim_time):
        """Restart the multistep ramp from `sim_time` with the current
        state (checkpoint restart / discontinuous state edit: the stored
        histories predate the new state; RK keeps no history)."""
        if self.kind == "multistep":
            G, S = self.shape
            zero = dd_zeros((self.steps, G, S))
            self.F_hist = zero
            self.MX_hist = zero
            self.LX_hist = zero
        self.dt_hist = []
        self.iteration = 0
        self.sim_time = float(sim_time)

    def _extras_dd(self):
        """Current dd data of the RHS's non-variable field inputs,
        version-cached (host split only when a field changed)."""
        out = []
        for f in self._extra_fields:
            cached = self._extra_cache.get(id(f))
            if cached is None or cached[0] != f._version:
                cached = (f._version,
                          dd_from_f64(np.asarray(f.require_coeff_space())))
                self._extra_cache[id(f)] = cached
            out.append(cached[1])
        return out

    # ------------------------------------------------------------ programs

    def _build_programs(self):
        solver = self.solver
        problem = solver.problem
        layout = solver.layout
        variables = solver.variables
        equations = solver.equations
        masks = solver._member_masks()
        time_field = problem.time
        from .field import Field as _Field
        from .future import Future as _Future
        from .solvers import scatter_state, state_key

        # non-variable fields feeding the RHS become dynamic inputs of the
        # step program (mirrors build_rhs_evaluator's extra_fields): baking
        # them as trace-time constants would silently freeze mid-run
        # updates to forcings/parameters
        extra = set()
        for eq in equations:
            for member, cond in eq["members"]:
                expr = member.get("F")
                if isinstance(expr, (_Field, _Future)):
                    extra |= expr.atoms(_Field)
        extra -= set(variables)
        if time_field is not None:
            extra.discard(time_field)
        self._extra_fields = sorted(extra, key=lambda f: (f.name or "", id(f)))
        self._extra_cache = {}

        def eval_F_dd(X, t, extra_dd):
            arrays_hi = scatter_state(layout, variables, X.hi)
            arrays_lo = scatter_state(layout, variables, X.lo)
            subs = {v: DD(arrays_hi[state_key(v)], arrays_lo[state_key(v)])
                    for v in variables}
            subs.update(zip(self._extra_fields, extra_dd))
            if time_field is not None:
                dim = solver.dist.dim
                shape = (1,) * dim
                subs[time_field] = DD(
                    jnp.reshape(jnp.asarray(t.hi, jnp.float32), shape),
                    jnp.reshape(jnp.asarray(t.lo, jnp.float32), shape))
            ctx = DDEvalContext(subs)
            parts_hi, parts_lo = [], []
            for eq, eq_masks in zip(equations, masks):
                size = layout.slot_size(eq["domain"], eq["tensorsig"])
                total = None
                for (member, cond), mask in zip(eq["members"], eq_masks):
                    expr = member.get("F")
                    if expr is None:
                        continue
                    data = dd_ev(expr, ctx, "c")
                    part = DD(layout.gather(data.hi, eq["domain"],
                                            eq["tensorsig"]),
                              layout.gather(data.lo, eq["domain"],
                                            eq["tensorsig"]))
                    if mask is not None:
                        m = jnp.asarray(mask, jnp.float32)[:, None]
                        part = dd_mul_f32(part, m)
                    total = part if total is None else dd_add(total, part)
                if total is None:
                    z = jnp.zeros((layout.n_groups, size), jnp.float32)
                    total = DD(z, z)
                parts_hi.append(total.hi)
                parts_lo.append(total.lo)
            F = DD(jnp.concatenate(parts_hi, axis=1),
                   jnp.concatenate(parts_lo, axis=1))
            return dd_mul_f32(F, device_constant(self.mask_np))

        ops = self.solver.ops
        M_planes = _consts.matrix_slices(self.M_host)
        L_planes = _consts.matrix_slices(self.L_host)

        def mx(planes_np, X):
            planes = device_constant(planes_np[0])
            inv = device_constant(planes_np[1])
            B = DD(X.hi[..., None], X.lo[..., None])        # (G, S, 1)
            C = dd_matmul(None, B, a_planes=(planes, inv))
            return DD(C.hi[..., 0], C.lo[..., 0])

        # dd A = a0*M + b0*L built from exact dd pairs of M and L; the
        # coefficients are dd SCALARS (dynamic inputs — one compiled
        # factorization serves every dt) — rounding a0 = 1.5/dt to one
        # f32 perturbs the scheme at ~1e-7 relative per step (observed:
        # a 4e-8 trajectory error floor with non-binary dt)
        def build_A_dd(a0, b0):
            Mh, Mlo = _consts.dd_pair(self.M_host)
            Lh, Llo = _consts.dd_pair(self.L_host)
            Mdd = DD(device_constant(Mh), device_constant(Mlo))
            Ldd = DD(device_constant(Lh), device_constant(Llo))
            return dd_add(dd_mul(Mdd, a0), dd_mul(Ldd, b0))

        def factor(a0, b0):
            A = build_A_dd(a0, b0)
            from ..libraries.doubledouble import _dd_slices
            planes, inv = _dd_slices(A, axis=-1, slices=8)
            aux32 = ops.factor(A.hi)
            return {"planes": planes, "inv": inv, "aux32": aux32}

        def solve_ir(lhs, rhs):
            """f32 solve + dd-residual iterative refinement."""
            x32 = ops.solve(lhs["aux32"], rhs.hi)
            x = DD(x32, jnp.zeros_like(x32))
            for _ in range(self.refine):
                B = DD(x.hi[..., None], x.lo[..., None])
                Ax = dd_matmul(None, B, a_planes=(lhs["planes"], lhs["inv"]))
                r = dd_sub(rhs, DD(Ax.hi[..., 0], Ax.lo[..., 0]))
                dx = ops.solve(lhs["aux32"], r.hi)
                x = dd_add(x, DD(dx, jnp.zeros_like(dx)))
            return x

        def step_body(X, t, F_hist, MX_hist, LX_hist, lhs, a, b, c,
                      extra_dd):
            # histories enter with slot 0 = current step's evaluations.
            # a, b, c are DD coefficient VECTORS (dynamic inputs): one
            # compiled program serves every startup order and timestep —
            # static coefficients would recompile the whole step on any
            # dt change (review finding; native path is dynamic too)
            Fn = eval_F_dd(X, t, extra_dd)
            MXn = mx(M_planes, X)
            LXn = mx(L_planes, X)
            roll = lambda H, new: DD(
                jnp.concatenate([new.hi[None], H.hi[:-1]]),
                jnp.concatenate([new.lo[None], H.lo[:-1]]))
            F_hist = roll(F_hist, Fn)
            MX_hist = roll(MX_hist, MXn)
            LX_hist = roll(LX_hist, LXn)
            RHS = None
            s = self.steps
            for j in range(s):
                terms = [dd_mul(F_hist[j], c[j]),
                         dd_mul(MX_hist[j], dd_neg(a[j + 1])),
                         dd_mul(LX_hist[j], dd_neg(b[j + 1]))]
                for term in terms:
                    RHS = term if RHS is None else dd_add(RHS, term)
            Xn = solve_ir(lhs, RHS)
            return Xn, F_hist, MX_hist, LX_hist

        def rk_step_body(X, t, dt, lhs_list, extra_dd):
            """One IMEX Runge-Kutta step in dd (mirrors the native
            RungeKuttaIMEX.step_body; tableau entries are exact dd
            constants closed over — they never change). lhs_list holds
            one factored LHS per stage (shared auxes alias upstream)."""
            scheme = self.scheme
            s = scheme.stages
            A = np.asarray(scheme.A, dtype=np.float64)
            H = np.asarray(scheme.H, dtype=np.float64)
            cvec = np.asarray(scheme.c, dtype=np.float64)
            MX0 = mx(M_planes, X)
            Fs, LXs = [], []
            Xi = X
            for i in range(1, s + 1):
                ti = dd_add(t, dd_mul(dt, _dd_scalar(cvec[i - 1])))
                LXs.append(mx(L_planes, Xi))
                Fs.append(eval_F_dd(Xi, ti, extra_dd))
                RHS = MX0
                for j in range(i):
                    if A[i, j] != 0.0:
                        RHS = dd_add(RHS, dd_mul(
                            dd_mul(dt, _dd_scalar(A[i, j])), Fs[j]))
                    if H[i, j] != 0.0:
                        RHS = dd_sub(RHS, dd_mul(
                            dd_mul(dt, _dd_scalar(H[i, j])), LXs[j]))
                Xi = solve_ir(lhs_list[i - 1], RHS)
            return Xi

        def rk_factor(dts):
            """One factored LHS per UNIQUE implicit diagonal (dts: dd
            scalars dt*H[i,i] per unique diagonal)."""
            one = _dd_scalar(1.0)
            return [factor(one, dth) for dth in dts]

        def step_n_body(X, t, F_hist, MX_hist, LX_hist, lhs, a, b, c,
                        extra_dd, dt_dd, n):
            """n constant-dt multistep steps in ONE lax.scan dispatch
            (post-ramp: coefficients are scan-invariant)."""
            def body(carry, _):
                Xc, tc, F, MX, LX = carry
                Xn, F2, MX2, LX2 = step_body(Xc, tc, F, MX, LX, lhs,
                                             a, b, c, extra_dd)
                return (Xn, dd_add(tc, dt_dd), F2, MX2, LX2), None
            carry, _ = jax.lax.scan(
                body, (X, t, F_hist, MX_hist, LX_hist), None, length=n)
            return carry

        def rk_step_n_body(X, t, dt, lhs_list, extra_dd, n):
            def body(carry, _):
                Xc, tc = carry
                Xn = rk_step_body(Xc, tc, dt, lhs_list, extra_dd)
                return (Xn, dd_add(tc, dt)), None
            carry, _ = jax.lax.scan(body, (X, t), None, length=n)
            return carry

        self._factor = lifted_jit(factor)
        self._step = lifted_jit(step_body)
        self._step_n = lifted_jit(step_n_body, static_argnums=(11,))
        self._rk_factor = lifted_jit(rk_factor)
        self._rk_step = lifted_jit(rk_step_body)
        self._rk_step_n = lifted_jit(rk_step_n_body, static_argnums=(5,))
        # validate the RHS tree's dd support NOW (abstract trace): an
        # unsupported node must surface at construction, where the
        # solver's auto-wiring can fall back to native f64 — not at the
        # first step's trace
        jax.eval_shape(eval_F_dd, self.X,
                       DD(jnp.float32(0.0), jnp.float32(0.0)),
                       self._extras_dd())

    # -------------------------------------------------------------- stepping

    def _lhs_for(self, a0, b0):
        """Factored LHS for a0*M + b0*L, cached on the rounded-coefficient
        key (native pattern, timesteppers.py: float noise in recomputed
        coefficients must not trigger spurious refactors)."""
        key = (round(float(a0), 14), round(float(b0), 14))
        if key != self._lhs_key:
            self._lhs = self._factor(_dd_scalar(a0), _dd_scalar(b0))
            self._lhs_key = key
        return self._lhs

    def _t_dd(self):
        """Current sim_time as an exact dd scalar."""
        return DD(jnp.float32(self.sim_time),
                  jnp.float32(self.sim_time
                              - float(np.float32(self.sim_time))))

    def step(self, dt):
        dt = float(dt)
        if not np.isfinite(dt):
            raise ValueError("Invalid timestep.")
        if self.kind == "rk":
            return self._rk_advance(dt)
        self.dt_hist = ([dt] + self.dt_hist)[: self.steps]
        order = min(self.iteration + 1, self.steps)
        a, b, c = self.scheme.compute_coefficients(self.dt_hist, order)
        # startup ramp returns order-length arrays; pad to the full
        # stencil so the (static) history loop bounds stay fixed
        s = self.steps
        a = np.concatenate([np.asarray(a, float), np.zeros(s + 1 - len(a))])
        b = np.concatenate([np.asarray(b, float), np.zeros(s + 1 - len(b))])
        c = np.concatenate([np.asarray(c, float), np.zeros(s - len(c))])
        lhs = self._lhs_for(a[0], b[0])
        self.X, self.F_hist, self.MX_hist, self.LX_hist = self._step(
            self.X, self._t_dd(), self.F_hist, self.MX_hist, self.LX_hist,
            lhs, _dd_vector(a), _dd_vector(b), _dd_vector(c),
            self._extras_dd())
        self.sim_time += dt
        self.iteration += 1

    def step_many(self, n, dt):
        """Advance n constant-dt steps with ONE device dispatch per block
        (lax.scan; small problems are host-latency bound at one dispatch
        per step). Multistep startup-ramp steps run individually first."""
        n = int(n)
        dt = float(dt)
        if not np.isfinite(dt):
            raise ValueError("Invalid timestep.")
        if n <= 0:
            return
        if self.kind == "rk":
            lhs_list, t_dd = self._rk_prepare(dt)
            self.X, _ = self._rk_step_n(
                self.X, t_dd, _dd_scalar(dt), lhs_list,
                self._extras_dd(), n)
            self.sim_time += n * dt
            self.iteration += n
            return
        # ramp to steady order, then scan
        while n > 0 and (self.iteration < self.steps
                         or self.dt_hist != [dt] * self.steps):
            self.step(dt)
            n -= 1
        if n <= 0:
            return
        a, b, c = self.scheme.compute_coefficients([dt] * self.steps,
                                                   self.steps)
        lhs = self._lhs_for(a[0], b[0])
        carry = self._step_n(
            self.X, self._t_dd(), self.F_hist, self.MX_hist, self.LX_hist,
            lhs, _dd_vector(np.asarray(a, float)),
            _dd_vector(np.asarray(b, float)),
            _dd_vector(np.asarray(c, float)), self._extras_dd(),
            _dd_scalar(dt), n)
        self.X, _, self.F_hist, self.MX_hist, self.LX_hist = carry
        self.sim_time += n * dt
        self.iteration += n

    def _rk_prepare(self, dt):
        scheme = self.scheme
        H_diag = [float(scheme.H[i, i]) for i in range(1, scheme.stages + 1)]
        uniq = sorted(set(H_diag))
        key = ("rk", round(dt, 14))
        if key != self._lhs_key:
            self._lhs = self._rk_factor([_dd_scalar(dt * h) for h in uniq])
            self._lhs_key = key
        lhs_list = [self._lhs[uniq.index(h)] for h in H_diag]
        return lhs_list, self._t_dd()

    def _rk_advance(self, dt):
        lhs_list, t_dd = self._rk_prepare(dt)
        self.X = self._rk_step(self.X, t_dd, _dd_scalar(dt), lhs_list,
                               self._extras_dd())
        self.sim_time += dt
        self.iteration += 1


def maybe_dd_runner(solver):
    """The dtype=np.float64-on-accelerator selection hook: the solver's
    auto-wired runner (InitialValueSolver constructs one when the backend
    is a TPU and [execution] EMULATED_F64 = auto), or a fresh DDIVPRunner
    under the same conditions, else None (including EMULATED_F64 = never
    and problems outside the dd-supported set)."""
    from ..tools.config import config
    existing = getattr(solver, "_dd", None)
    if existing is not None:
        return existing
    if config["execution"].get("EMULATED_F64", "auto").lower() == "never":
        return None
    if (np.dtype(solver.pencil_dtype) == np.dtype(np.float64)
            and jax.default_backend() in ("tpu", "axon")):
        try:
            return DDIVPRunner(solver)
        except DDUnsupportedError:
            return None
    return None
