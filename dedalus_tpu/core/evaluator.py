"""
Evaluator and output handlers (reference: dedalus/core/evaluator.py).

Handlers own lists of tasks (symbolic expressions) evaluated on wall-time /
sim-time / iteration cadences (reference: core/evaluator.py:248-278
check_schedule). The reference's layout-oscillation machinery
(evaluate_handlers :94-148) is unnecessary here: expression trees evaluate
as jnp programs with shared-transform memoization.

FileHandler writes HDF5 with the reference's file schema (tasks/<name>,
scales/sim_time|iteration|write_number|timestep) so checkpoint restart and
post-processing tooling are format-compatible.
"""

import os
import pathlib
import logging
import numpy as np

from .field import Field
from .future import Future

logger = logging.getLogger(__name__)


class Evaluator:
    """Coordinates scheduled evaluation of handler tasks
    (reference: core/evaluator.py:30 Evaluator)."""

    def __init__(self, solver):
        self.solver = solver
        self.handlers = []

    def add_dictionary_handler(self, **kw):
        handler = DictionaryHandler(self.solver, **kw)
        self.handlers.append(handler)
        return handler

    def add_file_handler(self, base_path, **kw):
        handler = FileHandler(self.solver, base_path, **kw)
        self.handlers.append(handler)
        return handler

    def evaluate_scheduled(self, iteration=0, wall_time=0.0, sim_time=0.0,
                           timestep=None, **kw):
        due = [h for h in self.handlers
               if h.check_schedule(iteration=iteration, wall_time=wall_time,
                                   sim_time=sim_time)]
        self.evaluate_handlers(due, iteration=iteration, wall_time=wall_time,
                               sim_time=sim_time, timestep=timestep)

    def evaluate_handlers(self, handlers=None, iteration=0, wall_time=0.0,
                          sim_time=0.0, timestep=None, **kw):
        if handlers is None:
            handlers = self.handlers
        for handler in handlers:
            handler.process(iteration=iteration, wall_time=wall_time,
                            sim_time=sim_time, timestep=timestep)


class Handler:
    """Task list with a schedule (reference: core/evaluator.py:209 Handler)."""

    def __init__(self, solver, group=None, wall_dt=None, sim_dt=None,
                 iter=None, custom_schedule=None):
        self.solver = solver
        self.tasks = []
        self.group = group
        self.wall_dt = wall_dt
        self.sim_dt = sim_dt
        self.iter = iter
        self.custom_schedule = custom_schedule
        self.last_wall_div = -1
        self.last_sim_div = -1
        self.last_iter_div = -1
        # optional transient-IO retry policy (tools/resilience.RetryPolicy
        # or any callable-with-.call) applied around file writes; None
        # writes directly (zero overhead beyond one attribute check)
        self.io_retry = None

    def schedule_state(self):
        """Scheduling counters as a restorable dict — captured into
        resilience snapshots (tools/resilience.py) so a rewound run
        re-arms its output cadences consistently with the rewound clock
        instead of skipping the replayed interval's writes."""
        return {"last_wall_div": self.last_wall_div,
                "last_sim_div": self.last_sim_div,
                "last_iter_div": self.last_iter_div}

    def restore_schedule_state(self, state):
        self.last_wall_div = state["last_wall_div"]
        self.last_sim_div = state["last_sim_div"]
        self.last_iter_div = state["last_iter_div"]

    def add_task(self, task, layout="g", name=None, scales=None):
        """Add a task (operand expression, field, or namespace string)."""
        if isinstance(task, str):
            namespace = self.solver.problem.namespace
            name = name or task
            task = eval(task, {}, namespace)
        if name is None:
            name = getattr(task, "name", None) or str(task)
        self.tasks.append({"operator": task, "layout": layout, "name": name,
                           "scales": scales})

    def add_tasks(self, tasks, **kw):
        for task in tasks:
            self.add_task(task, **kw)

    def add_system(self, system, **kw):
        self.add_tasks(system, **kw)

    def check_schedule(self, iteration=0, wall_time=0.0, sim_time=0.0):
        """Divisor-crossing cadence logic (reference: core/evaluator.py:248)."""
        scheduled = False
        if self.wall_dt is not None:
            div = int(wall_time // self.wall_dt)
            if div > self.last_wall_div:
                scheduled = True
                self.last_wall_div = div
        if self.sim_dt is not None:
            div = int((sim_time + 1e-12) // self.sim_dt)
            if div > self.last_sim_div:
                scheduled = True
                self.last_sim_div = div
        if self.iter is not None:
            div = iteration // self.iter
            if div > self.last_iter_div:
                scheduled = True
                self.last_iter_div = div
        if self.custom_schedule is not None:
            scheduled = scheduled or self.custom_schedule(
                iteration=iteration, wall_time=wall_time, sim_time=sim_time)
        return scheduled

    def _compile_tasks(self):
        """
        One compiled program evaluating every task of this handler under a
        shared memo, with all Field atoms as inputs: shared subexpressions
        and transforms are computed once per pass instead of once per task
        (reference batches tasks through grouped layout walks,
        core/evaluator.py:94-148).
        """
        from .future import EvalContext, CompiledWithFallback
        from .field import transform_to_grid, mesh_transforms
        dist = self.solver.dist
        tasks = list(self.tasks)
        atoms = set()
        for task in tasks:
            atoms |= task["operator"].atoms(Field)
        fields = sorted(atoms, key=lambda f: (f.name or "", id(f)))

        def fn(arrays):
            from ..tools.metrics import trace_scope
            with mesh_transforms(dist.mesh,
                                 chunks=getattr(self.solver,
                                                "_transpose_chunks", None)), \
                    trace_scope("evaluator", "tasks"):
                return fn_body(arrays)

        def fn_body(arrays):
            ctx = EvalContext(dict(zip(fields, arrays)))
            out = {}
            for task in tasks:
                op = task["operator"]
                if isinstance(op, Field):
                    data_c = ctx.field_data(op, "c")
                else:
                    data_c = op.ev(ctx, "c")
                if task["layout"] == "g":
                    scales = dist.remedy_scales(task["scales"] or 1)
                    tdim = len(op.tensorsig)
                    data = transform_to_grid(data_c, op.domain, scales, tdim,
                                             tensorsig=op.tensorsig)
                else:
                    data = data_c
                out[task["name"]] = data
            return out

        def eager():
            out = {}
            for task in tasks:
                op = task["operator"]
                field = op if isinstance(op, Field) else op.evaluate()
                if task["layout"] == "g":
                    field.change_scales(task["scales"] or 1)
                    out[task["name"]] = field["g"]
                else:
                    out[task["name"]] = field["c"]
            return out

        return CompiledWithFallback(fields, fn, eager,
                                    f"handler tasks {[t['name'] for t in tasks]}")

    def evaluate_tasks(self):
        """Evaluate all tasks, returning {name: numpy array}."""
        cache = getattr(self, "_task_cache", None)
        key = tuple((id(t["operator"]), t["layout"], t["scales"])
                    for t in self.tasks)
        if cache is None or cache["key"] != key:
            cache = self._task_cache = {"key": key,
                                        "runner": self._compile_tasks()}
        arrays = cache["runner"]()
        import jax
        if jax.process_count() > 1:
            # multi-process world: device arrays spanning processes are
            # gathered collectively to a full copy on every process
            # (reference: per-process files + merge or gather modes,
            # dedalus/core/evaluator.py:656-846 — here the gather mode);
            # host arrays / single-process arrays are already global
            from ..parallel import multihost

            def to_global(v):
                if isinstance(v, jax.Array) and not v.is_fully_addressable:
                    return multihost.process_allgather(v)
                return np.asarray(v)

            return {name: to_global(v) for name, v in arrays.items()}
        return {name: np.asarray(v) for name, v in arrays.items()}

    def process(self, **kw):
        raise NotImplementedError


class DictionaryHandler(Handler):
    """Stores task results in a dict (reference: core/evaluator.py:325)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.fields = {}

    def __getitem__(self, name):
        return self.fields[name]

    def process(self, **kw):
        self.fields.update(self.evaluate_tasks())


class FileHandler(Handler):
    """HDF5 output handler (reference: core/evaluator.py:369 H5FileHandler)."""

    def __init__(self, solver, base_path, max_writes=np.inf, mode=None, **kw):
        super().__init__(solver, **kw)
        from ..tools.config import config
        self.base_path = pathlib.Path(base_path)
        self.max_writes = max_writes
        self.mode = mode or config["analysis"].get("FILEHANDLER_MODE_DEFAULT",
                                                   "overwrite")
        self.set_num = 0
        self.write_num = 0
        self.current_file = None
        self.writes_in_set = 0
        from ..parallel import multihost
        self._primary = multihost.is_primary()
        if self._primary:
            os.makedirs(self.base_path, exist_ok=True)
        if self.mode == "append":
            # continue set and write numbering from existing output;
            # only the primary scans the (shared) filesystem, then the
            # bookkeeping is broadcast so every process numbers writes
            # identically (reference: core/evaluator.py:415-438)
            resume = 0
            if self._primary:
                self._scan_existing_sets()
                resume = int(self.current_file is not None)
            state = multihost.broadcast_from_primary(
                np.array([self.set_num, self.write_num,
                          self.writes_in_set, resume], dtype=np.int64))
            self.set_num, self.write_num, self.writes_in_set, resume = (
                int(v) for v in state)
            if resume and self.current_file is None:
                self.current_file = str(
                    self.base_path
                    / f"{self.base_path.name}_s{self.set_num}.h5")

    def _scan_existing_sets(self):
        from ..tools.post import get_assigned_sets
        existing = get_assigned_sets(self.base_path)
        if existing:
            import h5py
            self.set_num = int(existing[-1].stem.rsplit("_s", 1)[1])
            # scan back past empty/partial sets (e.g. from a crashed
            # run) so write_number stays globally unique
            for path in reversed(existing):
                with h5py.File(path, "r") as f:
                    if "scales/write_number" in f and len(f["scales/write_number"]):
                        self.write_num = int(np.asarray(f["scales/write_number"])[-1])
                        break
            # resume the last set if it still has room, instead of
            # opening a fresh under-filled set on every restart
            with h5py.File(existing[-1], "r") as f:
                writes = (len(f["scales/write_number"])
                          if "scales/write_number" in f else 0)
            if writes < self.max_writes:
                self.current_file = str(existing[-1])
                self.writes_in_set = writes

    def _new_file(self):
        import h5py
        self.set_num += 1
        self.writes_in_set = 0
        name = f"{self.base_path.name}_s{self.set_num}.h5"
        path = self.base_path / name
        self.current_file = str(path)
        if self._primary:
            with h5py.File(path, "w") as f:
                f.create_group("tasks")
                f.create_group("scales")
        return path

    def process(self, iteration=0, wall_time=0.0, sim_time=0.0, timestep=None, **kw):
        if self.current_file is None or self.writes_in_set >= self.max_writes:
            self._new_file()
        self.write_num += 1
        self.writes_in_set += 1
        # collective: every process participates in evaluation/gather;
        # only the primary touches the file below
        results = self.evaluate_tasks()
        if not self._primary:
            return
        write = lambda: self._write_results(results, iteration=iteration,
                                            wall_time=wall_time,
                                            sim_time=sim_time,
                                            timestep=timestep)
        if self.io_retry is not None:
            # transient host/IO faults (flaky disk/NFS) retried with
            # backoff before they can kill the run (tools/resilience.py)
            self.io_retry.call(write, label=f"write {self.current_file}")
        else:
            write()

    def _write_results(self, results, iteration, wall_time, sim_time,
                       timestep):
        import h5py
        with h5py.File(self.current_file, "a") as f:
            scales = f["scales"]
            for key, val in [("sim_time", sim_time), ("wall_time", wall_time),
                             ("iteration", iteration),
                             ("write_number", self.write_num),
                             ("timestep", timestep if timestep is not None else np.nan)]:
                if key not in scales:
                    scales.create_dataset(key, shape=(0,), maxshape=(None,), dtype=np.float64)
                ds = scales[key]
                ds.resize((ds.shape[0] + 1,))
                ds[-1] = val
            tasks = f["tasks"]
            for name, data in results.items():
                if name not in tasks:
                    tasks.create_dataset(name, shape=(0,) + data.shape,
                                         maxshape=(None,) + data.shape,
                                         dtype=data.dtype)
                    task = next((t for t in self.tasks
                                 if t["name"] == name), None)
                    # recorded so load_state can restore through the
                    # layout the data was written in ('c' checkpoints
                    # round-trip bitwise — no transform in the path)
                    tasks[name].attrs["layout"] = \
                        task["layout"] if task else "g"
                    self._attach_grid_scales(f, tasks[name], name)
                ds = tasks[name]
                ds.resize((ds.shape[0] + 1,) + data.shape)
                ds[-1] = data

    def _attach_grid_scales(self, f, ds, name):
        """Store the task's grid arrays once and attach them as HDF5
        dimension scales (reference: core/evaluator.py:656-728 setup_file
        attaches per-axis scales), so post-processing (plot_snapshots,
        xarray) can recover coordinates from the file alone."""
        task = next((t for t in self.tasks if t["name"] == name), None)
        if task is None or task["layout"] != "g":
            return
        op = task["operator"]
        scales = self.solver.dist.remedy_scales(task["scales"] or 1)
        tdim = len(op.tensorsig)
        grp = f["scales"]
        dim = 0
        ds.dims[dim].label = "write"
        dim += 1
        for _ in range(tdim):
            ds.dims[dim].label = "component"
            dim += 1
        grids = []
        for axis, basis in enumerate(op.domain.bases):
            if basis is None:
                grids.append((f"const_{axis}", np.zeros(1)))
            elif basis.dim == 1:
                coord = basis.coord
                grids.append((coord.name, basis.global_grid(scales[axis])))
            else:
                sub = axis - basis.first_axis
                if sub == 0:
                    gs = basis.global_grids(
                        tuple(scales[basis.first_axis + i]
                              for i in range(basis.dim)))
                    for i, g in enumerate(gs):
                        grids.append((basis.cs.names[i], np.ravel(g)))
        import hashlib
        for gname, grid in grids:
            flat = np.ravel(grid)
            key = f"{gname}_{hashlib.sha1(flat.tobytes()).hexdigest()[:12]}"
            if key not in grp:
                grp.create_dataset(key, data=flat)
                grp[key].make_scale(gname)
            ds.dims[dim].attach_scale(grp[key])
            ds.dims[dim].label = gname
            dim += 1
