"""
Problem classes: IVP, LBVP, NLBVP, EVP (reference: dedalus/core/problems.py).

Equations enter as strings (parsed with Python eval over a namespace of
variables + operator parseables + the user's namespace; reference:
core/problems.py:74-76) or as (LHS, RHS) operand tuples. Each equation is
validated and split into matrix expressions:

  IVP:   M.dt(X) + L.X = F(X,t)     (reference: core/problems.py:319-362)
  LBVP:  L.X = F                    (:156)
  EVP:   lam*M.X + L.X = 0          (:466)
  NLBVP: G(X) = H(X), Newton via Frechet differentials (:242)
"""

import numpy as np

from .field import Field, Operand
from .future import Future
from .operators import (parseables, TimeDerivative, ConvertNode, dt as dt_op)
from .arithmetic import (Add, Multiply, ScalarMultiply, MultiplyFields,
                         _union_domain, _is_scalar)
from .domain import Domain
from ..tools.parsing import split_equation
from ..tools.exceptions import UnsupportedEquationError, SymbolicParsingError


_public_parseables_cache = None


def _public_parseables():
    """
    Public operator/arithmetic names usable in equation strings, matching the
    reference's parseables built from operators.__all__ + arithmetic.__all__
    (reference: core/problems.py:28-33). Lazily imported (sphere/arithmetic
    would be circular at module load).
    """
    global _public_parseables_cache
    if _public_parseables_cache is None:
        from . import operators as ops
        from .arithmetic import DotProduct, CrossProduct
        from .sphere import MulCosine
        _public_parseables_cache = {
            "Lift": ops.LiftFactory, "LiftTau": ops.LiftTau,
            "Gradient": ops.Gradient, "Divergence": ops.Divergence,
            "Curl": ops.Curl, "Laplacian": ops.Laplacian,
            "Differentiate": ops.Differentiate,
            "UnaryGridFunction": ops.UnaryGridFunction,
            "GeneralFunction": ops.GeneralFunction,
            "RadialComponent": ops.Radial, "AngularComponent": ops.Angular,
            "AzimuthalComponent": ops.Azimuthal,
            "DotProduct": DotProduct, "dot": DotProduct,
            "CrossProduct": CrossProduct, "cross": CrossProduct,
            "MulCosine": MulCosine,
        }
    return _public_parseables_cache


def _flatten_terms(expr):
    """Flatten an expression into additive terms."""
    if isinstance(expr, Add):
        out = []
        for a in expr.args:
            out.extend(_flatten_terms(a))
        return out
    return [expr]


def _contains_marker(expr, marker):
    if expr is marker:
        return True
    if isinstance(marker, type) and isinstance(expr, marker):
        return True
    if isinstance(expr, Future):
        return any(_contains_marker(a, marker) for a in expr.args
                   if isinstance(a, (Field, Future)))
    return False


def _strip_dt(expr):
    """Replace dt(X) -> X; the result must contain no further dt."""
    if isinstance(expr, TimeDerivative):
        operand = expr.operand
        if _contains_marker(operand, TimeDerivative):
            raise UnsupportedEquationError("Nested time derivatives are not supported.")
        return operand
    if isinstance(expr, Future):
        new_args = [(_strip_dt(a) if isinstance(a, (Field, Future)) else a)
                    for a in expr.args]
        return expr.rebuild(new_args)
    return expr


def _distribute_marker(expr, marker):
    """
    Distribute products over Add factors containing `marker`, so that each
    top-level additive term carries at most one linear marker occurrence
    (lets equations like "(a - 2*q*cos_2x)*y = 0" split into eigenvalue
    and non-eigenvalue terms; reference expands LHS expressions before
    matrix extraction, core/problems.py:431).
    """
    if not isinstance(expr, (Field, Future)) or expr is marker:
        return expr
    if not _contains_marker(expr, marker):
        return expr
    if isinstance(expr, Add):
        return Add(*[_distribute_marker(a, marker) for a in expr.args])
    if isinstance(expr, ScalarMultiply):
        inner = _distribute_marker(expr.operand, marker)
        if isinstance(inner, Add):
            return Add(*[ScalarMultiply(expr.scalar, t) for t in inner.args])
        return ScalarMultiply(expr.scalar, inner)
    if isinstance(expr, MultiplyFields):
        a, b = expr.args
        a = _distribute_marker(a, marker)
        b = _distribute_marker(b, marker)
        if isinstance(a, Add) and _contains_marker(a, marker):
            return Add(*[_distribute_marker(MultiplyFields(t, b), marker)
                         for t in a.args])
        if isinstance(b, Add) and _contains_marker(b, marker):
            return Add(*[_distribute_marker(MultiplyFields(a, t), marker)
                         for t in b.args])
        # hoist scalar prefactors off the marker side so the linear-factor
        # strip sees MultiplyFields(marker, X) directly (e.g. the
        # dt = -1j*omega*A idiom builds ((-1j)*omega)*A)
        if isinstance(a, ScalarMultiply) and _contains_marker(a, marker):
            return ScalarMultiply(a.scalar, _distribute_marker(
                MultiplyFields(a.operand, b), marker))
        if isinstance(b, ScalarMultiply) and _contains_marker(b, marker):
            return ScalarMultiply(b.scalar, _distribute_marker(
                MultiplyFields(a, b.operand), marker))
        return MultiplyFields(a, b)
    if isinstance(expr, Future):
        new_args = [_distribute_marker(arg, marker) for arg in expr.args]
        return expr.rebuild(new_args)
    return expr


def _strip_linear_factor(expr, marker):
    """Remove one linear occurrence of `marker` (a constant Field) from expr."""
    if expr is marker:
        raise UnsupportedEquationError(
            "Eigenvalue must multiply variables, not appear alone.")
    if isinstance(expr, ScalarMultiply):
        return ScalarMultiply(expr.scalar, _strip_linear_factor(expr.operand, marker))
    if isinstance(expr, MultiplyFields):
        a, b = expr.args
        if a is marker:
            return b
        if b is marker:
            return a
        if _contains_marker(a, marker):
            return MultiplyFields(_strip_linear_factor(a, marker), b)
        return MultiplyFields(a, _strip_linear_factor(b, marker))
    if isinstance(expr, Future):
        new_args = []
        for arg in expr.args:
            if isinstance(arg, (Field, Future)) and _contains_marker(arg, marker):
                new_args.append(_strip_linear_factor(arg, marker))
            else:
                new_args.append(arg)
        return expr.rebuild(new_args)
    raise UnsupportedEquationError(f"Cannot strip eigenvalue from {expr!r}")


class ProblemBase:
    """Base problem (reference: core/problems.py:27 ProblemBase)."""

    def __init__(self, variables, namespace=None, time="t"):
        if not variables:
            raise ValueError("Problems require at least one variable.")
        self.variables = list(variables)
        self.dist = variables[0].dist
        self.equations = []
        self.time_name = time
        self._user_namespace = dict(namespace or {})
        self.LHS_variables = self.variables

    @property
    def namespace(self):
        ns = {}
        ns.update(parseables)
        ns.update(_public_parseables())
        ns["np"] = np
        for var in self.variables:
            if var.name:
                ns[var.name] = var
        for coord in self.dist.coords:
            ns.setdefault(coord.name, coord)
        ns.update(self._user_namespace)
        return ns

    def add_equation(self, equation, condition=None):
        """
        Add an equation as a string or (LHS, RHS) tuple
        (reference: core/problems.py:67 add_equation).

        `condition` is a per-group guard evaluated over separable group
        indices named 'n' + coordinate name (e.g. "nx != 0"): the equation
        only enters pencil groups satisfying it. Conditioned equations with
        matching (bases, tensor signature) share one row block, exactly one
        active per group (reference: core/subsystems.py:527-541).
        """
        if isinstance(equation, str):
            lhs_str, rhs_str = split_equation(equation)
            ns = self.namespace
            try:
                lhs = eval(lhs_str, {}, ns)
                rhs = eval(rhs_str, {}, ns)
            except Exception as exc:
                raise SymbolicParsingError(
                    f"Failed to parse equation {equation!r}: {exc}") from exc
        else:
            lhs, rhs = equation
        if not isinstance(lhs, (Field, Future)):
            raise UnsupportedEquationError("Equation LHS must involve variables.")
        eq = self._build_matrix_expressions(lhs, rhs)
        eq["LHS_str"] = str(lhs)
        eq["condition"] = condition
        self.equations.append(eq)
        return eq

    # -- helpers shared by problem types --

    def _eq_domain(self, exprs):
        operands = [e for e in exprs if isinstance(e, (Field, Future))]
        domain = _union_domain(self.dist, operands)
        tensorsigs = {tuple(op.tensorsig) for op in operands}
        if len(tensorsigs) != 1:
            raise UnsupportedEquationError("LHS terms have mismatched tensor signatures.")
        return domain, next(iter(tensorsigs))

    def _wrap(self, expr, domain):
        if expr is None:
            return None
        if tuple(expr.domain.bases) == domain.bases:
            return expr
        return ConvertNode(expr, domain.bases)

    def _wrap_rhs(self, rhs, domain, tensorsig):
        if rhs is None or (_is_scalar(rhs) and rhs == 0):
            return None
        if _is_scalar(rhs):
            if tensorsig:
                raise UnsupportedEquationError("Scalar RHS for a tensor equation.")
            const = self.dist.Field(name=f"const_{len(self.equations)}")
            const["g"] = rhs
            rhs = const
        if tuple(rhs.tensorsig) != tuple(tensorsig):
            raise UnsupportedEquationError("RHS tensor signature does not match LHS.")
        return self._wrap(rhs, domain)

    def build_solver(self, *args, **kw):
        raise NotImplementedError


class LBVP(ProblemBase):
    """Linear boundary value problem: L.X = F (reference: core/problems.py:128)."""

    def _build_matrix_expressions(self, lhs, rhs):
        if _contains_marker(lhs, TimeDerivative):
            raise UnsupportedEquationError("LBVPs cannot contain time derivatives.")
        domain, tensorsig = self._eq_domain([lhs])
        eq = {"domain": domain, "tensorsig": tensorsig,
              "L": self._wrap(lhs, domain),
              "F": self._wrap_rhs(rhs, domain, tensorsig)}
        return eq

    def build_solver(self, **kw):
        from .solvers import LinearBoundaryValueSolver
        return LinearBoundaryValueSolver(self, **kw)


class IVP(ProblemBase):
    """Initial value problem: M.dt(X) + L.X = F
    (reference: core/problems.py:241 IVP)."""

    def __init__(self, variables, namespace=None, time="t"):
        super().__init__(variables, namespace=namespace, time=time)
        self.time = self.dist.Field(name=time)
        self._user_namespace.setdefault(time, self.time)
        self.sim_time = 0.0

    def _build_matrix_expressions(self, lhs, rhs):
        terms = _flatten_terms(lhs)
        m_terms, l_terms = [], []
        for term in terms:
            if _is_scalar(term):
                if term != 0:
                    raise UnsupportedEquationError("Constant terms belong on the RHS.")
                continue
            if _contains_marker(term, TimeDerivative):
                m_terms.append(_strip_dt(term))
            else:
                l_terms.append(term)
        M_expr = Add(*m_terms) if len(m_terms) > 1 else (m_terms[0] if m_terms else None)
        L_expr = Add(*l_terms) if len(l_terms) > 1 else (l_terms[0] if l_terms else None)
        domain, tensorsig = self._eq_domain([e for e in (M_expr, L_expr) if e is not None])
        return {"domain": domain, "tensorsig": tensorsig,
                "M": self._wrap(M_expr, domain),
                "L": self._wrap(L_expr, domain),
                "F": self._wrap_rhs(rhs, domain, tensorsig)}

    def build_solver(self, timestepper, **kw):
        from .solvers import InitialValueSolver
        return InitialValueSolver(self, timestepper, **kw)

    def build_EVP(self, eigenvalue=None, perturbations=None, **kw):
        """
        Convert this IVP into an EVP linearized about the CURRENT variable
        values (reference: core/problems.py:364 build_EVP):
            M.dt(X) + L.X = F(X)   ->   lam*M.X1 + L.X1 - F'(X0).X1 = 0
        NCC data in the linearized operators reads the IVP variables, so
        set the background state on them before solving.
        """
        variables = self.variables
        if eigenvalue is None:
            eigenvalue = self.dist.Field(name="lam")
        if perturbations is None:
            perturbations = []
            for var in variables:
                pert = Field(var.dist, bases=var.domain.bases,
                             tensorsig=var.tensorsig,
                             name=f"d_{var.name}", dtype=var.dtype)
                perturbations.append(pert)
        evp = EVP(perturbations, eigenvalue=eigenvalue)
        for eq in self.equations:
            terms = []
            M_expr, L_expr, F_expr = eq.get("M"), eq.get("L"), eq.get("F")
            if M_expr is not None:
                sub = M_expr
                for var, pert in zip(variables, perturbations):
                    sub = sub.replace(var, pert)
                terms.append(Multiply(eigenvalue, sub))
            if L_expr is not None:
                sub = L_expr
                for var, pert in zip(variables, perturbations):
                    sub = sub.replace(var, pert)
                terms.append(sub)
            if F_expr is not None:
                if _contains_marker(F_expr, self.time):
                    raise UnsupportedEquationError(
                        "Cannot convert a time-dependent IVP to an EVP.")
                dF = F_expr.frechet_differential(variables, perturbations)
                if not (np.isscalar(dF) and dF == 0):
                    terms.append(ScalarMultiply(-1.0, dF))
            lhs = Add(*terms) if len(terms) > 1 else terms[0]
            evp.add_equation((lhs, 0), condition=eq.get("condition"))
        return evp


class EVP(ProblemBase):
    """Eigenvalue problem: lam*M.X + L.X = 0 (reference: core/problems.py:410)."""

    def __init__(self, variables, eigenvalue=None, namespace=None, **kw):
        super().__init__(variables, namespace=namespace, **kw)
        if eigenvalue is None:
            raise ValueError("EVP requires an eigenvalue field.")
        self.eigenvalue = eigenvalue

    def _build_matrix_expressions(self, lhs, rhs):
        if not (_is_scalar(rhs) and rhs == 0):
            raise UnsupportedEquationError("EVP equations must have zero RHS.")
        lhs = _distribute_marker(lhs, self.eigenvalue)
        terms = _flatten_terms(lhs)
        m_terms, l_terms = [], []
        for term in terms:
            if _is_scalar(term):
                continue
            if _contains_marker(term, self.eigenvalue):
                m_terms.append(_strip_linear_factor(term, self.eigenvalue))
            else:
                l_terms.append(term)
        M_expr = Add(*m_terms) if len(m_terms) > 1 else (m_terms[0] if m_terms else None)
        L_expr = Add(*l_terms) if len(l_terms) > 1 else (l_terms[0] if l_terms else None)
        domain, tensorsig = self._eq_domain([e for e in (M_expr, L_expr) if e is not None])
        return {"domain": domain, "tensorsig": tensorsig,
                "M": self._wrap(M_expr, domain),
                "L": self._wrap(L_expr, domain),
                "F": None}

    def build_solver(self, **kw):
        from .solvers import EigenvalueSolver
        return EigenvalueSolver(self, **kw)


class NLBVP(ProblemBase):
    """Nonlinear boundary value problem solved by Newton-Kantorovich
    iteration (reference: core/problems.py:196 NLBVP)."""

    def __init__(self, variables, namespace=None, **kw):
        super().__init__(variables, namespace=namespace, **kw)
        # Perturbation variables for the Newton linearization
        self.perturbations = []
        for var in self.variables:
            pert = Field(var.dist, bases=var.domain.bases, tensorsig=var.tensorsig,
                         name=f"d_{var.name}", dtype=var.dtype)
            self.perturbations.append(pert)

    def _build_matrix_expressions(self, lhs, rhs):
        # Residual G = lhs - rhs; Newton solves dG.dX = -G
        if _is_scalar(rhs) and rhs == 0:
            residual = lhs
        elif _is_scalar(rhs):
            const = self.dist.Field(name=f"const_{len(self.equations)}")
            const["g"] = rhs
            residual = lhs - const
        else:
            residual = lhs - rhs
        dG = residual.frechet_differential(self.variables, self.perturbations)
        if _is_scalar(dG):
            raise UnsupportedEquationError("Equation has no dependence on variables.")
        domain, tensorsig = self._eq_domain([dG])
        return {"domain": domain, "tensorsig": tensorsig,
                "L": self._wrap(dG, domain),
                "residual": residual,
                "F": None}

    def build_solver(self, **kw):
        from .solvers import NonlinearBoundaryValueSolver
        return NonlinearBoundaryValueSolver(self, **kw)
