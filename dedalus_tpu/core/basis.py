"""
Spectral bases (reference: dedalus/core/basis.py — interval bases; curvilinear
bases live in their own modules as they are added).

A basis owns: metadata (size, bounds, dealias), the affine change-of-variables
to its native interval, transform-plan dispatch, group/pair structure along
separable axes, validity masks, and the per-operator matrix builders used by
subproblem assembly.

Coefficient conventions (matching the reference where structure leaks into
matrices):
  * Jacobi: orthonormal Jacobi coefficients; derivative bases are
    (a0+k, b0+k); the grid is always the (a0, b0) Gauss grid
    (reference: core/basis.py:435 Jacobi).
  * RealFourier: interleaved (cos, -sin) pairs, group_shape=2, the k=0
    minus-sin slot is invalid (reference: core/basis.py:1108).
  * ComplexFourier: FFT wavenumber ordering with the Nyquist slot invalid
    (reference: core/basis.py:951).
"""

import numpy as np

from ..tools.cache import CachedClass, CachedMethod
from ..tools import jacobi as jacobi_tools
from ..tools.config import config
from .transforms import get_plan

DEFAULT_LIBRARY = config["transforms"].get("DEFAULT_LIBRARY", "fft")


class AffineCOV:
    """
    Affine change-of-variables between native and problem coordinates
    (reference: core/basis.py:46 AffineCOV).
    """

    def __init__(self, native_bounds, problem_bounds):
        self.native_bounds = native_bounds
        self.problem_bounds = problem_bounds
        n0, n1 = native_bounds
        p0, p1 = problem_bounds
        self.stretch = (p1 - p0) / (n1 - n0)

    def problem_coord(self, native_coord):
        n0, _ = self.native_bounds
        p0, _ = self.problem_bounds
        return p0 + (np.asarray(native_coord) - n0) * self.stretch

    def native_coord(self, problem_coord):
        n0, _ = self.native_bounds
        p0, _ = self.problem_bounds
        pc = problem_coord
        if isinstance(pc, str):
            # accept 'left'/'right'/'center' for boundary interpolation
            if pc == "left":
                return self.native_bounds[0]
            if pc == "right":
                return self.native_bounds[1]
            if pc == "center":
                return (self.native_bounds[0] + self.native_bounds[1]) / 2
            raise ValueError(f"Unknown position: {pc}")
        return n0 + (np.asarray(pc) - p0) / self.stretch


class Basis(metaclass=CachedClass):
    """Base class for 1D spectral bases."""

    dim = 1
    constant = False

    def __init__(self, coord, size, bounds, dealias=1.0, library=None):
        self.coord = coord
        self.coordsystem = getattr(coord, "cs", None) or coord
        self.size = int(size)
        self.bounds = tuple(map(float, bounds))
        self.dealias = float(dealias)
        self.library = library or DEFAULT_LIBRARY

    def grid_size(self, scale):
        return int(np.ceil(scale * self.size))

    @CachedMethod
    def transform_plan(self, scale, library=None):
        return get_plan(self, scale, library)

    def _effective_library(self, library, dtype):
        return library or self.library

    def forward_transform(self, gdata, axis, scale, library=None,
                          tensorsig=(), sub_axis=0):
        library = self._effective_library(library, gdata.dtype)
        return self.transform_plan(scale, library).forward(gdata, axis)

    def backward_transform(self, cdata, axis, scale, library=None,
                           tensorsig=(), sub_axis=0):
        library = self._effective_library(library, cdata.dtype)
        return self.transform_plan(scale, library).backward(cdata, axis)

    # --- multi-axis accessors (1D defaults; curvilinear bases override) ---

    @property
    def first_axis(self):
        return self.coord.axis

    def coeff_size(self, sub_axis):
        return self.size

    def sub_grid_size(self, sub_axis, scale):
        return self.grid_size(scale)

    def sub_separable(self, sub_axis):
        return self.separable

    def sub_group_shape(self, sub_axis):
        return self.group_shape

    def sub_n_groups(self, sub_axis):
        return self.n_groups

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """
        Component-resolved validity over this basis's axes at one group:
        bool array (ncomp, *per-axis slot sizes). 1D default broadcasts the
        axis mask over components.
        """
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        axis = self.first_axis
        if axis in sep_widths:
            ax_mask = self.valid_elements()[group[axis]]
        else:
            # layout-coupled axis: the whole-axis slot is the flattened
            # (group, pair) coefficient run
            ax_mask = np.ravel(self.valid_elements())
        return np.broadcast_to(ax_mask[None], (ncomp,) + ax_mask.shape)

    # --- group structure (separable axes); coupled bases override ---
    separable = False
    group_shape = 1

    def __repr__(self):
        return f"{type(self).__name__}({self.coord.name}, {self.size})"

    def derivative_basis(self, order=1):
        return self

    def constant_column(self):
        """Column embedding a constant into this basis's coefficients. (N, 1)."""
        raise NotImplementedError


class Jacobi(Basis):
    """
    Jacobi-family interval basis (reference: core/basis.py:435).

    Parameters a0, b0 give the family (grid); k gives the derivative level:
    coefficients are in (a, b) = (a0+k, b0+k).
    """

    separable = False

    def __init__(self, coord, size, bounds, a, b, a0=None, b0=None,
                 dealias=1.0, library=None, k=None):
        # default library comes from config DEFAULT_LIBRARY; the 'fft' plan
        # is the DCT fast path for Chebyshev grids and falls back to the
        # MMT internally for other Jacobi families
        super().__init__(coord, size, bounds, dealias=dealias, library=library)
        if a0 is None:
            a0 = a
        if b0 is None:
            b0 = b
        self.a, self.b = float(a), float(b)
        self.a0, self.b0 = float(a0), float(b0)
        self.k = int(round(self.a - self.a0))
        if not np.allclose([self.a - self.a0, self.b - self.b0], self.k):
            raise ValueError("Jacobi derivative level must be integer and equal in a and b.")
        self.COV = AffineCOV((-1.0, 1.0), self.bounds)

    def __repr__(self):
        return f"Jacobi({self.coord.name}, {self.size}, a={self.a}, b={self.b})"

    def derivative_basis(self, order=1):
        return Jacobi(self.coord, self.size, self.bounds,
                      a=self.a + order, b=self.b + order,
                      a0=self.a0, b0=self.b0, dealias=self.dealias, library=self.library)

    def base_basis(self):
        return Jacobi(self.coord, self.size, self.bounds, a=self.a0, b=self.b0,
                      dealias=self.dealias, library=self.library)

    def native_grid(self, scale=1.0):
        return jacobi_tools.build_grid(self.grid_size(scale), self.a0, self.b0)

    def global_grid(self, scale=1.0):
        return self.COV.problem_coord(self.native_grid(scale))

    # ---- operator submatrices (problem coordinates) ----

    @CachedMethod
    def conversion_matrix(self, dk):
        """(a,b) -> (a+dk, b+dk), shape (N, N)."""
        return jacobi_tools.conversion_matrix(self.size, self.a, self.b, dk, dk)

    @CachedMethod
    def differentiation_matrix(self):
        """d/dx in problem coords: (a,b) coeffs -> (a+1,b+1) coeffs."""
        D = jacobi_tools.differentiation_matrix(self.size, self.a, self.b)
        return D / self.COV.stretch

    @CachedMethod
    def interpolation_vector(self, position):
        """Row (1, N): evaluate (a,b) coefficients at problem position."""
        xi = self.COV.native_coord(position)
        return jacobi_tools.interpolation_vector(self.size, self.a, self.b, xi)[None, :]

    @CachedMethod
    def integration_vector(self):
        """Row (1, N): integral over the problem interval."""
        return jacobi_tools.integration_vector(self.size, self.a, self.b)[None, :] * self.COV.stretch

    def multiplication_matrix(self, f_coeffs, f_basis, dk_out=0):
        """
        Matrix mapping this basis's coeffs to coeffs of (f * u) in
        (a + dk_out, b + dk_out), for NCC f with coefficients in f_basis.
        """
        return jacobi_tools.multiplication_matrix(
            self.size, self.a + dk_out, self.b + dk_out,
            self.size, self.a, self.b,
            np.asarray(f_coeffs), f_basis.a, f_basis.b)

    def lift_column(self, index):
        """Column (N, 1): embed a constant-in-axis tau via mode `index`."""
        col = np.zeros((self.size, 1))
        col[index, 0] = 1.0
        return col

    def constant_column(self):
        col = np.zeros((self.size, 1))
        col[0, 0] = np.sqrt(jacobi_tools.mass(self.a0, self.b0))
        if self.k:
            C = jacobi_tools.conversion_matrix(self.size, self.a0, self.b0, self.k, self.k)
            col = C @ col
        return col

    def valid_elements(self):
        return np.ones(self.size, dtype=bool)


def ChebyshevT(coord, size, bounds, **kw):
    """First-kind Chebyshev basis (reference: core/basis.py:649)."""
    return Jacobi(coord, size, bounds, a=-1/2, b=-1/2, **kw)


def ChebyshevU(coord, size, bounds, **kw):
    return Jacobi(coord, size, bounds, a=1/2, b=1/2, a0=-1/2, b0=-1/2, **kw)


def ChebyshevV(coord, size, bounds, **kw):
    return Jacobi(coord, size, bounds, a=3/2, b=3/2, a0=-1/2, b0=-1/2, **kw)


def Legendre(coord, size, bounds, **kw):
    """Legendre basis (reference: core/basis.py:636)."""
    return Jacobi(coord, size, bounds, a=0, b=0, **kw)


def Ultraspherical(coord, size, bounds, alpha, alpha0=None, **kw):
    """Gegenbauer/ultraspherical basis (reference: core/basis.py:640)."""
    a = alpha - 1/2
    a0 = a if alpha0 is None else alpha0 - 1/2
    return Jacobi(coord, size, bounds, a=a, b=a, a0=a0, b0=a0, **kw)


class FourierBase(Basis):
    """Common machinery for periodic Fourier bases."""

    separable = True

    def __init__(self, coord, size, bounds=(0, 2*np.pi), dealias=1.0, library=None):
        super().__init__(coord, size, bounds, dealias=dealias, library=library)
        if self.size % 2:
            raise ValueError("Fourier basis size must be even.")
        self.COV = AffineCOV((0.0, 2*np.pi), self.bounds)
        self.length = self.bounds[1] - self.bounds[0]
        # native wavenumber -> problem wavenumber factor
        self.kappa = 2 * np.pi / self.length

    def native_grid(self, scale=1.0):
        Ng = self.grid_size(scale)
        return 2 * np.pi * np.arange(Ng) / Ng

    def global_grid(self, scale=1.0):
        return self.COV.problem_coord(self.native_grid(scale))

    def derivative_basis(self, order=1):
        return self

    def _effective_library(self, library, dtype):
        library = library or self.library
        if library == "fft" and np.dtype(dtype).itemsize == 8:
            import jax
            if jax.default_backend() in ("tpu", "axon"):
                # TPU has no complex128: route 64-bit data through the
                # real-valued MMT (a batched matmul on the MXU).
                return "matrix"
        return library

    def _mult_plan_cls(self):
        """MMT plan class for this basis: registry lookup walks the MRO so
        subclasses (e.g. the polar S1 azimuth bases) reuse their Fourier
        parent's plans."""
        from .transforms import transform_registry
        for cls in type(self).__mro__:
            plan = transform_registry.get((cls.__name__, "matrix"))
            if plan is not None:
                return plan
        raise KeyError(f"No matrix transform plan for {type(self).__name__}")

    @CachedMethod
    def _mult_forward_matrix(self, Ng):
        """Cached dense forward MMT on the Ng-point grid: only diag(g)
        varies between multiplication_matrix calls (e.g. the Mathieu
        parameter sweep rebuilds per q), so the O(Ng N^2) construction is
        paid once per (basis, Ng)."""
        return self._mult_plan_cls().build_forward(self, Ng / self.size)

    @CachedMethod
    def _mult_backward_matrix(self, Ng):
        return self._mult_plan_cls().build_backward(self, Ng / self.size)

    def multiplication_matrix(self, ncc_coeffs, ncc_basis=None):
        """
        Coefficient-space matrix multiplying by the function with
        coefficients `ncc_coeffs` (on `ncc_basis`, default self): the
        coupling matrix of an LHS NCC that varies along this periodic axis
        (reference supports Fourier NCCs via non-separable subproblems,
        e.g. the Mathieu example). Built exactly as forward . diag(ncc on
        grid) . backward on a 2x-oversampled common grid (alias-free for
        products of two resolved functions).
        """
        ncc_basis = ncc_basis or self
        Ng = 2 * max(self.size, ncc_basis.size)
        F = self._mult_forward_matrix(Ng)
        B = self._mult_backward_matrix(Ng)
        B_ncc = B if ncc_basis is self else ncc_basis._mult_backward_matrix(Ng)
        g = B_ncc @ np.asarray(ncc_coeffs)
        return F @ (g[:, None] * B)


class RealFourier(FourierBase):
    """
    Real trigonometric basis with interleaved (cos, -sin) coefficient pairs
    (reference: core/basis.py:1108; group_shape=(2,) at :1114).
    """

    group_shape = 2

    @property
    def n_groups(self):
        return self.size // 2

    def group_wavenumber(self, g):
        """Problem-coordinate wavenumber of group g."""
        return np.asarray(g) * self.kappa

    def valid_elements(self):
        """(n_groups, 2) bool: the k=0 minus-sin slot is invalid."""
        valid = np.ones((self.n_groups, 2), dtype=bool)
        valid[0, 1] = False
        return valid

    # --- per-group operator blocks (each (2, 2), problem coordinates) ---

    def identity_blocks(self):
        return np.tile(np.eye(2), (self.n_groups, 1, 1))

    def differentiation_blocks(self):
        """
        d/dx on (cos, -sin) amplitudes of mode k:
            f  = c cos(kx) + s (-sin(kx))
            f' = (-k s) cos(kx) + (k c)(-sin(kx))
        """
        k = self.group_wavenumber(np.arange(self.n_groups))
        blocks = np.zeros((self.n_groups, 2, 2))
        blocks[:, 0, 1] = -k
        blocks[:, 1, 0] = k
        return blocks

    def integration_blocks(self):
        """Integrate over the interval: L * cos0 amplitude, into the constant slot."""
        blocks = np.zeros((self.n_groups, 2, 2))
        blocks[0, 0, 0] = self.length
        return blocks

    def constant_blocks(self):
        """Embed a constant-along-axis value into (cos0, group 0)."""
        blocks = np.zeros((self.n_groups, 2, 2))
        blocks[0, 0, 0] = 1.0
        return blocks

    def interpolation_rows(self, position):
        """(n_groups, 2) row weights evaluating each group at `position`."""
        theta0 = self.COV.native_coord(position)
        g = np.arange(self.n_groups)
        rows = np.stack([np.cos(g * theta0), -np.sin(g * theta0)], axis=-1)
        rows[0, 1] = 0.0
        return rows


class ComplexFourier(FourierBase):
    """
    Complex exponential basis, FFT wavenumber ordering, Nyquist invalid
    (reference: core/basis.py:951).
    """

    group_shape = 1

    @property
    def n_groups(self):
        return self.size

    @property
    def wavenumbers_native(self):
        return np.fft.fftfreq(self.size, d=1.0 / self.size).astype(int)

    def group_wavenumber(self, g):
        return self.wavenumbers_native[np.asarray(g)] * self.kappa

    def valid_elements(self):
        valid = np.ones((self.n_groups, 1), dtype=bool)
        valid[self.size // 2, 0] = False
        return valid

    def identity_blocks(self):
        return np.ones((self.n_groups, 1, 1), dtype=complex)

    def differentiation_blocks(self):
        k = self.group_wavenumber(np.arange(self.n_groups))
        return (1j * k).reshape(-1, 1, 1)

    def integration_blocks(self):
        blocks = np.zeros((self.n_groups, 1, 1), dtype=complex)
        blocks[0, 0, 0] = self.length
        return blocks

    def constant_blocks(self):
        blocks = np.zeros((self.n_groups, 1, 1), dtype=complex)
        blocks[0, 0, 0] = 1.0
        return blocks

    def interpolation_rows(self, position):
        theta0 = self.COV.native_coord(position)
        k = self.wavenumbers_native
        rows = np.exp(1j * k * theta0).reshape(-1, 1)
        rows[self.size // 2] = 0.0
        return rows


def Fourier(coord, size, bounds, dtype=np.float64, **kw):
    """Dtype-dispatching Fourier factory."""
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        return ComplexFourier(coord, size, bounds, **kw)
    return RealFourier(coord, size, bounds, **kw)
