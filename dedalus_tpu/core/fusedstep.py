"""
Fused spectral step: transform -> solve -> transform without intermediate
round-trips (ROADMAP item 2; TurboFNO in PAPERS.md shows the shape of the
win for FFT->GEMM->iFFT chains).

Profile-driven design. The PR-1 phase timers on the CPU headline rank the
step's traffic (rb256x64, RK222, banded, f64, 2 host cores):

    matsolve   141.7 ms/stage   (~91% of the step)
    rhs_eval    16.3 ms/stage   (transforms 4.7 ms of it)

and inside matsolve, the blocked banded substitution dominates: each of
the NB sequential scan steps dispatches a batched `solve_triangular`
custom call that costs ~19x an equivalent batched matmul at these shapes
((G, q, q) x (G, q, 1): 876 us vs 47 us measured). The highest-traffic
"pair" is therefore the RHS-assembly GEMM feeding the banded
substitution, not the transform pair — so the measured default fuses the
solve side, and the MMT composition targets the accelerator backends
where matmul transforms are the architecture win (the same reasoning
that picked BatchedInverse for the TPU dense path).

Fusion layers (config section [fusion], resolved once per solver build):

  FUSED_SOLVE     — at `factor_lincomb` time the banded panel factors are
                    precomposed into explicit inverses (L1^-1, U11^-1,
                    last-block A^-1, Woodbury capacitance^-1), so every
                    substitution scan step and the Woodbury correction
                    run as batched GEMMs instead of triangular-solve /
                    pivoted-LU custom calls (libraries/pencilops.py).
                    Factor-time cost, amortized over the step loop; LBVP/
                    NLBVP/EVP `factor()` keeps the backward-stable
                    substitution (one factor, one solve — nothing to
                    amortize).
  FUSED_MATVEC    — M@X and L@X in one pass: shared permute/pad/scatter,
                    both band stores walked over one padded operand
                    (`BandedOps.matvec_pair`); bitwise-identical to the
                    separate matvecs by construction.
  FUSED_TRANSFORMS— RHS linear-operator chains precomposed host-side into
                    single batched GEMMs: dealias-scaled backward MMT @
                    (conversion/derivative matrices) on the coupled
                    Jacobi axis, so `grad`/`lap`/`Lift` chains evaluate
                    grid-ward with no intermediate coefficient layout
                    (FusedEvalPlan below; composites are cached through
                    the PR-5 assembly cache under a fusion-keyed entry).
  DONATE_STEP     — the multistep fused step program donates its history
                    buffers (F/MX/LX) so XLA writes the rolled histories
                    in place. Consumers that hold cross-step references
                    (resilience snapshot ring, async checkpoint capture,
                    phase-probe caches) copy when
                    `timestepper.donates_histories` is set.
  PALLAS          — experimental: the fused banded substitution as ONE
                    Pallas kernel per pencil group (forward + backward
                    sweeps with the precomposed inverses in a single
                    kernel, no HBM round-trips between block rows).
                    Interpret-mode on CPU; requires FUSED_SOLVE.

Every fused solve still routes through `pencilops.AdjointSolveOps.solve`
(the custom_vjp funnel), so `DifferentiableIVP` adjoints keep working;
the composite GEMMs are plain jnp matmuls (natively differentiable) and
compose under vmap (EnsembleSolver) and shard_map (distributed pencils)
with zero post-warmup retraces — see tests/test_fusion.py.
"""

import hashlib
import logging

import numpy as np
import jax
import jax.numpy as jnp

from ..tools.config import config

logger = logging.getLogger(__name__)

__all__ = ["FusionPlan", "resolve_fusion", "cache_token", "FusedEvalPlan",
           "pallas_substitution", "guard_histories"]


_ACCEL_BACKENDS = ("tpu", "axon")


def guard_histories(ts, hists=None):
    """The donation contract in ONE place: a DONATE_STEP program aliases
    its multistep history inputs (F/MX/LX) to outputs, so any cross-step
    reference holder — the resilience snapshot ring, SDC replay restore,
    async sharded-checkpoint capture, the phase-probe cache — must own
    device-side copies or it reads donated (deleted) arrays after the
    next step. Returns (F_hist, MX_hist, LX_hist) — the timestepper's
    live buffers by default — copied iff `ts` donates. The copies are
    async device dispatches; no host sync."""
    if hists is None:
        hists = (ts.F_hist, ts.MX_hist, ts.LX_hist)
    if getattr(ts, "donates_histories", False):
        hists = tuple(jnp.array(h, copy=True) for h in hists)
    return hists


class FusionPlan:
    """Resolved fusion switches (immutable per solver build)."""

    __slots__ = ("solve", "matvec", "transforms", "donate", "pallas")

    def __init__(self, solve, matvec, transforms, donate, pallas):
        self.solve = bool(solve)
        self.matvec = bool(matvec)
        self.transforms = bool(transforms)
        self.donate = bool(donate)
        self.pallas = bool(pallas)

    def token(self):
        """Stable content token for cache keys (tools/assembly_cache.py):
        the RESOLVED composition structure, so an `auto` that lands
        differently on another backend keys differently too."""
        return ("fusion-v1", self.solve, self.matvec, self.transforms,
                self.pallas)

    def __repr__(self):
        on = [k for k in ("solve", "matvec", "transforms", "donate",
                          "pallas") if getattr(self, k)]
        return f"FusionPlan({'+'.join(on) or 'off'})"


def _flag(section, key, default, auto_value):
    raw = section.get(key, default).strip().lower() if section else default
    if raw in ("on", "true", "1", "yes"):
        return True
    if raw in ("off", "false", "0", "no", ""):
        return False
    if raw != "auto":
        # a typo'd flag must not SILENTLY resolve to auto: the fused and
        # unfused solves sit in different tolerance classes, so a user
        # who wrote `offf` would compare against the wrong baseline
        raise ValueError(
            f"[fusion] {key} = {raw!r} is not a recognized value "
            f"(on/off/auto)")
    return auto_value


def resolve_fusion(decision=None):
    """Resolve the [fusion] config against the active backend. `auto`
    semantics are profile-driven (module docstring): solve/matvec/donate
    fuse everywhere; transform composition defaults on only where MMT
    GEMMs beat the DCT/FFT fast paths (accelerator backends).

    `decision` (a tools.autotune.Decision) supplies MEASURED auto values
    for the tunable flags: PALLAS (the substitution kernel is a
    first-class autotuner candidate — `auto` means off unless a tuned
    decision selected it) and FUSED_TRANSFORMS when the decision pins
    one. Explicit on/off still wins, exactly as before."""
    section = config["fusion"] if config.has_section("fusion") else None
    accel = jax.default_backend() in _ACCEL_BACKENDS
    cell = getattr(decision, "cell", None) or {}
    transforms_auto = cell.get("fused_transforms")
    if transforms_auto is None:
        transforms_auto = accel
    solve = _flag(section, "FUSED_SOLVE", "auto", True)
    return FusionPlan(
        solve=solve,
        matvec=_flag(section, "FUSED_MATVEC", "auto", True),
        transforms=_flag(section, "FUSED_TRANSFORMS", "auto",
                         bool(transforms_auto)),
        donate=_flag(section, "DONATE_STEP", "auto", True),
        # the Pallas substitution consumes the precomposed inverses
        pallas=_flag(section, "PALLAS", "auto",
                     bool(cell.get("pallas", False))) and solve,
    )


def cache_token():
    """The fusion component of assembly-cache content keys: a flag flip
    (or an `auto` resolving differently) can never serve a payload whose
    precomposed composites were built under another composition."""
    return resolve_fusion().token()


# ------------------------------------------------- composite transform GEMMs
#
# The RHS evaluator's linear-operator chains on the coupled Jacobi axis
# currently evaluate as: operand coeff -> per-axis operator matrices
# (conversion/derivative, coeff layout) -> backward transform (DCT chain
# or MMT) -> grid. Each arrow materializes a full intermediate. The
# composite folds the whole chain into ONE host-precomposed
# (Ng, N) GEMM per term: dealias-scaled backward MMT of the node's
# OUTPUT basis @ the term's coupled-axis matrix, applied directly to the
# operand's coefficients. Separable-axis factors ("blocks": Fourier
# derivative 2x2s) stay in coefficient space ahead of it — they are
# group-diagonal and exact — and the remaining separable axes transform
# after the (already summed) terms, so the whole node costs one GEMM +
# one FFT pass instead of per-term transform chains.

def _foldable_terms(node):
    """[(tensor_factor, blocks_descrs, folded_axis, fold_mat_or_None)] for
    a LinearOperator whose every term couples at most ONE 1-D Jacobi axis
    via a "full" matrix (+ any "blocks" on separable axes), or None when
    the node is outside the foldable set (curvilinear group stacks,
    multi-axis coupling, tensor-shape changes without factors...)."""
    from .basis import Jacobi
    domain = node.domain
    try:
        terms = node.device_terms()
    except Exception:
        return None
    jac_axes = [axis for axis, basis in enumerate(domain.bases)
                if isinstance(basis, Jacobi) and basis.dim == 1]
    if len(jac_axes) != 1:
        return None
    folded_axis = jac_axes[0]
    out = []
    for tensor_factor, descrs in terms:
        blocks = [None] * len(descrs)
        fold_mat = None
        for axis, descr in enumerate(descrs):
            if descr is None:
                continue
            kind = descr[0]
            if axis == folded_axis and kind == "full":
                fold_mat = descr[1]
            elif kind == "blocks" and domain.bases[axis] is not None \
                    and domain.bases[axis].separable:
                blocks[axis] = descr[1]
            else:
                return None
        if tensor_factor is None \
                and tuple(node.operand.tshape) != tuple(node.tshape):
            return None
        out.append((tensor_factor, blocks, folded_axis, fold_mat))
    return out or None


def _fold_spec(node, fold_mat):
    """(plan, fold_mat, shape) for the composite of `node`'s coupled-axis
    term: the node's output-basis backward MMT at dealias scale, folded
    with the term's matrix. The shape is known WITHOUT running the fold,
    so a warm build can validate and adopt cached composites before any
    host GEMM runs (the fold itself happens in FusedEvalPlan._fold, only
    on a cache miss)."""
    axis = None
    from .basis import Jacobi
    for ax, basis in enumerate(node.domain.bases):
        if isinstance(basis, Jacobi) and basis.dim == 1:
            axis = ax
            break
    basis = node.domain.bases[axis]
    scale = node.domain.dealias[axis]
    plan = basis.transform_plan(scale, library="matrix")
    Bshape = np.shape(plan.backward_mat)
    ncols = Bshape[1] if fold_mat is None else int(fold_mat.shape[1])
    return plan, fold_mat, (int(Bshape[0]), int(ncols))


class FusedEvalPlan:
    """
    Per-solver registry of fused RHS linear-operator evaluations.

    Built in two stages so warm builds actually skip the folds: the
    construction walk only records fold SPECS (plan, matrix, composite
    shape — all derivable without folding), the caller consults the
    assembly cache, and `finalize(payload)` either adopts the cached
    composites or runs the host folds fresh. `EvalContext.fusion`
    carries the plan into the traced evaluator; `LinearOperator.ev`
    consults it for grid-layout evaluations.
    """

    def __init__(self, solver, exprs):
        from .operators import LinearOperator
        # optional low-precision composite GEMMs ([precision] MMT_DTYPE,
        # libraries/solvecomp.py): resolved on the solver's build-start
        # plan — grid_eval casts the operand around the contraction
        # (apply_matrix_jax matches the matrix to the operand dtype)
        splan = getattr(solver, "_solve_plan", None)
        self._mmt_dtype = splan.mmt_dtype if splan is not None else "native"
        self.nodes = {}        # id(node) -> [(factor, blocks, axis, comp)]
        self._walk_order = []  # deterministic node order for cache payload
        # id(node) -> [(factor, blocks, axis, plan, fold_mat, shape)];
        # holding plan/fold_mat here pins their ids for _fold's intern
        # (Lift columns are built fresh per device_terms() call, so an
        # unpinned id could be reused by a DIFFERENT matrix and alias)
        self._pending = {}
        seen = set()

        def walk(expr):
            from .future import Future
            if not isinstance(expr, Future) or id(expr) in seen:
                return
            seen.add(id(expr))
            if isinstance(expr, LinearOperator):
                folded = _foldable_terms(expr)
                if folded is not None:
                    entries = []
                    for factor, blocks, axis, fold_mat in folded:
                        plan, mat, shape = _fold_spec(expr, fold_mat)
                        entries.append((factor, blocks, axis,
                                        plan, mat, shape))
                    self._pending[id(expr)] = entries
                    self._walk_order.append(expr)
            for arg in expr.args:
                walk(arg)

        for expr in exprs:
            walk(expr)

        # composition signature, from spec shapes only (no folds): the
        # same bytes whether computed before or after finalize
        h = hashlib.blake2b(digest_size=16)
        for node in self._walk_order:
            for factor, blocks, axis, _plan, _mat, shape \
                    in self._pending[id(node)]:
                h.update(type(node).__name__.encode())
                h.update(repr((np.shape(factor) if factor is not None
                               else None,
                               [np.shape(b) if b is not None else None
                                for b in blocks],
                               axis, tuple(shape))).encode())
        self._signature = h.hexdigest()

    def __len__(self):
        return len(self._walk_order)

    def finalize(self, payload=None):
        """Make the plan evaluable: adopt the cached composites when the
        payload validates against the fresh walk's specs (shape + kind +
        signature — a mismatch is a clean miss, never a wrong GEMM; this
        is the warm path, NO folds run), else fold fresh. Returns True on
        a cache install."""
        installed = payload is not None and self._install(payload)
        if not installed:
            self._fold()
        self._pending = None
        return installed

    def _install(self, payload):
        try:
            meta, arrays = payload["meta"], payload["arrays"]
        except Exception:
            return False
        if meta.get("kind") != "fused_composites" \
                or meta.get("signature") != self.signature():
            return False
        nodes = {}
        for i, node in enumerate(self._walk_order):
            entries = []
            for j, (factor, blocks, axis, _plan, _mat, shape) \
                    in enumerate(self._pending[id(node)]):
                cached = arrays.get(f"comp_{i}_{j}")
                if cached is None or tuple(cached.shape) != tuple(shape):
                    return False
                entries.append((factor, blocks, axis,
                                np.ascontiguousarray(cached)))
            nodes[id(node)] = entries
        self.nodes = nodes
        return True

    def _fold(self):
        """Run the host folds (cache miss): one B @ T per distinct
        (plan, matrix) pair — ids are stable while _pending pins the
        sources — interned so shared chains lift one device copy."""
        interned = {}
        for node in self._walk_order:
            entries = []
            for factor, blocks, axis, plan, fold_mat, _shape \
                    in self._pending[id(node)]:
                key = (id(plan),
                       id(fold_mat) if fold_mat is not None else None)
                comp = interned.get(key)
                if comp is None:
                    B = np.asarray(plan.backward_mat, dtype=np.float64)
                    if fold_mat is None:
                        comp = np.ascontiguousarray(B)
                    else:
                        T = fold_mat.toarray() \
                            if hasattr(fold_mat, "toarray") \
                            else np.asarray(fold_mat)
                        comp = np.ascontiguousarray(B @ T)
                    interned[key] = comp
                entries.append((factor, blocks, axis, comp))
            self.nodes[id(node)] = entries

    # ------------------------------------------------------- traced eval

    def grid_eval(self, node, ctx):
        """Fused grid-layout evaluation of a registered node, or None.
        Falls back (None) under an active transform mesh: the composite
        replaces the coupled-axis backward inside the sharded layout
        walk, whose transpose constraints the generic path owns."""
        entries = self.nodes.get(id(node))
        if entries is None:
            return None
        from .field import _active_mesh
        mesh, _ = _active_mesh(node.domain)
        if mesh is not None:
            return None
        from .future import ev
        from .operators import (apply_axis_blocks, apply_tensor_factor)
        from ..tools.array import apply_matrix_jax
        data = ev(node.operand, ctx, "c")
        tdim_in = node.operand.tdim
        total = None
        folded_axis = entries[0][2]
        with jax.named_scope("dedalus/transform/fused_composite"):
            for factor, blocks, axis, comp in entries:
                term = data
                for bax, blk in enumerate(blocks):
                    if blk is not None:
                        term = apply_axis_blocks(term, blk, tdim_in + bax)
                # the composite GEMM: coupled-axis operator chain +
                # dealiased backward transform in one contraction
                # (optionally in the [precision] MMT dtype — the matrix
                # follows the operand via the match_precision funnel,
                # the result is cast back to the working precision)
                if self._mmt_dtype != "native":
                    from ..libraries.solvecomp import low_dtype
                    wide = term.dtype
                    term = apply_matrix_jax(
                        comp, term.astype(low_dtype(self._mmt_dtype, wide)),
                        tdim_in + axis).astype(wide)
                else:
                    term = apply_matrix_jax(comp, term, tdim_in + axis)
                if factor is not None:
                    term = apply_tensor_factor(
                        term, factor, node.operand.tshape, node.tshape)
                total = term if total is None else total + term
            # remaining axes walk grid-ward in transform_to_grid order
            # (last axis first), the folded axis already in grid layout
            tdim = node.tdim
            domain = node.domain
            for bax in range(domain.dim - 1, -1, -1):
                basis = domain.bases[bax]
                if basis is None or bax == folded_axis:
                    continue
                total = basis.backward_transform(
                    total, tdim + bax, domain.dealias[bax],
                    tensorsig=node.tensorsig, sub_axis=bax - basis.first_axis)
        return total

    # ------------------------------------------------- assembly-cache IO

    def signature(self):
        """Composition-structure signature: per-node composite shapes and
        term layout, hashed into the cache entry key so a drifted problem
        or fold set can never alias. Computed from the walk's specs at
        construction — available before (and unchanged by) finalize."""
        return self._signature

    def cache_key(self, solver):
        base = getattr(solver, "assembly_key", None)
        if base is None or not self._walk_order:
            return None
        plan = getattr(solver, "_fusion_plan", None)
        token = plan.token() if plan is not None else cache_token()
        h = hashlib.blake2b(digest_size=20)
        h.update(b"fused-composites")
        h.update(base.encode())
        h.update(repr(token).encode())
        h.update(self.signature().encode())
        return h.hexdigest()

    def store(self, solver, cache):
        """Persist the precomposed composites (meta + arrays)."""
        key = self.cache_key(solver)
        if cache is None or key is None:
            return None
        arrays = {}
        for i, node in enumerate(self._walk_order):
            for j, (_, _, _, comp) in enumerate(self.nodes[id(node)]):
                arrays[f"comp_{i}_{j}"] = comp
        meta = {"kind": "fused_composites", "signature": self.signature(),
                "n_nodes": len(self._walk_order)}
        try:
            cache.store(key, meta, arrays)
        except Exception as exc:
            logger.warning(f"fused-composite cache store failed: {exc!r}")
        return key

def build_eval_plan(solver):
    """FusedEvalPlan over the solver's RHS `F` expressions (None when
    transform fusion is off or nothing folds), persisted through the
    assembly cache: on a warm hit `finalize` adopts the cached arrays
    and the host folds are skipped entirely."""
    plan = getattr(solver, "_fusion_plan", None) or resolve_fusion()
    if not plan.transforms:
        return None
    from .field import Field
    from .future import Future
    exprs = []
    for eq in solver.equations:
        for member, _cond in eq["members"]:
            expr = member.get("F")
            if isinstance(expr, (Field, Future)):
                exprs.append(expr)
    eval_plan = FusedEvalPlan(solver, exprs)
    if not len(eval_plan):
        return None
    from ..tools import assembly_cache
    cache = assembly_cache.resolve() if solver.cache_ok else None
    key = eval_plan.cache_key(solver)
    payload = cache.load(key) if (cache is not None and key is not None) \
        else None
    if eval_plan.finalize(payload):
        logger.info(f"fused composites: assembly cache hit "
                    f"({len(eval_plan)} node(s), key {key[:12]})")
    elif cache is not None and key is not None:
        if payload is not None:
            # parseable but mismatched/corrupt: quarantine, fresh folds
            cache.discard(key)
        eval_plan.store(solver, cache)
    return eval_plan


# ------------------------------------------------------- Pallas substitution
#
# The experimental end state of the fused solve: the ENTIRE blocked
# substitution (forward elimination + backward substitution over NB block
# rows, with the precomposed panel inverses) as one kernel per pencil
# group — block-row intermediates never round-trip through HBM between
# scan steps. CPU runs interpret mode (the tested configuration); TPU
# lowering is upside when the chip returns. Requires FUSED_SOLVE (the
# kernel consumes the precomposed inverses) and the unchunked single-RHS
# solve shape; callers fall back to the XLA scan path otherwise.

def pallas_substitution(fsub, fp, q):
    """Fused banded substitution: solve B~ y = fp, one RHS column per
    group, as ONE kernel instance per pencil group — the forward and
    backward sweeps run over the precomposed FwdOp/BwdOp/lastOp GEMM
    operators (libraries/pencilops.BandedOps._precompose_subst) with all
    block-row intermediates held in kernel registers/VMEM, never
    round-tripping through HBM between block rows.

    fsub: {"FwdOp": (NB-1, G, 4q^2), "BwdOp": (NB-1, G, 3q^2),
           "lastOp": (G, q, q)}; fp (G, n_pad). Returns y (G, n_pad).
    """
    from jax.experimental import pallas as pl

    G, n_pad = fp.shape
    NB = n_pad // q
    interpret = jax.default_backend() not in _ACCEL_BACKENDS

    def kernel(fwd_ref, bwd_ref, last_ref, fp_ref, out_ref):
        f = fp_ref[0]                                     # (NB, q)
        fwd_ops = fwd_ref[0]                              # (NB-1, 4q^2)
        bwd_ops = bwd_ref[0]                              # (NB-1, 3q^2)
        last_op = last_ref[0]                             # (q, q)
        w0 = f[0]
        ys0 = jnp.zeros((max(NB - 1, 1), q), dtype=f.dtype)

        def fwd(i, carry):
            w, ys = carry
            wf = jnp.concatenate([w, jax.lax.dynamic_index_in_dim(
                f, i + 1, axis=0, keepdims=False)])
            op = jax.lax.dynamic_index_in_dim(
                fwd_ops, i, axis=0, keepdims=False).reshape(2 * q, 2 * q)
            yw = op @ wf
            ys = jax.lax.dynamic_update_index_in_dim(ys, yw[:q], i, axis=0)
            return yw[q:], ys

        w, ys = jax.lax.fori_loop(0, NB - 1, fwd, (w0, ys0))
        x_last = last_op @ w
        xs0 = jax.lax.dynamic_update_index_in_dim(
            jnp.zeros((NB, q), dtype=f.dtype), x_last, NB - 1, axis=0)

        def bwd(j, carry):
            xs, x1, x2 = carry
            i = NB - 2 - j
            y = jax.lax.dynamic_index_in_dim(ys, i, axis=0, keepdims=False)
            op = jax.lax.dynamic_index_in_dim(
                bwd_ops, i, axis=0, keepdims=False).reshape(q, 3 * q)
            x = op @ jnp.concatenate([y, x1, x2])
            xs = jax.lax.dynamic_update_index_in_dim(xs, x, i, axis=0)
            return xs, x, x1

        xs, _, _ = jax.lax.fori_loop(
            0, NB - 1, bwd, (xs0, x_last, jnp.zeros_like(x_last)))
        out_ref[0] = xs.reshape(n_pad)

    # group axis g is the pallas grid; step-stacked operators transpose
    # group-major first so each kernel instance reads one contiguous slab
    fwd_g = jnp.moveaxis(fsub["FwdOp"], 1, 0)   # (G, NB-1, 4q^2)
    bwd_g = jnp.moveaxis(fsub["BwdOp"], 1, 0)
    fpb = fp.reshape(G, NB, q)

    def spec(a):
        nd = a.ndim
        return pl.BlockSpec((1,) + a.shape[1:],
                            lambda g, nd=nd: (g,) + (0,) * (nd - 1))

    return pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[spec(fwd_g), spec(bwd_g), spec(fsub["lastOp"]),
                  spec(fpb)],
        out_specs=pl.BlockSpec((1, n_pad), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((G, n_pad), fp.dtype),
        interpret=interpret,
    )(fwd_g, bwd_g, fsub["lastOp"], fpb).reshape(G, n_pad)
