"""
Deferred-evaluation expression nodes (reference: dedalus/core/future.py).

TPU-native redesign: instead of the reference's per-step interpreted
`evaluate()` walks with layout oscillation (core/evaluator.py:94-148), each
node implements `ev(ctx, layout)` — a pure jnp computation memoized per
(node, layout) within one trace. Whole expression trees therefore compile
into single XLA programs; duplicated transforms are shared via the memo and
XLA CSE.

Layout protocol: 'c' = full coefficient space (in the node's output bases,
including Jacobi derivative levels), 'g' = full grid space at dealias scales.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp

from .field import Operand, Field, transform_to_coeff, transform_to_grid

logger = logging.getLogger(__name__)


class CompiledWithFallback:
    """
    One jit-compiled evaluation over Field-atom inputs with a permanent
    eager fallback: untraceable user callbacks (GeneralFunction host code,
    backends without host callbacks) fail in arbitrary ways on the first
    compiled call, after which evaluation stays eager. Shared by
    Future.evaluate and the output handlers (evaluator.evaluate_tasks).
    """

    def __init__(self, fields, fn, eager, describe):
        from ..tools.jitlift import lifted_jit
        self.fields = fields
        self.fn = lifted_jit(fn)
        self.eager = eager
        self.describe = describe
        self.jit_ok = True

    def __call__(self):
        if self.jit_ok:
            try:
                return self.fn([f.coeff_data() for f in self.fields])
            except Exception as exc:
                logger.debug(f"{self.describe}: compiled evaluation failed "
                             f"({exc!r}); falling back to eager permanently.")
                self.jit_ok = False
        return self.eager()


class EvalContext:
    """Carries substitutions (Field -> traced coeff array) and the memo.
    `fusion` (set by the IVP's RHS evaluator) carries the solver's
    FusedEvalPlan so LinearOperator grid evaluations can route through
    precomposed composite GEMMs (core/fusedstep.py); None = generic."""

    fusion = None

    def __init__(self, subs=None):
        self.subs = subs or {}
        self.memo = {}

    def field_data(self, field, layout):
        key = (id(field), layout)
        if key in self.memo:
            return self.memo[key]
        if field in self.subs:
            coeff = self.subs[field]
        else:
            coeff = field.coeff_data()
        if layout == "c":
            out = coeff
        else:
            out = transform_to_grid(coeff, field.domain, field.domain.dealias,
                                    field.tdim, tensorsig=field.tensorsig)
        self.memo[key] = out
        return out


def ev(node, ctx, layout):
    """Evaluate an operand (Field, Future, or scalar) in the given layout."""
    if isinstance(node, Field):
        return ctx.field_data(node, layout)
    if isinstance(node, Future):
        return node.ev(ctx, layout)
    # plain number
    return node


class Future(Operand):
    """Expression-tree node base (reference: core/future.py:22 Future)."""

    name = "Future"
    natural_layout = "g"

    def __init__(self, *args):
        self.args = list(args)
        self.dist = self._find_dist(args)
        self._build_metadata()

    @staticmethod
    def _find_dist(args):
        for arg in args:
            if isinstance(arg, (Field, Future)):
                return arg.dist
        raise ValueError("Expression has no field operands.")

    def _build_metadata(self):
        """Subclasses set self.domain, self.tensorsig, self.dtype."""
        raise NotImplementedError

    @property
    def tshape(self):
        return tuple(cs.dim for cs in self.tensorsig)

    @property
    def tdim(self):
        return len(self.tensorsig)

    def __repr__(self):
        argstr = ", ".join(map(str, self.args))
        return f"{self.name}({argstr})"

    __str__ = __repr__

    # ------------------------------------------------------------ evaluation

    def ev(self, ctx, layout):
        key = (id(self), layout)
        if key in ctx.memo:
            return ctx.memo[key]
        if layout == self.natural_layout:
            out = self.ev_impl(ctx)
        elif layout == "g":
            out = transform_to_grid(self.ev(ctx, "c"), self.domain,
                                    self.domain.dealias, self.tdim,
                                    tensorsig=self.tensorsig)
        else:
            out = transform_to_coeff(self.ev(ctx, "g"), self.domain,
                                     self.domain.dealias, self.tdim,
                                     tensorsig=self.tensorsig)
        ctx.memo[key] = out
        return out

    def ev_impl(self, ctx):
        raise NotImplementedError

    def evaluate(self):
        """
        Host-facing evaluation: returns a new Field with this node's data.

        The whole expression tree compiles into one cached XLA program per
        node, with the current data of every Field atom passed as an input
        (so repeated evaluation picks up field updates without retracing).
        Nodes whose ev_impl cannot trace (e.g. a GeneralFunction running
        host code) fall back to eager evaluation permanently.
        """
        runner = getattr(self, "_evaluate_cache", None)
        if runner is None:
            fields = sorted(self.atoms(Field),
                            key=lambda f: (f.name or "", id(f)))

            def fn(arrays):
                ctx = EvalContext(dict(zip(fields, arrays)))
                return self.ev(ctx, "c")

            runner = self._evaluate_cache = CompiledWithFallback(
                fields, fn, lambda: self.ev(EvalContext(), "c"), repr(self))
        data = runner()
        out = Field(self.dist, bases=self.domain.bases, tensorsig=self.tensorsig,
                    dtype=self.dtype)
        out.preset_coeff(jnp.asarray(data))
        return out

    # --------------------------------------------------------- symbolic API

    def operand_args(self):
        return [a for a in self.args if isinstance(a, (Field, Future))]

    def atoms(self, *types):
        out = set()
        if not types or isinstance(self, types):
            out.add(self)
        for arg in self.operand_args():
            if isinstance(arg, Future):
                out |= arg.atoms(*types)
            elif not types or isinstance(arg, types):
                out.add(arg)
        return out

    def has(self, *operands):
        for op in operands:
            if self is op:
                return True
            if isinstance(op, type) and isinstance(self, op):
                return True
        return any(isinstance(a, (Field, Future)) and _has(a, operands)
                   for a in self.args)

    def replace(self, old, new):
        if self is old:
            return new
        if isinstance(old, type) and isinstance(self, old):
            return new
        new_args = [a.replace(old, new) if isinstance(a, (Field, Future)) else a
                    for a in self.args]
        return self.rebuild(new_args)

    def rebuild(self, new_args):
        return type(self)(*new_args)

    def frechet_differential(self, variables, perturbations):
        """
        Symbolic derivative d/de [self with vars -> vars + e*perts] at e=0
        (reference: core/field.py:259). Linear nodes: differential passes
        through; nonlinear nodes override.
        """
        out = 0
        for i, arg in enumerate(self.args):
            if isinstance(arg, (Field, Future)):
                d_arg = arg.frechet_differential(variables, perturbations)
                if not (np.isscalar(d_arg) and d_arg == 0):
                    new_args = list(self.args)
                    new_args[i] = d_arg
                    out = out + self.rebuild(new_args)
        return out

    # -------------------------------------------------- matrix construction

    def expression_matrices(self, subproblem, vars, **kw):
        """Sparse matrices mapping each var's pencil to this node's pencil
        (reference: core/operators.py:739 expression_matrices)."""
        raise NotImplementedError(f"{type(self).__name__} has no matrix form.")


def _has(operand, operands):
    if isinstance(operand, Future):
        return operand.has(*operands)
    return any(operand is op for op in operands
               if not isinstance(op, type))
