"""
Solver distribution over a device mesh
(reference: dedalus/core/distributor.py:35 Distributor process-mesh setup;
the per-rank pencil ownership becomes a NamedSharding of the batched pencil
arrays, and GSPMD inserts the reference's transpose/gather collectives
inside the jitted step).
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def pencil_sharding(mesh, ndim=1, axis_name=None):
    """NamedSharding placing the leading (pencil-group) axis on the mesh."""
    axis_name = axis_name or mesh.axis_names[0]
    spec = [axis_name] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def distribute_solver(solver, mesh=None, axis_name=None):
    """
    Shard an InitialValueSolver's device state over the mesh: the pencil
    batch (group) dimension is the data-parallel axis — every group's
    implicit solve is independent (reference: core/timesteppers.py:160-172
    per-pencil factorizations), and the RHS transforms inside the jitted
    step trigger GSPMD all-to-alls exactly where the reference placed MPI
    transposes.

    Returns the solver (modified in place).
    """
    mesh = mesh or solver.dist.mesh
    if mesh is None:
        return solver
    if getattr(solver, "_dd", None) is not None:
        raise ValueError(
            "distribute_solver requires the native step path: the "
            "emulated-f64 (double-double) runner (core/ddstep.py) steps "
            "a single-process dd state the mesh sharding would bypass. "
            "Build with [execution] EMULATED_F64 = never to distribute "
            "f64 solves.")
    # record on the distributor: the compiled transform walks read it to
    # pin intermediate shardings (field.mesh_transforms)
    solver.dist.mesh = mesh
    axis_name = axis_name or mesh.axis_names[0]
    G = solver.pencil_shape[0]
    n = mesh.shape[axis_name]
    if G % n:
        raise ValueError(
            f"Mesh axis {axis_name!r} (size {n}) does not divide pencil "
            f"count {G}; choose resolutions with G % n == 0.")
    s2 = pencil_sharding(mesh, 2, axis_name)
    hist_sharding = NamedSharding(mesh, P(None, axis_name, None))
    solver.X = jax.device_put(solver.X, s2)
    # M/L are pytrees whose every leaf leads with the pencil-group axis
    # (dense (G,S,S), or banded {bands,U,V,C} arrays).
    shard_leaf = lambda a: jax.device_put(
        a, pencil_sharding(mesh, a.ndim, axis_name))
    solver.M_mat = jax.tree.map(shard_leaf, solver.M_mat)
    solver.L_mat = jax.tree.map(shard_leaf, solver.L_mat)
    ts = solver.timestepper
    for name in ("F_hist", "MX_hist", "LX_hist"):
        if hasattr(ts, name):
            setattr(ts, name, jax.device_put(getattr(ts, name), hist_sharding))
    # invalidate any cached LHS factorization built pre-sharding
    if hasattr(ts, "_lhs_key"):
        ts._lhs_key = None
        ts._lhs_aux = None
    return solver
