"""
Distributed execution over JAX device meshes
(reference: dedalus/core/transposes.pyx + dedalus/core/distributor.py layout
chain — the MPI pencil machinery replaced by XLA collectives over ICI/DCN).
"""

from .transposes import (all_to_all_transpose, DistributedPencilPipeline,
                         resolve_transpose_chunks)
from .sharding import distribute_solver, pencil_sharding
from . import multihost
