"""
Multi-host (multi-process) execution support
(reference: the MPI world — mpi4py COMM_WORLD throughout,
dedalus/core/distributor.py:109-113; here one jax.distributed world whose
global device set backs the solver's Mesh, with collectives riding
ICI/DCN and process-0-guarded host IO).

Launch recipe (one process per host, e.g. a v4-32's 4 hosts):

    import dedalus_tpu.parallel.multihost as mh
    mh.initialize()                      # env-driven on TPU pods
    mesh = mh.device_mesh()              # spans ALL processes' devices
    dist = d3.Distributor(coords, mesh=mesh)
    ...
    distribute_solver(solver)            # shards over the global mesh

On TPU pods `jax.distributed.initialize()` reads the cluster environment
automatically. For CPU rehearsal (tests) pass coordinator/process counts
explicitly.
"""

import numpy as np
import jax

__all__ = ["initialize", "device_mesh", "is_primary", "barrier",
           "process_allgather"]

_initialized = False


# NOTE: TPU_WORKER_HOSTNAMES is deliberately absent — single-chip tunnel
# environments set it for libtpu init without implying a multi-host world.
_CLUSTER_ENV_HINTS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                      "MEGASCALE_COORDINATOR_ADDRESS",
                      "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE")


def _cluster_expected(coordinator_address, num_processes):
    import os
    if coordinator_address is not None or num_processes not in (None, 1):
        return True
    return any(os.environ.get(k) for k in _CLUSTER_ENV_HINTS)


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               **kw):
    """Join (or start) the jax.distributed world. Idempotent. A failure is
    swallowed ONLY when nothing suggested a cluster (no arguments, no
    cluster environment) — silently degrading a real pod launch to
    standalone would let every host think it is process 0 and diverge."""
    global _initialized
    if _initialized:
        return
    client = getattr(jax.distributed, "global_state", None)
    if client is not None and getattr(client, "client", None) is not None:
        # user code already called jax.distributed.initialize() directly
        _initialized = True
        return
    # CPU rehearsal worlds (the 2-process tests, laptop dry runs): the
    # default XLA:CPU client has no cross-process collectives ("Multiprocess
    # computations aren't implemented on the CPU backend"); jaxlib's gloo
    # implementation provides them. Must be set before the backend spins
    # up — initialize() is that point; harmless for TPU/GPU worlds (the
    # flag only affects CPU client construction) and best-effort across
    # jax versions that lack the option.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kw)
        _initialized = True
    except Exception:
        if _cluster_expected(coordinator_address, num_processes):
            raise
        # single-process, no cluster env: run standalone


def device_mesh(shape=None, axis_names=None):
    """A Mesh over the GLOBAL device set (all processes). `shape` defaults
    to one flat axis; multi-axis shapes reshape the device list in
    process-major order so intra-host links carry the fastest axis."""
    devices = np.array(jax.devices())
    if shape is None:
        shape = (devices.size,)
    axis_names = tuple(axis_names or
                       ("x", "y", "z", "w")[:len(shape)])
    from jax.sharding import Mesh
    return Mesh(devices.reshape(shape), axis_names)


def is_primary():
    """Whether this process should perform shared-filesystem output
    (reference: rank-0 guarded IO, dedalus/tools/parallel.py:10 Sync)."""
    return jax.process_index() == 0


def barrier(name="dedalus_tpu_barrier"):
    """Cross-process synchronization point (e.g. before process-0 mkdir)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def process_allgather(x):
    """Gather a (possibly sharded) array to a full local copy on every
    process (reference: allgather_data, core/field.py:731)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def broadcast_from_primary(values):
    """Broadcast a flat numeric array from process 0 to all processes
    (reference: rank-0 state scattered through COMM_WORLD; used for
    append-mode output bookkeeping so only the primary scans the shared
    filesystem)."""
    values = np.asarray(values)
    if jax.process_count() == 1:
        return values
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.broadcast_one_to_all(values))
