"""
Pencil redistribution via lax.all_to_all inside shard_map
(reference: dedalus/core/transposes.pyx:22 FFTWTranspose / :246
AlltoallvTranspose — the hand-written MPI pack/unpack loops become one XLA
collective; the pack/unpack reshapes fuse into neighboring ops).

A D-dimensional state on an R-dimensional device mesh keeps the first R
axes block-distributed in coefficient space. Transforming an axis requires
it to be device-local, so the layout walk alternates local transforms with
these all-to-all transposes — exactly the reference's Transform/Transpose
ladder (core/distributor.py:128-166), but compiled: under jit, XLA
schedules the collective on the ICI and overlaps it with local compute
where possible.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from ..tools.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def all_to_all_transpose(data, axis_in, axis_out, mesh, axis_name,
                         layout=None):
    """
    Redistribute `data` from block-sharded along `axis_in` to block-sharded
    along `axis_out` (both global axis indices), preserving the global
    array. `layout` maps OTHER array dims to mesh axis names that stay
    sharded throughout (the multi-axis-mesh case: only `axis_name` moves).

    Equivalent to the reference's pencil transpose
    (core/transposes.pyx:336-355 Alltoallv + split/combine loops over one
    mesh-axis subcommunicator, core/distributor.py:702-713).
    """
    layout = dict(layout or {})
    n = mesh.shape[axis_name]
    # local block divisibility: the out axis is split n-ways on top of any
    # existing sharding of other dims
    if data.shape[axis_out] % n:
        raise ValueError(
            f"Axis {axis_out} (size {data.shape[axis_out]}) must be "
            f"divisible by mesh axis {axis_name!r} (size {n}).")
    in_spec = [layout.get(d) for d in range(data.ndim)]
    out_spec = list(in_spec)
    in_spec[axis_in] = axis_name
    out_spec[axis_out] = axis_name

    @partial(shard_map, mesh=mesh, in_specs=P(*in_spec), out_specs=P(*out_spec))
    def _transpose(block):
        return lax.all_to_all(block, axis_name, split_axis=axis_out,
                              concat_axis=axis_in, tiled=True)

    # phase label shared with the metrics timers (dedalus/transpose/...,
    # see tools/metrics.py) so profiler traces attribute the collective
    with jax.named_scope("dedalus/transpose/all_to_all"):
        return _transpose(data)


class DistributedPencilPipeline:
    """
    Distributed full-coefficient <-> full-grid transform pipeline for a
    D-dimensional domain over an R-dimensional device mesh (R < D): mesh
    axis r shards array dim r in coefficient space and array dim r+1 in
    grid space (the reference's block "pencil" decomposition,
    core/distributor.py:59-74).

    to_grid walk (mirroring the reference layout chain, :128-166):
      for axis = D-1 .. R:  local backward transform      [Transform]
      for r   = R-1 .. 0:   all_to_all mesh axis r: dim r -> dim r+1
                            then local backward transform of dim r
                                                          [Transpose+Transform]
    to_coeff reverses the walk. Each step is jnp inside one jit; the
    collectives ride the ICI. Tensor components (leading dims) are never
    distributed.
    """

    def __init__(self, domain, mesh, axis_names=None):
        self.domain = domain
        self.mesh = mesh
        if isinstance(axis_names, str):
            axis_names = (axis_names,)
        self.axis_names = tuple(axis_names or mesh.axis_names)
        self.R = len(self.axis_names)
        self.D = domain.dim
        if self.R >= self.D:
            raise ValueError(f"Mesh rank {self.R} must be below the domain "
                             f"dimension {self.D}.")
        for axis in range(self.D):
            if domain.bases[axis] is None:
                raise ValueError("Pipeline requires a basis on every axis.")

    def _transform(self, data, axis, scales, tensorsig, forward):
        basis = self.domain.bases[axis]
        fn = basis.forward_transform if forward else basis.backward_transform
        return fn(data, len(tensorsig) + axis, scales[axis],
                  tensorsig=tensorsig, sub_axis=axis - basis.first_axis)

    def _constrain(self, data, layout):
        """Pin the stage sharding: fft ops are unpartitionable, so without
        explicit constraints GSPMD gathers at the first local transform
        after a transpose and the walk degrades to replicated."""
        spec = [layout.get(d) for d in range(data.ndim)]
        return jax.lax.with_sharding_constraint(
            data, NamedSharding(self.mesh, P(*spec)))

    def coeff_layout(self, tdim=0):
        """{array dim: mesh axis} for full-coefficient arrays."""
        return {tdim + r: self.axis_names[r] for r in range(self.R)}

    def grid_layout(self, tdim=0):
        """{array dim: mesh axis} for full-grid arrays."""
        return {tdim + r + 1: self.axis_names[r] for r in range(self.R)}

    def to_grid(self, cdata, scales=None, tensorsig=()):
        """Full coefficient -> full grid, sharded end-to-end. The current
        {dim: mesh axis} layout is published to core/meshctx so every
        local transform routes its fft through shard_map (XLA cannot
        partition fft ops), and each stage's sharding is pinned."""
        from ..core import meshctx
        scales = scales or (1.0,) * self.D
        D, R = self.D, self.R
        tdim = len(tensorsig)
        layout = self.coeff_layout(tdim)
        prev = meshctx.set_walk(self.mesh, layout)
        try:
            out = self._constrain(cdata, layout)
            for axis in range(D - 1, R - 1, -1):
                out = self._transform(out, axis, scales, tensorsig,
                                      forward=False)
            for r in range(R - 1, -1, -1):
                del layout[tdim + r]
                out = all_to_all_transpose(out, tdim + r, tdim + r + 1,
                                           self.mesh, self.axis_names[r],
                                           layout=layout)
                layout[tdim + r + 1] = self.axis_names[r]
                meshctx.set_walk(self.mesh, layout)
                out = self._constrain(out, layout)
                out = self._transform(out, r, scales, tensorsig,
                                      forward=False)
            return self._constrain(out, layout)
        finally:
            meshctx.restore_walk(prev)

    def to_coeff(self, gdata, scales=None, tensorsig=()):
        """Full grid -> full coefficient, sharded end-to-end (see to_grid
        for the meshctx walk publication + stage pinning)."""
        from ..core import meshctx
        scales = scales or (1.0,) * self.D
        D, R = self.D, self.R
        tdim = len(tensorsig)
        layout = self.grid_layout(tdim)
        prev = meshctx.set_walk(self.mesh, layout)
        try:
            out = self._constrain(gdata, layout)
            for r in range(R):
                out = self._transform(out, r, scales, tensorsig,
                                      forward=True)
                del layout[tdim + r + 1]
                out = all_to_all_transpose(out, tdim + r + 1, tdim + r,
                                           self.mesh, self.axis_names[r],
                                           layout=layout)
                layout[tdim + r] = self.axis_names[r]
                meshctx.set_walk(self.mesh, layout)
                out = self._constrain(out, layout)
            for axis in range(R, D):
                out = self._transform(out, axis, scales, tensorsig,
                                      forward=True)
            return self._constrain(out, layout)
        finally:
            meshctx.restore_walk(prev)
