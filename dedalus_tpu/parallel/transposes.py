"""
Pencil redistribution via lax.all_to_all inside shard_map
(reference: dedalus/core/transposes.pyx:22 FFTWTranspose / :246
AlltoallvTranspose — the hand-written MPI pack/unpack loops become one XLA
collective; the pack/unpack reshapes fuse into neighboring ops).

A D-dimensional state on an R-dimensional device mesh keeps the first R
axes block-distributed in coefficient space. Transforming an axis requires
it to be device-local, so the layout walk alternates local transforms with
these all-to-all transposes — exactly the reference's Transform/Transpose
ladder (core/distributor.py:128-166), but compiled.

Overlapped chunking ([distributed] TRANSPOSE_CHUNKS): a monolithic
all_to_all leaves the device idle through the whole exchange before the
next axis's transform starts. Each transpose+transform stage is therefore
CHUNKED — the per-device destination block is split into
TRANSPOSE_CHUNKS sub-blocks, each issued as its own lax.all_to_all with
the already-arrived chunk's local transform running between issues, so
communication for chunk k+1 rides under compute for chunk k (the
AccFFT/DaggerFFT overlap structure; XLA's async collective scheduling
does the interleave on TPU ICI, and the dataflow graph carries no false
dependencies between chunks on any backend). The whole stage runs inside
ONE shard_map (explicit per-stage manual sharding, so GSPMD can never
degrade a stage to a gather), and the chunk extraction is STRIDED so
every chunk's all_to_all lands in canonical block order — reassembly is
a local reshape and the chunked stage is bit-identical data movement.
The interleaved transforms are the fft fast paths, which are
batch-slab-invariant bitwise; chunked walks therefore reproduce the
monolithic walk bit-for-bit (asserted in tests/test_distributed.py).
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from ..tools.compat import shard_map
from ..tools.config import cfg_get
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["all_to_all_transpose", "DistributedPencilPipeline",
           "resolve_transpose_chunks", "stage_chunks",
           "overlapped_to_grid_stage", "overlapped_to_coeff_stage"]

# 'auto' chunk counts, by backend class. Accelerators (async collectives
# on the ICI that genuinely run under compute): 4 sub-blocks, so the
# first chunk's transform starts after ~1/4 of the exchange while
# per-chunk collective latency stays amortized. CPU (collectives are
# thread-pool memcpys with nothing to hide under): 2 — the chunked walk
# must stay within the >=0.95x non-regression bar, and measured CPU cost
# is ~0.7% at 2 chunks vs ~4% at 4 (benchmarks/scaling.py rows). Every
# stage additionally clamps to a divisor of its per-device destination
# block (stage_chunks), so small problems degrade gracefully toward the
# monolithic walk.
AUTO_CHUNKS_ACCELERATOR = 4
AUTO_CHUNKS_CPU = 2
_ACCELERATOR_BACKENDS = ("tpu", "axon", "gpu", "cuda", "rocm")


def resolve_transpose_chunks(value=None, decision=None):
    """
    Resolve the transpose chunk count ONCE (per solver build / pipeline
    construction): `[distributed] TRANSPOSE_CHUNKS` = 'auto' (backend
    heuristic documented at AUTO_CHUNKS_*) or a positive integer. The
    resolved value rides the assembly-cache solver key and the serving
    pool key (tools/assembly_cache.py) — pooled compiled programs depend
    on the chunk structure, so two chunk configs must never alias one
    entry. Raises ValueError on anything else.

    `decision` (a tools.autotune.Decision) supplies a MEASURED value for
    the `auto` branch when its cell pins one; an explicit config integer
    still wins.
    """
    if value is None:
        value = cfg_get("distributed", "TRANSPOSE_CHUNKS", "auto")
    if isinstance(value, str):
        text = value.strip().lower()
        if text == "auto":
            cell = getattr(decision, "cell", None) or {}
            tuned = cell.get("transpose_chunks")
            if isinstance(tuned, int) and not isinstance(tuned, bool) \
                    and tuned >= 1:
                return int(tuned)
            backend = jax.default_backend()
            return (AUTO_CHUNKS_ACCELERATOR
                    if backend in _ACCELERATOR_BACKENDS
                    else AUTO_CHUNKS_CPU)
        try:
            value = int(text)
        except ValueError:
            raise ValueError(
                f"[distributed] TRANSPOSE_CHUNKS must be 'auto' or a "
                f"positive integer, got {value!r}") from None
    if not isinstance(value, (int, np.integer)) or isinstance(value, bool):
        raise ValueError(
            f"[distributed] TRANSPOSE_CHUNKS must be 'auto' or a "
            f"positive integer, got {value!r}")
    if value < 1:
        raise ValueError(
            f"[distributed] TRANSPOSE_CHUNKS must be >= 1, got {value}")
    return int(value)


def stage_chunks(requested, block):
    """Largest chunk count <= `requested` dividing the per-device
    destination block `block` (>=1 always divides, so every stage has a
    legal chunking and small blocks fall back toward monolithic)."""
    block = int(block)
    c = max(1, min(int(requested), block))
    while block % c:
        c -= 1
    return c


def _validate_divisible(data, axis_in, axis_out, n, axis_name):
    """Both moving axes must divide the mesh axis: the sharded `axis_in`
    splits into n local blocks, and the tiled all_to_all splits `axis_out`
    n ways. A non-divisible axis_in used to sail through and produce a
    wrong-shaped tiled exchange; now each failure names its axis."""
    for which, axis in (("axis_in", axis_in), ("axis_out", axis_out)):
        if data.shape[axis] % n:
            raise ValueError(
                f"{which} {axis} (size {data.shape[axis]}) must be "
                f"divisible by mesh axis {axis_name!r} (size {n}); a "
                f"non-divisible {which} would mis-shape the tiled "
                f"all_to_all blocks.")


def all_to_all_transpose(data, axis_in, axis_out, mesh, axis_name,
                         layout=None):
    """
    Redistribute `data` from block-sharded along `axis_in` to block-sharded
    along `axis_out` (both global axis indices), preserving the global
    array. `layout` maps OTHER array dims to mesh axis names that stay
    sharded throughout (the multi-axis-mesh case: only `axis_name` moves —
    including the ensemble `batch` axis of the 2-D batch x pencil
    composition, which rides in `layout` untouched).

    Equivalent to the reference's pencil transpose
    (core/transposes.pyx:336-355 Alltoallv + split/combine loops over one
    mesh-axis subcommunicator, core/distributor.py:702-713).
    """
    layout = dict(layout or {})
    n = mesh.shape[axis_name]
    _validate_divisible(data, axis_in, axis_out, n, axis_name)
    in_spec = [layout.get(d) for d in range(data.ndim)]
    out_spec = list(in_spec)
    in_spec[axis_in] = axis_name
    out_spec[axis_out] = axis_name

    @partial(shard_map, mesh=mesh, in_specs=P(*in_spec), out_specs=P(*out_spec))
    def _transpose(block):
        return lax.all_to_all(block, axis_name, split_axis=axis_out,
                              concat_axis=axis_in, tiled=True)

    # phase label shared with the metrics timers (dedalus/transpose/...,
    # see tools/metrics.py) so profiler traces attribute the collective
    with jax.named_scope("dedalus/transpose/all_to_all"):
        return _transpose(data)


def _suspend_walk():
    """Deactivate the meshctx transform-walk inside a stage body: stage
    data is already device-local, so the per-chunk transforms must not
    re-route their ffts through a nested shard_map of their own."""
    from ..core import meshctx
    return meshctx


def _take_strided_chunk(block, axis, n, C, k):
    """Chunk k of the destination-block-strided split of `axis` (local
    view, full size n*B): rows {d*B + k*B/C + t} for every destination
    device d — so the chunk's all_to_all lands exactly in canonical block
    order and the final reassembly is a LOCAL concatenation."""
    shp = block.shape
    B = shp[axis] // n
    resh = block.reshape(shp[:axis] + (n, C, B // C) + shp[axis + 1:])
    piece = lax.index_in_dim(resh, k, axis=axis + 1, keepdims=False)
    return piece.reshape(shp[:axis] + (n * (B // C),) + shp[axis + 1:])


def overlapped_to_grid_stage(data, transform, axis_in, axis_out, mesh,
                             axis_name, layout=None, chunks=1):
    """
    One to_grid walk stage: all_to_all transpose (axis_in -> axis_out)
    followed by the local backward `transform` along axis_in, chunked so
    chunk k+1's collective is issued before chunk k's transform runs
    (double-buffered: exactly one arrived chunk is in flight through the
    transform while the next exchange proceeds). The chunk axis is the
    per-device DESTINATION block of axis_out; chunks are strided by
    destination device so the exchange is canonical-block-ordered data
    movement and the chunked stage output is bit-identical to the
    monolithic stage. Runs inside one shard_map: every chunk's sharding
    is explicit, so GSPMD cannot degrade any part of the stage to a
    gather.
    """
    layout = dict(layout or {})
    n = mesh.shape[axis_name]
    _validate_divisible(data, axis_in, axis_out, n, axis_name)
    C = stage_chunks(chunks, data.shape[axis_out] // n)
    in_spec = [layout.get(d) for d in range(data.ndim)]
    out_spec = list(in_spec)
    in_spec[axis_in] = axis_name
    out_spec[axis_out] = axis_name
    meshctx = _suspend_walk()

    def a2a(piece):
        return lax.all_to_all(piece, axis_name, split_axis=axis_out,
                              concat_axis=axis_in, tiled=True)

    @partial(shard_map, mesh=mesh, in_specs=P(*in_spec),
             out_specs=P(*out_spec))
    def _stage(block):
        prev = meshctx.set_walk(None, {})
        try:
            if C == 1:
                with jax.named_scope("dedalus/transpose/all_to_all"):
                    moved = a2a(block)
                return transform(moved)
            outs = []
            with jax.named_scope("dedalus/transpose/all_to_all"):
                arrived = a2a(_take_strided_chunk(block, axis_out, n, C, 0))
            for k in range(1, C):
                # comm for chunk k rides under compute for chunk k-1
                with jax.named_scope("dedalus/transpose/all_to_all"):
                    in_flight = a2a(
                        _take_strided_chunk(block, axis_out, n, C, k))
                outs.append(transform(arrived))
                arrived = in_flight
            outs.append(transform(arrived))
            return jnp.concatenate(outs, axis=axis_out)
        finally:
            meshctx.restore_walk(prev)

    with jax.named_scope("dedalus/transpose/overlapped_stage"):
        return _stage(data)


def overlapped_to_coeff_stage(data, transform, axis_in, axis_out, mesh,
                              axis_name, layout=None, chunks=1):
    """
    One to_coeff walk stage: local forward `transform` along axis_out
    followed by the all_to_all transpose (axis_in -> axis_out), chunked
    along the SOURCE per-device block of axis_in so each chunk's
    collective is issued while the NEXT chunk is still transforming.
    Received chunks arrive source-device-major; the final local reshape
    restores canonical global order, so the chunked stage is bit-identical
    data movement around batch-slab-invariant transforms. One shard_map,
    explicit sharding throughout.
    """
    layout = dict(layout or {})
    n = mesh.shape[axis_name]
    if data.shape[axis_in] % n:
        raise ValueError(
            f"axis_in {axis_in} (size {data.shape[axis_in]}) must be "
            f"divisible by mesh axis {axis_name!r} (size {n}); a "
            f"non-divisible axis_in would mis-shape the tiled "
            f"all_to_all blocks.")
    B = data.shape[axis_in] // n
    C = stage_chunks(chunks, B)
    in_spec = [layout.get(d) for d in range(data.ndim)]
    out_spec = list(in_spec)
    in_spec[axis_in] = axis_name
    out_spec[axis_out] = axis_name
    meshctx = _suspend_walk()

    def a2a(piece):
        # the transform ran first, so axis_out now carries the coeff
        # size: validate it divides before the exchange mis-shapes
        if piece.shape[axis_out] % n:
            raise ValueError(
                f"axis_out {axis_out} (transformed size "
                f"{piece.shape[axis_out]}) must be divisible by mesh "
                f"axis {axis_name!r} (size {n}); a non-divisible "
                f"axis_out would mis-shape the tiled all_to_all blocks.")
        return lax.all_to_all(piece, axis_name, split_axis=axis_out,
                              concat_axis=axis_in, tiled=True)

    @partial(shard_map, mesh=mesh, in_specs=P(*in_spec),
             out_specs=P(*out_spec))
    def _stage(block):
        prev = meshctx.set_walk(None, {})
        try:
            if C == 1:
                moved = transform(block)
                with jax.named_scope("dedalus/transpose/all_to_all"):
                    return a2a(moved)
            sub = B // C
            pieces = [lax.slice_in_dim(block, k * sub, (k + 1) * sub,
                                       axis=axis_in)
                      for k in range(C)]
            outs = []
            pending = transform(pieces[0])
            for k in range(1, C):
                # comm for chunk k-1 rides under compute for chunk k
                with jax.named_scope("dedalus/transpose/all_to_all"):
                    outs.append(a2a(pending))
                pending = transform(pieces[k])
            with jax.named_scope("dedalus/transpose/all_to_all"):
                outs.append(a2a(pending))
            # reassemble canonical order along axis_in: each chunk came
            # back source-device-major (n, sub); interleave chunks back
            # into each source block with one local reshape
            shp = outs[0].shape
            resh = [o.reshape(shp[:axis_in] + (n, sub) + shp[axis_in + 1:])
                    for o in outs]
            stacked = jnp.stack(resh, axis=axis_in + 1)   # (n, C, sub)
            return stacked.reshape(shp[:axis_in] + (n * C * sub,)
                                   + shp[axis_in + 1:])
        finally:
            meshctx.restore_walk(prev)

    with jax.named_scope("dedalus/transpose/overlapped_stage"):
        return _stage(data)


class DistributedPencilPipeline:
    """
    Distributed full-coefficient <-> full-grid transform pipeline for a
    D-dimensional domain over an R-dimensional device mesh (R < D): mesh
    axis r shards array dim r in coefficient space and array dim r+1 in
    grid space (the reference's block "pencil" decomposition,
    core/distributor.py:59-74).

    to_grid walk (mirroring the reference layout chain, :128-166):
      for axis = D-1 .. R:  local backward transform      [Transform]
      for r   = R-1 .. 0:   chunked all_to_all mesh axis r: dim r -> r+1
                            interleaved with the local backward transform
                            of dim r                [Transpose||Transform]
    to_coeff reverses the walk. Each transpose+transform stage is an
    overlapped chunked stage (see module docstring): `chunks` sub-block
    exchanges per stage, each riding under the neighboring chunk's
    transform, inside one shard_map per stage. `chunks=None` resolves
    `[distributed] TRANSPOSE_CHUNKS` once at construction; `chunks=1`
    reproduces the monolithic walk (and the chunked walk reproduces it
    bit-for-bit). Tensor components (leading dims) are never distributed.
    """

    def __init__(self, domain, mesh, axis_names=None, chunks=None):
        self.domain = domain
        self.mesh = mesh
        if isinstance(axis_names, str):
            axis_names = (axis_names,)
        self.axis_names = tuple(axis_names or mesh.axis_names)
        self.R = len(self.axis_names)
        self.D = domain.dim
        self.chunks = resolve_transpose_chunks(chunks)
        if self.R >= self.D:
            raise ValueError(f"Mesh rank {self.R} must be below the domain "
                             f"dimension {self.D}.")
        for axis in range(self.D):
            if domain.bases[axis] is None:
                raise ValueError("Pipeline requires a basis on every axis.")

    def _transform(self, data, axis, scales, tensorsig, forward):
        basis = self.domain.bases[axis]
        fn = basis.forward_transform if forward else basis.backward_transform
        return fn(data, len(tensorsig) + axis, scales[axis],
                  tensorsig=tensorsig, sub_axis=axis - basis.first_axis)

    def _constrain(self, data, layout):
        """Pin the stage sharding: fft ops are unpartitionable, so without
        explicit constraints GSPMD gathers at the first local transform
        after a transpose and the walk degrades to replicated."""
        spec = [layout.get(d) for d in range(data.ndim)]
        return jax.lax.with_sharding_constraint(
            data, NamedSharding(self.mesh, P(*spec)))

    def coeff_layout(self, tdim=0):
        """{array dim: mesh axis} for full-coefficient arrays."""
        return {tdim + r: self.axis_names[r] for r in range(self.R)}

    def grid_layout(self, tdim=0):
        """{array dim: mesh axis} for full-grid arrays."""
        return {tdim + r + 1: self.axis_names[r] for r in range(self.R)}

    def to_grid(self, cdata, scales=None, tensorsig=()):
        """Full coefficient -> full grid, sharded end-to-end. The current
        {dim: mesh axis} layout is published to core/meshctx so every
        local transform of the non-transposing phase routes its fft
        through shard_map (XLA cannot partition fft ops); each
        transpose+transform stage runs as one overlapped chunked
        shard_map with its sharding pinned on entry and exit."""
        from ..core import meshctx
        scales = scales or (1.0,) * self.D
        D, R = self.D, self.R
        tdim = len(tensorsig)
        layout = self.coeff_layout(tdim)
        prev = meshctx.set_walk(self.mesh, layout)
        try:
            out = self._constrain(cdata, layout)
            for axis in range(D - 1, R - 1, -1):
                out = self._transform(out, axis, scales, tensorsig,
                                      forward=False)
            for r in range(R - 1, -1, -1):
                del layout[tdim + r]
                out = overlapped_to_grid_stage(
                    out,
                    lambda x, _r=r: self._transform(x, _r, scales,
                                                    tensorsig,
                                                    forward=False),
                    tdim + r, tdim + r + 1, self.mesh, self.axis_names[r],
                    layout=layout, chunks=self.chunks)
                layout[tdim + r + 1] = self.axis_names[r]
                meshctx.set_walk(self.mesh, layout)
                out = self._constrain(out, layout)
            return out
        finally:
            meshctx.restore_walk(prev)

    def to_coeff(self, gdata, scales=None, tensorsig=()):
        """Full grid -> full coefficient, sharded end-to-end (see to_grid
        for the meshctx walk publication + per-stage pinning)."""
        from ..core import meshctx
        scales = scales or (1.0,) * self.D
        D, R = self.D, self.R
        tdim = len(tensorsig)
        layout = self.grid_layout(tdim)
        prev = meshctx.set_walk(self.mesh, layout)
        try:
            out = self._constrain(gdata, layout)
            for r in range(R):
                del layout[tdim + r + 1]
                out = overlapped_to_coeff_stage(
                    out,
                    lambda x, _r=r: self._transform(x, _r, scales,
                                                    tensorsig,
                                                    forward=True),
                    tdim + r + 1, tdim + r, self.mesh, self.axis_names[r],
                    layout=layout, chunks=self.chunks)
                layout[tdim + r] = self.axis_names[r]
                meshctx.set_walk(self.mesh, layout)
                out = self._constrain(out, layout)
            for axis in range(R, D):
                out = self._transform(out, axis, scales, tensorsig,
                                      forward=True)
            return self._constrain(out, layout)
        finally:
            meshctx.restore_walk(prev)
