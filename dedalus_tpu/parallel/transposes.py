"""
Pencil redistribution via lax.all_to_all inside shard_map
(reference: dedalus/core/transposes.pyx:22 FFTWTranspose / :246
AlltoallvTranspose — the hand-written MPI pack/unpack loops become one XLA
collective; the pack/unpack reshapes fuse into neighboring ops).

A D-dimensional state on an R-dimensional device mesh keeps the first R axes
block-distributed in coefficient space. Transforming an axis requires it to
be device-local, so the layout walk alternates local transforms with these
all-to-all transposes — exactly the reference's Transform/Transpose ladder
(core/distributor.py:128-166), but compiled: under jit, XLA schedules the
collective on the ICI and overlaps it with local compute where possible.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map


def all_to_all_transpose(data, axis_in, axis_out, mesh, axis_name):
    """
    Redistribute `data` from block-sharded along `axis_in` to block-sharded
    along `axis_out` (both global axis indices), preserving the global array.

    Equivalent to the reference's pencil transpose
    (core/transposes.pyx:336-355 Alltoallv + split/combine loops): each
    device exchanges tiles so that the formerly-distributed axis becomes
    local and vice versa.
    """
    n = mesh.shape[axis_name]
    if data.shape[axis_out] % n:
        raise ValueError(
            f"Axis {axis_out} (size {data.shape[axis_out]}) must divide the "
            f"mesh axis {axis_name!r} (size {n}).")
    in_spec = [None] * data.ndim
    in_spec[axis_in] = axis_name
    out_spec = [None] * data.ndim
    out_spec[axis_out] = axis_name

    @partial(shard_map, mesh=mesh, in_specs=P(*in_spec), out_specs=P(*out_spec))
    def _transpose(block):
        return lax.all_to_all(block, axis_name, split_axis=axis_out,
                              concat_axis=axis_in, tiled=True)

    return _transpose(data)


class DistributedPencilPipeline:
    """
    Distributed full-coefficient <-> full-grid transform pipeline for a
    2D separable-x-coupled domain (e.g. Fourier x Chebyshev), with the x
    axis block-distributed over a 1D mesh.

    Walk (mirroring the reference layout chain, core/distributor.py:128):
      coeff (kx sharded, z local)
        -> local z transform                       [Transform]
        -> all_to_all: shard z, localize kx        [Transpose]
        -> local x transform                       [Transform]
      grid (x local, z sharded)

    Each step is jnp inside one jit; the collective rides the ICI.
    """

    def __init__(self, domain, mesh, axis_name="x"):
        self.domain = domain
        self.mesh = mesh
        self.axis_name = axis_name
        if domain.dim != 2:
            raise NotImplementedError("Pipeline implemented for 2D domains.")
        self.xbasis, self.zbasis = domain.bases

    def to_grid(self, cdata, scales=(1.0, 1.0)):
        """Full coefficient -> full grid, sharded end-to-end."""
        domain = self.domain
        # z transform is local (axis 1 local while kx is sharded)
        out = self.zbasis.backward_transform(cdata, 1, scales[1])
        # kx -> x requires locality: transpose shards to the (larger) z axis
        out = all_to_all_transpose(out, 0, 1, self.mesh, self.axis_name)
        out = self.xbasis.backward_transform(out, 0, scales[0])
        return out

    def to_coeff(self, gdata, scales=(1.0, 1.0)):
        """Full grid -> full coefficient, sharded end-to-end."""
        out = self.xbasis.forward_transform(gdata, 0, scales[0])
        out = all_to_all_transpose(out, 1, 0, self.mesh, self.axis_name)
        out = self.zbasis.forward_transform(out, 1, scales[1])
        return out
