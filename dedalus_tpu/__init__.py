"""
Dedalus-TPU: a TPU-native spectral PDE framework.

A from-scratch JAX/XLA re-design of the capabilities of Dedalus v3
(reference: kburns/dedalus, surveyed in SURVEY.md): global spectral methods
for PDEs on Cartesian and curvilinear domains, symbolic vector equations,
IMEX initial value problems, boundary/eigenvalue problems — with the hot
path (transforms, pencil solves, distributed transposes) compiled by XLA
onto TPU (MXU matmuls, fused elementwise, mesh collectives) instead of
FFTW/MPI/SuperLU.

Architecture notes:
  * Symbolic problem layer runs on host (numpy/scipy), like the reference's
    (reference: dedalus/core/problems.py, operators.py).
  * The IVP step is ONE jitted function: spectral<->grid transforms,
    pointwise nonlinearities, and a batched dense/banded LU solve over all
    pencils (pencil index = batch dimension on the MXU).
  * Distribution uses jax.sharding.Mesh + named shardings; the reference's
    MPI Alltoallv pencil transposes (dedalus/core/transposes.pyx) become
    XLA-inserted all-to-alls.
"""

__version__ = "0.1.0"

# Double precision is the house dtype of spectral methods (the reference is
# float64/complex128 end-to-end). Enable x64 before any jax import users run.
import jax

jax.config.update("jax_enable_x64", True)

from .tools.logging import setup_logging

setup_logging()
