"""
Dedalus-TPU: a TPU-native spectral PDE framework.

A from-scratch JAX/XLA re-design of the capabilities of Dedalus v3
(reference: kburns/dedalus, surveyed in SURVEY.md): global spectral methods
for PDEs on Cartesian and curvilinear domains, symbolic vector equations,
IMEX initial value problems, boundary/eigenvalue problems — with the hot
path (transforms, pencil solves, distributed transposes) compiled by XLA
onto TPU (MXU matmuls, fused elementwise, mesh collectives) instead of
FFTW/MPI/SuperLU.

Architecture notes:
  * Symbolic problem layer runs on host (numpy/scipy), like the reference's
    (reference: dedalus/core/problems.py, operators.py).
  * The IVP step is ONE jitted function: spectral<->grid transforms,
    pointwise nonlinearities, and a batched dense/banded LU solve over all
    pencils (pencil index = batch dimension on the MXU).
  * Distribution uses jax.sharding.Mesh + named shardings; the reference's
    MPI Alltoallv pencil transposes (dedalus/core/transposes.pyx) become
    XLA-inserted all-to-alls.
"""

__version__ = "0.1.0"

# Double precision is the house dtype of spectral methods (the reference is
# float64/complex128 end-to-end). Enable x64 before any jax import users run.
import logging

import jax

jax.config.update("jax_enable_x64", True)

from .tools.logging import setup_logging

setup_logging()


def _setup_compilation_cache():
    """Enable the persistent XLA compilation cache (config [compilation]).

    Compiled step/factor programs are reused across runs and processes,
    cutting time-to-first-step on warm builds (cold RB 256x64 spends most
    of its build in XLA; see BENCHMARKS.md build-time breakdown)."""
    import os
    from .tools.config import config
    cache_dir = config["compilation"].get("CACHE_DIR", "").strip()
    if not cache_dir:
        return
    cache_dir = os.path.expanduser(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        min_secs = config["compilation"].getfloat("CACHE_MIN_COMPILE_SECS",
                                                  fallback=1.0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_secs)
        # cache regardless of entry size (large factor programs are the
        # expensive ones)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # enabling the dir comes LAST: a failure above must not leave the
        # cache active with unconfigured thresholds
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as exc:  # unwritable dir, older jax: run uncached
        try:
            jax.config.update("jax_compilation_cache_dir", "")
        except Exception:
            pass
        logging.getLogger(__name__).warning(
            f"persistent compilation cache disabled: {exc!r}")


_setup_compilation_cache()
