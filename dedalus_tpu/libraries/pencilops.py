"""
Structured batched pencil operators: the device-side representation of the
per-group LHS matrices and their factorization/solve algorithms.

The reference solves each pencil's sparse matrix with pivoted SuperLU on the
host (reference: dedalus/libraries/matsolvers.py:126-194, ScipyBanded :187,
Woodbury :285). The TPU-native equivalents here treat the pencil index G as
an MXU batch dimension and exploit structure instead of general sparsity:

  DenseOps  — (G, S, S) dense matrices; factor/solve delegate to the
              registered batched matsolvers (inverse / LU / refined).
  BandedOps — the mode-interleaved, matching-aligned permutation
              (core/subsystems.MatrixStructure) makes every true row
              banded; dense rows (BCs, gauges) are replaced by identity
              "pin" rows and restored by a rank-t Woodbury correction
              (reference Woodbury: libraries/matsolvers.py:285-316).
              Storage is (G, D, n) diagonals plus the pinned-row block
              Vt (G, t, n). The banded factorization is a blocked
              windowed-partial-pivoting LU (the batched analogue of
              LAPACK dgbtrf, reference matsolver ScipyBanded) over
              q-wide blocks via lax.scan; solves are two block
              substitution scans plus the t x t capacitance solve.
              Optional iterative-refinement sweeps polish the result
              using cheap banded matvecs.

All methods are pure jnp functions safe to trace inside jit; the structure
metadata (permutations, band offsets, block size, pin positions) is
host-static.
"""

import threading

import numpy as np
import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
from jax.sharding import PartitionSpec

from . import solvecomp
from .matsolvers import BatchedInverseRefined, get_solver, refined_ladder
from ..tools.compat import shard_map
from ..tools.config import config
from ..tools.array import zeropad


# ------------------------------------------------------- pencil-mesh routing
#
# XLA's SPMD partitioner cannot partition the pivoted-LU custom calls
# (lu_solve's pivot gather/scatter loop, triangular_solve): with the pencil
# batch sharded over a mesh, a plain jitted factor/solve lowers as
# all-gather + replicated full-batch solve — the exact failure local_fft
# (core/meshctx.py) guards against for ffts. The step bodies publish the
# active pencil mesh here at trace time; the batched dense factor/solve
# funnels below then run inside shard_map so each device factors/solves
# only its own group block. EnsembleSolver (core/ensemble.py) reuses the
# same routing with its member axis as the leading batch dimension.

_PENCIL_MESH = threading.local()


class pencil_mesh:
    """Trace-time context: batched factor/solve calls under this context
    run inside shard_map over the leading batch axis of `mesh`'s first
    axis (or `axis_name`). `mesh=None` INHERITS any active context (so
    an undistributed solver's factor/solve bodies traced inside an outer
    pencil context — the 2-D batch x pencil fleet, core/ensemble.py —
    keep the outer routing); with no outer context it is a no-op and
    unsharded traces compile identically to before."""

    def __init__(self, mesh, axis_name=None):
        self.inherit = mesh is None
        self.state = None if mesh is None else \
            (mesh, axis_name or mesh.axis_names[0])

    def __enter__(self):
        self.prev = getattr(_PENCIL_MESH, "state", None)
        if not self.inherit:
            _PENCIL_MESH.state = self.state
        return getattr(_PENCIL_MESH, "state", None)

    def __exit__(self, *exc):
        _PENCIL_MESH.state = self.prev


def active_pencil_mesh():
    return getattr(_PENCIL_MESH, "state", None)


# -------------------------------------------------- adjoint solve funnel
#
# The batched pivoted-LU solves are opaque to JAX's autodiff at the
# factorization boundary: the factors (aux) are precomputed OUTSIDE the
# differentiated program (they are value-dependent host dispatches), and
# letting autodiff transpose the solve's internals op-by-op would drag
# the substitution scans through linearization for no reason. The
# mathematical fact is simpler: x = A^-1 f is LINEAR in f, and the vjp
# of a linear solve is one more linear solve against the SAME matrix,
# transposed. Every ops.solve therefore routes through one
# jax.custom_vjp whose backward pass is `solve_transpose` — an adjoint
# solve reusing the cached LHS factors (core/adjoint.py is the
# consumer; the primal lowering is unchanged, so forward-only stepping
# compiles exactly as before).
#
# Factors and matrices receive ZERO cotangents: gradients w.r.t. the
# M/L assembly data are not implemented (the factorization is outside
# the trace; see docs/differentiable.md for the contract).

def _zeros_like_tree(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def _adjoint_solve_primal(ops, aux, rhs, mats):
    return ops._solve_impl(aux, rhs, mats)


_adjoint_solve = jax.custom_vjp(_adjoint_solve_primal, nondiff_argnums=(0,))


def _adjoint_solve_fwd(ops, aux, rhs, mats):
    # residuals are references to the already-resident factor buffers,
    # never copies — the backward solve reuses them in place
    return ops._solve_impl(aux, rhs, mats), (aux, mats)


def _adjoint_solve_bwd(ops, res, ct):
    aux, mats = res
    ct_rhs = ops.solve_transpose(aux, ct, mats=mats)
    return (_zeros_like_tree(aux), ct_rhs, _zeros_like_tree(mats))


_adjoint_solve.defvjp(_adjoint_solve_fwd, _adjoint_solve_bwd)


class AdjointSolveOps:
    """Shared solve surface of the pencil-ops classes: the public `solve`
    is the custom-VJP funnel above; `solve_transpose` is its backward
    pass (and a public API in its own right — data assimilation codes
    want A^T solves against the forward factorization)."""

    def solve(self, aux, rhs, mats=None):
        """Solve A x = rhs against the cached factorization. Linear in
        `rhs` with a registered custom VJP: the backward pass is
        `solve_transpose` against the same factors, and aux/mats get
        zero cotangents (M/L data is not differentiable)."""
        return _adjoint_solve(self, aux, rhs, mats)

    def solve_transpose(self, aux, rhs, mats=None):
        """Solve A^T x = rhs against the SAME factorization: the solve
        is linear in its RHS, so its transpose re-expresses the compiled
        substitution chain transposed — triangular solves against the
        transposed factors, run in reverse order, plus the transposed
        Woodbury/refinement corrections — without ever refactoring (the
        adjoint of a linear solve is a linear solve with the same
        matrix). Routed through jax.vjp rather than jax.linear_transpose
        because raw `lax.scan` equations (the blocked banded
        substitutions) carry no linearity flags for the direct transpose
        rule; linearizing first marks them. The linearization point is
        zeros, so every primal-side value is a DCE-able constant and the
        compiled backward contains just the transposed solve."""
        # the experimental Pallas substitution is not differentiable
        # (jax.vjp cannot trace through pallas_call): transpose against
        # the XLA-scan fused path instead — identical linear algebra on
        # the same precomposed operators, so the adjoint contract holds
        # under every [fusion] composition
        pallas = getattr(self, "_pallas", False)
        self._pallas = False
        try:
            with jax.named_scope(f"dedalus/matsolve/{self.kind}.solve_T"):
                _, f_vjp = jax.vjp(
                    lambda r: self._solve_impl(aux, r, mats),
                    jnp.zeros_like(rhs))
                (out,) = f_vjp(rhs)
                return out
        finally:
            self._pallas = pallas


def shard_groups(fn, G, *args):
    """
    Run `fn(*args)` with the length-G leading batch axis sharded over the
    active pencil mesh (each device computes its local block; zero
    collectives inside). Falls back to a direct call when no mesh context
    is active, G does not divide the mesh axis, or any array leaf does not
    lead with the batch axis (e.g. the chunked banded factor slabs, whose
    leading dim is the chunk count — those rely on GSPMD propagation).
    Scalar leaves ride along replicated.
    """
    state = active_pencil_mesh()
    if state is None:
        return fn(*args)
    mesh, name = state
    if G % mesh.shape[name]:
        return fn(*args)
    spec = PartitionSpec(name)

    def spec_of(leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 0:
            return PartitionSpec()
        return spec if leaf.shape[0] == G else None

    in_specs = jax.tree.map(spec_of, args)
    if any(s is None for s in jax.tree.leaves(
            in_specs, is_leaf=lambda x: x is None)):
        return fn(*args)
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=spec)(*args)


class DenseOps(AdjointSolveOps):
    """Dense (G, S, S) pencil operators (small problems / fallback)."""

    kind = "dense"

    def __init__(self, matsolver=None, solve_plan=None):
        # solve-composition/precision plan: callers in a solver build
        # pass the plan the solver resolved ONCE (solver._solve_plan);
        # standalone constructions resolve fresh. The scan compositions
        # are inert on the dense path (there is no substitution scan to
        # restructure — accepted as no-ops so one [fusion] config drives
        # mixed dense/banded fleets); the precision ladder routes the
        # solve through the refined low-dtype inverse + f64 residual
        # polish (matsolvers.refined_ladder). The bare-ops fallback goes
        # through the TUNER-AWARE resolver (dense ops carry no system
        # size at construction, so 0 = "no registered shape"): a bare
        # build and a solver build must never silently pick different
        # plans for the same shape (tools/autotune.py).
        if solve_plan is None:
            solve_plan = solvecomp.resolve_solve_plan_for_ops("dense", 0)
        self._solve_plan = solve_plan
        self._composition = "sequential"
        if solve_plan.dtype != "native":
            self.solver_cls = refined_ladder(solve_plan)
        else:
            self.solver_cls = get_solver(matsolver)

    def to_device(self, host_mat, dtype):
        return jnp.asarray(host_mat, dtype=dtype)

    def matvec(self, A, X):
        with jax.named_scope("dedalus/matsolve/dense.matvec"):
            return jnp.einsum("gij,gj->gi", A, X)

    def matvec_pair(self, M, L, X):
        """(M @ X, L @ X) — the fused-step pair surface (core/fusedstep).
        Dense matvecs share nothing to factor out, so this is the two
        einsums (bitwise identical to separate calls by construction)."""
        with jax.named_scope("dedalus/matsolve/dense.matvec_pair"):
            return (jnp.einsum("gij,gj->gi", M, X),
                    jnp.einsum("gij,gj->gi", L, X))

    def lincomb(self, a, A, b, B):
        return a * A + b * B

    def scale(self, a, A):
        return a * A

    def factor(self, A):
        with jax.named_scope("dedalus/matsolve/dense.factor"):
            return shard_groups(self.solver_cls.factor, A.shape[0], A)

    def factor_lincomb(self, a, A, b, B):
        return self.factor(self.lincomb(a, A, b, B))

    def _solve_impl(self, aux, rhs, mats=None):
        with jax.named_scope("dedalus/matsolve/dense.solve"):
            return shard_groups(self.solver_cls.solve, rhs.shape[0],
                                aux, rhs)

    def solve_report(self, aux, rhs, mats=None):
        """Diagnostic solve + achieved relative residual as a device
        scalar (None when this aux carries no reconstructible matrix) —
        the flush-time `precision` telemetry probe and the benchmark
        accuracy rows. Never called on the step path."""
        x = self.solve(aux, rhs, mats=mats)
        if not (isinstance(self.solver_cls, type)
                and issubclass(self.solver_cls, BatchedInverseRefined)):
            return x, None
        return x, jnp.max(self.solver_cls.residual(aux, x, rhs))

    def densify_host(self, host_mat, g):
        return np.asarray(host_mat[g])


@jax.tree_util.register_pytree_node_class
class BandedMatrix:
    """
    One pencil matrix in trimmed banded + pinned-row storage: only the
    structurally nonzero diagonals are kept (`dsel` maps stored rows to the
    shared 0..nd-1 diagonal lattice), and an all-zero pinned-row block is
    dropped entirely. The mass matrix M typically occupies a few diagonals
    of the lattice the stiffness L defines, so trimming cuts both storage
    and matvec work.
    """

    def __init__(self, bands, Vt, dsel):
        self.bands = bands    # (G, len(dsel), n_store) — ASSEMBLED width
        self.Vt = Vt          # (G, t, n_store) or None
        self.dsel = tuple(int(d) for d in dsel)

    def tree_flatten(self):
        return (self.bands, self.Vt), self.dsel

    @classmethod
    def tree_unflatten(cls, dsel, children):
        bands, Vt = children
        return cls(bands, Vt, dsel)


class BandedOps(AdjointSolveOps):
    """
    Banded + pinned-row pencil operators.

    Host representation per matrix name (core/subsystems.build_banded_arrays):
        bands : (G, D, n_store)  diagonals of the matched (true-banded)
                rows, offsets -kl..ku; bands[g, d, p] = A'[g, p, p+d-kl].
                n_store is the ASSEMBLED width (structural NB*q); factor
                transients and solves run at the re-blocked width n_pad
                >= n_store when BANDED_MIN_Q raises q.
        Vt    : (G, t, n_store)  true content of the pinned rows

    with A' the row/column-permuted matrix. The represented matrix is
    A' = B + sum_i e_{p_i} Vt_i^T where B carries zero rows at the pin
    positions. Factorization pins those rows (B~ = B + sum_i e_{p_i}
    e_{p_i}^T, well-conditioned: pins constrain the coefficients the
    boundary rows would otherwise leave free) and applies Woodbury:
        A'^-1 = B~^-1 - B~^-1 E (I + (Vt - E^T) B~^-1 E)^-1 (Vt - E^T) B~^-1
    """

    kind = "banded"

    def __init__(self, structure, refine=1, fusion=None, solve_plan=None):
        st = structure
        # Structures arrive either freshly finalized or rehydrated from
        # the persistent assembly cache (MatrixStructure.from_state);
        # validate the contract HERE so a drifted/hand-edited cache
        # payload fails with a clear message instead of an AttributeError
        # deep inside a factorization scan.
        missing = [attr for attr in
                   ("S", "NB", "q", "t_pins", "kl", "ku", "row_perm",
                    "col_perm", "pinned_positions")
                   if getattr(st, attr, None) is None]
        if missing:
            raise ValueError(
                f"BandedOps: structure is missing {missing} (corrupt or "
                f"stale assembly-cache payload?)")
        self.st = st
        self.refine = int(refine)
        # fused-step switches: callers in a solver build pass the plan
        # the solver resolved ONCE (solver._fusion_plan) so mid-build
        # config edits can never split one solver across two
        # compositions; standalone constructions resolve fresh.
        # FUSED_SOLVE engages on the factor_lincomb paths only (the IVP
        # step loop, where the factor-time inversion cost is amortized);
        # plain factor() keeps the backward-stable pivoted substitution
        # for the one-factor-one-solve solver classes.
        if fusion is None:
            from ..core.fusedstep import resolve_fusion
            fusion = resolve_fusion()
        plan = fusion
        self._fused_solve = plan.solve
        self._fused_matvec = plan.matvec
        self._pallas = plan.pallas
        # solve-composition/precision plan (libraries/solvecomp.py):
        # like `fusion`, resolved once per solver build and passed in so
        # a mid-build config edit can never split one solver across two
        # compositions; the plan token rides the assembly/pool keys.
        # The bare-ops fallback goes through the TUNER-AWARE resolver
        # keyed on this structure's system size, so a bare BandedOps and
        # a tuned solver build can never silently pick different plans
        # for the same shape (tools/autotune.py).
        if solve_plan is None:
            solve_plan = solvecomp.resolve_solve_plan_for_ops(
                "banded", structure.S)
        self._solve_plan = solve_plan
        if solve_plan.composition != "sequential" and not plan.solve:
            raise ValueError(
                f"[fusion] SOLVE_COMPOSITION = {solve_plan.composition} "
                "requires FUSED_SOLVE: the restructured sweeps run over "
                "the precomposed FwdOp/BwdOp GEMM operators")
        if self._pallas and solve_plan.composition != "sequential":
            raise ValueError(
                "[fusion] PALLAS covers the sequential substitution "
                f"only; SOLVE_COMPOSITION = {solve_plan.composition} "
                "already removes the per-block-row HBM round-trips the "
                "kernel exists to avoid")
        self._composition = solve_plan.composition if plan.solve \
            else "sequential"
        self._spike_chunks_cfg = solve_plan.spike_chunks
        self._ladder = solve_plan.dtype != "native"
        # refinement schedule: explicit [precision] sweeps win; None
        # defers to the legacy `refine` count (the PR-12 fused tolerance
        # class is calibrated against it)
        self._refine_sweeps = solve_plan.sweeps
        self._refine_tol = solve_plan.tol
        # pencil-batch chunking (lax.map over G-chunks): bounds the
        # factorization's HLO temp footprint AND forces the scan-stacked
        # factor outputs into flat (Gc, 2q*q) layouts that tile (8, 128)
        # cleanly — full-G factors otherwise materialize as 4-D
        # (NB, G, 2q, q) buffers whose q-sized minor dims pad 2-4x on TPU.
        # Chosen at factor time (needs G and the dtype); solve re-derives
        # the count from the aux's shapes — this attr is diagnostic only.
        self._g_chunks = 1
        # Re-blocking: the factorization/solve scans run NB sequential
        # steps; on TPU each step is latency-bound, so BANDED_MIN_Q
        # re-blocks the SAME banded lattice with larger q (fewer, fatter
        # scan steps feeding the MXU). The band STORAGE keeps its
        # assembled width (n_store); factor transients pad to the
        # re-blocked width. q only has to satisfy kl, ku <= q, which
        # growing q preserves. 'auto' grows q by doubling on TPU backends
        # while the per-factor slab stays under BANDED_Q_BUDGET_GB (a
        # system already over budget — e.g. the north-star RB 2048x1024 —
        # keeps its structural q); the final q is chosen at first factor,
        # when the group count is known (_ensure_q).
        self._min_q_cfg = config["linear algebra"].get(
            "BANDED_MIN_Q", "0").strip().lower()
        self.n = st.S                  # true system size
        self.n_store = st.NB * st.q    # band-array width as assembled
        self.t = st.t_pins
        self.kl = st.kl
        self.ku = st.ku
        self.nd = st.kl + st.ku + 1    # number of stored diagonals
        # static permutation index arrays
        self.row_perm = np.asarray(st.row_perm)   # permuted pos -> orig index
        self.col_perm = np.asarray(st.col_perm)
        self.pos_col = np.argsort(self.col_perm)  # orig index -> permuted pos
        self.pin_pos = np.asarray(st.pinned_positions)
        self._set_q(st.q if self._min_q_cfg in ("0", "auto", "")
                    else max(st.q, int(self._min_q_cfg)))

    def _set_q(self, q):
        """(Re)derive the blocking-dependent geometry for block size q."""
        self.q = int(q)
        self.n_pad = -(-self.n_store // self.q) * self.q
        self.NB = self.n_pad // self.q
        # static block-gather indices: block[o][ri, ci] reads
        # bands[:, o*q + ci - ri + kl, block_row*q + ri]
        ri = np.arange(self.q)[:, None]
        ci = np.arange(self.q)[None, :]
        self._blk_idx = {}
        for o in (-1, 0, 1):
            d = o * self.q + ci - ri + self.kl       # (q, q)
            valid = (d >= 0) & (d < self.nd)
            self._blk_idx[o] = (np.where(valid, d, 0), valid)

    def _ensure_q(self, G, itemsize):
        """Finalize the re-blocking once the group count is known (first
        factor): 'auto' doubles q while the persistent factor slab
        (panelLU + U12, 2 * 2q*q per block row) stays under
        BANDED_Q_BUDGET_GB and q <= 256, on TPU backends only."""
        if self._min_q_cfg != "auto":
            return
        import jax
        if jax.default_backend() not in ("tpu", "axon"):
            return
        budget = float(config["linear algebra"].get(
            "BANDED_Q_BUDGET_GB", "2.0")) * 1e9

        def slab_bytes(q):
            nb = -(-self.n_store // q)
            return G * nb * (2 * q * q) * 2 * itemsize

        q = self.q
        while (2 * q <= 256 and slab_bytes(2 * q) <= budget
               and 2 * q < self.n_store):
            q *= 2
        if q != self.q:
            self._set_q(q)

    # ------------------------------------------------------------ host side

    def to_device(self, host_arrs, dtype):
        """Host band store -> trimmed BandedMatrix. Accepts pre-trimmed
        storage (a "dsel" key, the assembly fast path) or a full
        (G, nd, n_pad) lattice, trimmed here."""
        bands = host_arrs["bands"]
        Vt = host_arrs["Vt"]
        if "dsel" in host_arrs:
            dsel = list(host_arrs["dsel"])
            trimmed = jnp.asarray(bands, dtype=dtype)
        else:
            dsel = [d for d in range(self.nd) if np.any(bands[:, d, :])]
            if not dsel:
                dsel = [self.kl]
            # fancy-index slice is already a fresh contiguous array
            trimmed = jnp.asarray(bands[:, dsel, :], dtype=dtype)
        Vt_dev = None
        if self.t and np.any(Vt):
            Vt_dev = jnp.asarray(Vt, dtype=dtype)
        return BandedMatrix(trimmed, Vt_dev, dsel)

    def densify_host(self, host_arrs, g):
        """Reconstruct the original-ordering dense (S, S) matrix (host)."""
        S = self.n
        W = host_arrs["bands"].shape[-1]
        Ap = np.zeros((W, W), dtype=host_arrs["bands"].dtype)
        bands = host_arrs["bands"][g]
        dsel = host_arrs.get("dsel", range(self.nd))
        for i, d in enumerate(dsel):
            off = d - self.kl
            rr = np.arange(max(0, -off), min(W, W - off))
            Ap[rr, rr + off] = bands[i, rr]
        if self.t:
            Ap[self.pin_pos, :] += host_arrs["Vt"][g]
        Ap = Ap[:S, :S]
        # un-permute: Ap[i, j] = A[row_perm[i], col_perm[j]]
        A = np.zeros_like(Ap)
        A[np.ix_(self.row_perm, self.col_perm)] = Ap
        return A

    # ----------------------------------------------------------- device ops

    def expand(self, A, a=1.0):
        """Trimmed BandedMatrix -> full-lattice (bands (G, nd, n_pad),
        Vt (G, t, n_pad)) scaled by `a` (factorization transient)."""
        G = A.bands.shape[0]
        dtype = A.bands.dtype
        full = jnp.zeros((G, self.nd, self.n_pad), dtype=dtype)
        full = full.at[:, np.asarray(A.dsel), :self.n_store].set(a * A.bands)
        Vt = jnp.zeros((G, self.t, self.n_pad), dtype=dtype)
        if self.t and A.Vt is not None:
            Vt = Vt.at[:, :, :self.n_store].set(a * A.Vt)
        return full, Vt

    def _band_mv(self, bands, dsel, x):
        """y[g, p] = sum_{d in dsel} bands[g, i, p] * x[g, p + d - kl];
        width follows the band ARRAY (assembled storage, not the
        re-blocked factor width)."""
        width = bands.shape[-1]
        xpad = zeropad(x, ((0, 0), (self.kl, self.ku)))
        y = jnp.zeros_like(x)
        for i, d in enumerate(dsel):
            y = y + bands[:, i, :] * jax.lax.slice_in_dim(
                xpad, d, d + width, axis=1)
        return y

    def matvec(self, A, X):
        """Full A @ X in the ORIGINAL slot ordering; X (G, S)."""
        with jax.named_scope("dedalus/matsolve/banded.matvec"):
            xp = X[:, self.col_perm]
            xp = zeropad(xp, ((0, 0), (0, A.bands.shape[-1] - self.n)))
            yp = self._band_mv(A.bands, A.dsel, xp)
            if self.t and A.Vt is not None:
                pin_vals = jnp.einsum("gtn,gn->gt", A.Vt, xp)
                yp = yp.at[:, self.pin_pos].add(pin_vals)
            # yp[p] = (A @ X)[row_perm[p]]
            out = jnp.zeros_like(X)
            return out.at[:, self.row_perm].set(yp[:, :self.n])

    def matvec_pair(self, M, L, X):
        """(M @ X, L @ X) in ONE pass over the operand: the fused-step
        pair surface (core/fusedstep.py). The column permutation, pad,
        pin einsums and row scatter run once over a shared padded X; each
        matrix keeps its own trimmed diagonal loop, so both outputs are
        BITWISE identical to separate `matvec` calls."""
        with jax.named_scope("dedalus/matsolve/banded.matvec_pair"):
            width = M.bands.shape[-1]
            xp = X[:, self.col_perm]
            xp = zeropad(xp, ((0, 0), (0, width - self.n)))
            outs = []
            for A in (M, L):
                yp = self._band_mv(A.bands, A.dsel, xp)
                if self.t and A.Vt is not None:
                    pin_vals = jnp.einsum("gtn,gn->gt", A.Vt, xp)
                    yp = yp.at[:, self.pin_pos].add(pin_vals)
                out = jnp.zeros_like(X)
                outs.append(out.at[:, self.row_perm].set(yp[:, :self.n]))
            return tuple(outs)

    def _chunk_blocks(self, chunk):
        """One block-row's (G, D, q) band chunk -> (diag, left, right) blocks
        ((i, i), (i, i-1), (i, i+1)); avoids materializing the full block
        tridiagonal (3 extra (G, NB, q, q) arrays) during factorization."""
        q = self.q
        ri = np.broadcast_to(np.arange(q)[:, None], (q, q))
        out = {}
        for o in (-1, 0, 1):
            d, valid = self._blk_idx[o]                      # (q, q)
            blk = chunk[:, d, ri] * jnp.asarray(valid, dtype=chunk.dtype)
            out[o] = blk
        return out[0], out[-1], out[1]

    def _factor_interior(self, bands):
        """
        Blocked banded LU with windowed partial pivoting (the batched-TPU
        analogue of LAPACK dgbtrf, reference matsolver ScipyBanded:
        libraries/matsolvers.py:187): at block column i the (2q x q) panel
        [S_i; Lo_i] is factored with row pivoting (pivots confined to the
        window, exactly LAPACK's banded pivot range for kl <= q), the
        permutation + elimination are applied to the (2q x 2q) trailing
        window, and the upper fill (bandwidth ku + kl <= 2q) is stored in
        a (q x 2q) U12 block per step. Unconditionally stable where the
        no-pivot block elimination breaks on constraint rows.

        Factors are stored LAPACK-packed — the raw (2q x q) panel LU holds
        L1 (unit-lower), U11 (upper) and L2 in one array — halving
        persistent factor memory vs separate L1/L2/U11 blocks.

        Returns aux tuple (perms, panelLU, U12, lastP, lastLU).
        """
        G = bands.shape[0]
        q, NB = self.q, self.NB
        dtype = bands.dtype
        if NB == 1:
            Dg0, _, _ = self._chunk_blocks(bands)
            lu, _, perm = jax.lax.linalg.lu(Dg0)
            return (None, None, None, perm, lu)

        eye_q = jnp.eye(q, dtype=dtype)
        zero_qq = jnp.zeros((G, q, q), dtype=dtype)

        # All arrays entering/leaving the scan are flattened to (G, flat):
        # TPU tiles the two minor dims to (8, 128), so stacked (steps, G, q,
        # q)-shaped arrays with q ~ 32 pay 4-8x padding; (steps, G, q*q)
        # tiles cleanly. The scan consumes the band storage directly as
        # per-block-row chunks (one (G, D, q) slab per step) instead of a
        # pre-materialized block tridiagonal.
        nd = self.nd

        def step(carry, chunk_flat):
            A11, A12 = carry              # (G,q,q), (G,q,2q): cols i+1, i+2
            D_n, Lo_i, Up_n = self._chunk_blocks(
                chunk_flat.reshape(G, nd, q))
            panel = jnp.concatenate([A11, Lo_i], axis=1)          # (G,2q,q)
            lu, _, perm = jax.lax.linalg.lu(panel)
            L1 = jnp.tril(lu[:, :q, :], -1) + eye_q               # (G,q,q)
            L2 = lu[:, q:, :]                                     # (G,q,q)
            T = jnp.concatenate(
                [A12, jnp.concatenate([D_n, Up_n], axis=2)], axis=1)  # (G,2q,2q)
            T = jnp.take_along_axis(T, perm[:, :, None], axis=1)
            U12 = jsl.solve_triangular(L1, T[:, :q, :], lower=True,
                                       unit_diagonal=True)        # (G,q,2q)
            Tn = T[:, q:, :] - L2 @ U12                           # (G,q,2q)
            carry = (Tn[:, :, :q],
                     jnp.concatenate([Tn[:, :, q:], zero_qq], axis=2))
            return carry, (perm, lu.reshape(G, 2 * q * q),
                           U12.reshape(G, 2 * q * q))

        chunks = jnp.moveaxis(bands.reshape(G, nd, NB, q), 2, 0)  # (NB,G,nd,q)
        chunks = chunks.reshape(NB, G, nd * q)
        Dg0, _, Up0 = self._chunk_blocks(chunks[0].reshape(G, nd, q))
        A12_0 = jnp.concatenate([Up0, zero_qq], axis=2)
        (A11_f, _), (perms, panelLU, U12) = jax.lax.scan(
            step, (Dg0, A12_0), chunks[1:])
        lu, _, lastP = jax.lax.linalg.lu(A11_f)
        return (perms, panelLU, U12, lastP, lu)

    def _precompose_subst(self, interior):
        """Precomposed matmul-substitution operators (FUSED_SOLVE,
        core/fusedstep.py). At factor time each panel's unit-lower and
        upper blocks are inverted (one batched triangular solve against
        the identity over every block row at once) and FOLDED with the
        window permutation and the elimination update into per-step
        GEMM operators:

            fwd:  [y_i; w_next] = FwdOp_i @ [w; f_{i+1}]
                  FwdOp_i = [[L1inv P_top], [P_bot - L2 L1inv P_top]]
            bwd:  x_i = BwdOp_i @ [y_i; x_{i+1}; x_{i+2}]
                  BwdOp_i = [U11inv | -U11inv U12]
            last: x = lastOp @ w,  lastOp = U^-1 L^-1 P

        so every substitution scan step is ONE batched (2q, 2q)-class
        matmul — no triangular-solve custom calls, no gathers, no
        separate elimination update (measured ~19x per triangular solve
        and ~2x per scan step in op overhead on CPU; the TPU dense
        path's BatchedInverse principle applied to the banded factors).
        The substitution result moves off the backward-stable sweep by
        ~eps*cond(block); the refinement polish (refine >= 1) drives the
        final residual back to the unfused level — the documented
        fused-vs-unfused tolerance (tests/test_fusion.py)."""
        perms, panelLU, U12, lastP, lastLU = interior
        q = self.q
        dtype = lastLU.dtype
        eye = jnp.eye(q, dtype=dtype)

        def inv_lower(lu):
            L1 = jnp.tril(lu, -1) + eye
            return jsl.solve_triangular(
                L1, jnp.broadcast_to(eye, L1.shape), lower=True,
                unit_diagonal=True)

        def inv_upper(lu):
            return jsl.solve_triangular(
                jnp.triu(lu), jnp.broadcast_to(eye, lu.shape), lower=False)

        # last block: A^-1 P = U^-1 L^-1 P composed once (perm folded)
        lastPmat = jax.nn.one_hot(lastP, q, dtype=dtype, axis=-1)
        fsub = {"lastOp": inv_upper(lastLU) @ inv_lower(lastLU) @ lastPmat}
        if panelLU is not None:
            steps, G = panelLU.shape[:2]
            lu = panelLU.reshape(steps * G, 2 * q, q)
            L1inv = inv_lower(lu[:, :q, :])
            U11inv = inv_upper(lu[:, :q, :])
            Pmat = jax.nn.one_hot(perms.reshape(steps * G, 2 * q), 2 * q,
                                  dtype=dtype, axis=-1)
            top = L1inv @ Pmat[:, :q, :]                      # (., q, 2q)
            bot = Pmat[:, q:, :] - lu[:, q:, :] @ top
            fwd_op = jnp.concatenate([top, bot], axis=1)      # (., 2q, 2q)
            bwd_op = jnp.concatenate(
                [U11inv, -(U11inv @ U12.reshape(steps * G, q, 2 * q))],
                axis=2)                                       # (., q, 3q)
            fsub["FwdOp"] = fwd_op.reshape(steps, G, 4 * q * q)
            fsub["BwdOp"] = bwd_op.reshape(steps, G, 3 * q * q)
        return fsub

    # ------------------------------- restructured substitutions (solvecomp)
    #
    # Both precomposed sweeps are affine recurrences over factor-time
    # operators: forward w_{i+1} = A_i w_i + B_i f_{i+1} with outputs
    # y_i = C_i w_i + D_i f_{i+1} ((A|B; C|D) = blocks of FwdOp), and
    # backward z_i = A'_i z_{i+1} + B'_i y_i over the stacked pair
    # z_i = [x_i; x_{i+1}] (A', B' built from BwdOp = [Y | P]:
    # x_i = Y_i y_i + P_i z_{i+1}). The [fusion] SOLVE_COMPOSITION knob
    # swaps the O(N)-depth lax.scan over these recurrences for the
    # log-depth parallel prefix (ascan) or the chunk-partitioned SPIKE
    # program (libraries/solvecomp.py has the depth/flops model).

    def _subst_fwd_system(self, fsub):
        """(A, B, C, D) of the forward sweep from the precomposed
        FwdOp blocks; state/input/output widths all q."""
        q = self.q
        steps, G = fsub["FwdOp"].shape[:2]
        op = fsub["FwdOp"].reshape(steps, G, 2 * q, 2 * q)
        return (op[:, :, q:, :q], op[:, :, q:, q:],
                op[:, :, :q, :q], op[:, :, :q, q:])

    def _subst_bwd_system(self, fsub):
        """(A', B', C', D') of the backward sweep, step-reversed into a
        forward recurrence over v_j = z_{NB-2-j}; state width 2q,
        input/output width q. The output row extracts x_i = z_i[:q]
        (the post-step state's top block: C' = P, D' = Y)."""
        q = self.q
        steps, G = fsub["BwdOp"].shape[:2]
        op = fsub["BwdOp"].reshape(steps, G, q, 3 * q)
        Yb = op[..., :q]                                  # acts on y_i
        Pb = op[..., q:]                                  # acts on z_{i+1}
        shift = jnp.broadcast_to(
            jnp.concatenate([jnp.eye(q, dtype=op.dtype),
                             jnp.zeros((q, q), dtype=op.dtype)], axis=1),
            (steps, G, q, 2 * q))                         # x_{i+1} carry row
        A = jnp.concatenate([Pb, shift], axis=2)[::-1]
        B = jnp.concatenate([Yb, jnp.zeros_like(Yb)], axis=2)[::-1]
        return A, B, Pb[::-1], Yb[::-1]

    def _attach_spike(self, fsub):
        """Factor-time SPIKE precomposition: fold the within-chunk
        transfer products of both sweeps into dense per-chunk GEMM
        operators (solvecomp.spike_precompose) and DROP FwdOp/BwdOp —
        the spike solve consumes only the chunk operators, so keeping
        the step-stacked forms would double the persistent factor
        store. Degenerate step counts (too few steps to chunk) keep the
        sequential operators untouched."""
        n_steps = fsub["FwdOp"].shape[0]
        chunks = solvecomp.spike_chunk_count(n_steps, self._spike_chunks_cfg)
        if chunks <= 1:
            return
        fsub["spikeF"] = solvecomp.spike_precompose(
            *self._subst_fwd_system(fsub), chunks)
        fsub["spikeB"] = solvecomp.spike_precompose(
            *self._subst_bwd_system(fsub), chunks)
        del fsub["FwdOp"], fsub["BwdOp"]

    def _solve_interior_ascan(self, f, fsub):
        """Solve B~ x = f with both substitution sweeps as parallel
        prefixes over (A, b) pairs (lax.associative_scan, matmul
        combine): O(log NB) depth, no sequential scan in the lowered
        program (the DTP106 contract's ascan branch)."""
        G, _, k = f.shape
        q, NB = self.q, self.NB
        fb = jnp.moveaxis(f.reshape(G, NB, q, k), 1, 0)   # (NB, G, q, k)
        ys, w_f = solvecomp.ascan_apply(
            *self._subst_fwd_system(fsub), fb[1:], fb[0])
        x_last = fsub["lastOp"] @ w_f
        z0 = jnp.concatenate([x_last, jnp.zeros_like(x_last)], axis=1)
        outs, _ = solvecomp.ascan_apply(
            *self._subst_bwd_system(fsub), ys[::-1], z0)
        x = jnp.concatenate([outs[::-1], x_last[None]], axis=0)
        return jnp.moveaxis(x, 0, 1).reshape(G, self.n_pad, k)

    def _solve_interior_spike(self, f, fsub):
        """Solve B~ x = f against the factor-time SPIKE operators: each
        sweep is two batched GEMMs over all chunks plus the C-step
        reduced coupling scan (the DTP106 contract's spike branch)."""
        G, _, k = f.shape
        q, NB = self.q, self.NB
        fb = jnp.moveaxis(f.reshape(G, NB, q, k), 1, 0)
        ys, w_f = solvecomp.spike_apply(fsub["spikeF"], fb[1:], fb[0])
        x_last = fsub["lastOp"] @ w_f
        z0 = jnp.concatenate([x_last, jnp.zeros_like(x_last)], axis=1)
        outs, _ = solvecomp.spike_apply(fsub["spikeB"], ys[::-1], z0)
        x = jnp.concatenate([outs[::-1], x_last[None]], axis=0)
        return jnp.moveaxis(x, 0, 1).reshape(G, self.n_pad, k)

    def _solve_interior_fused(self, interior_aux, f, fsub):
        """Solve B~ x = f via the precomposed substitution operators: the
        same blocked sweeps as `_solve_interior`, each scan step one
        batched GEMM against the factor-time FwdOp/BwdOp."""
        G, _, k = f.shape
        q, NB = self.q, self.NB
        lastOp = fsub["lastOp"]
        fb = jnp.moveaxis(f.reshape(G, NB, q, k), 1, 0).reshape(NB, G, q * k)
        if NB == 1:
            x = lastOp @ fb[0].reshape(G, q, k)
            return jnp.moveaxis(x[None], 0, 1).reshape(G, self.n_pad, k)
        # restructured compositions (resolved once per build): spike
        # factors carry their chunk operators in the aux; ascan slices
        # the step-stacked operators at solve time
        if "spikeF" in fsub:
            return self._solve_interior_spike(f, fsub)
        if self._composition == "ascan":
            return self._solve_interior_ascan(f, fsub)

        def fwd(w_cur, xs):
            f_next, op_flat = xs
            wf = jnp.concatenate([w_cur, f_next.reshape(G, q, k)], axis=1)
            yw = op_flat.reshape(G, 2 * q, 2 * q) @ wf
            return yw[:, q:], yw[:, :q].reshape(G, q * k)

        w_f, ys = jax.lax.scan(fwd, fb[0].reshape(G, q, k),
                               (fb[1:], fsub["FwdOp"]))
        x_last = lastOp @ w_f
        zero = jnp.zeros_like(x_last)

        def bwd(carry, xs):
            x1, x2 = carry
            y_flat, op_flat = xs
            z = jnp.concatenate([y_flat.reshape(G, q, k), x1, x2], axis=1)
            x = op_flat.reshape(G, q, 3 * q) @ z
            return (x, x1), x.reshape(G, q * k)

        _, xs_rev = jax.lax.scan(bwd, (x_last, zero),
                                 (ys, fsub["BwdOp"]), reverse=True)
        x = jnp.concatenate([xs_rev.reshape(NB - 1, G, q, k),
                             x_last[None]], axis=0)
        return jnp.moveaxis(x, 0, 1).reshape(G, self.n_pad, k)

    def _solve_interior(self, interior_aux, f, fsub=None):
        """Solve B~ x = f for f (G, n_pad, k) via the pivoted block factors."""
        if fsub is not None:
            return self._solve_interior_fused(interior_aux, f, fsub)
        perms, panelLU, U12, lastP, lastLU = interior_aux
        G, _, k = f.shape
        q, NB = self.q, self.NB
        eye_q = jnp.eye(q, dtype=f.dtype)
        # flattened (steps, G, q*k) stacking: see _factor_interior layout note
        fb = jnp.moveaxis(f.reshape(G, NB, q, k), 1, 0).reshape(NB, G, q * k)

        def last_solve(w):
            y = jsl.solve_triangular(jnp.tril(lastLU, -1) + eye_q, w,
                                     lower=True, unit_diagonal=True)
            return jsl.solve_triangular(jnp.triu(lastLU), y, lower=False)

        if NB == 1:
            w = jnp.take_along_axis(fb[0].reshape(G, q, k),
                                    lastP[:, :, None], axis=1)
            x = last_solve(w)
            return jnp.moveaxis(x[None], 0, 1).reshape(G, self.n_pad, k)

        # forward: eliminate with pivots; carry the updated next block
        def fwd(w_cur, xs):
            f_next, perm, lu_flat = xs
            lu_i = lu_flat.reshape(G, 2 * q, q)
            w = jnp.concatenate([w_cur, f_next.reshape(G, q, k)], axis=1)
            w = jnp.take_along_axis(w, perm[:, :, None], axis=1)  # (G,2q,k)
            L1_i = jnp.tril(lu_i[:, :q, :], -1) + eye_q
            y = jsl.solve_triangular(L1_i, w[:, :q], lower=True,
                                     unit_diagonal=True)
            w_next = w[:, q:] - lu_i[:, q:, :] @ y
            return w_next, y.reshape(G, q * k)

        w_f, ys = jax.lax.scan(fwd, fb[0].reshape(G, q, k),
                               (fb[1:], perms, panelLU))
        w = jnp.take_along_axis(w_f, lastP[:, :, None], axis=1)
        x_last = last_solve(w)                                    # (G,q,k)

        # backward: x_i = U11_i^-1 (y_i - U12_i @ [x_{i+1}; x_{i+2}])
        zero = jnp.zeros_like(x_last)

        def bwd(carry, xs):
            x1, x2 = carry                                        # x_{i+1}, x_{i+2}
            y_flat, lu_flat, U12_flat = xs
            y_i = y_flat.reshape(G, q, k)
            lu_i = lu_flat.reshape(G, 2 * q, q)
            U12_i = U12_flat.reshape(G, q, 2 * q)
            rhs = y_i - U12_i @ jnp.concatenate([x1, x2], axis=1)
            x = jsl.solve_triangular(jnp.triu(lu_i[:, :q, :]), rhs,
                                     lower=False)
            return (x, x1), x.reshape(G, q * k)

        _, xs_rev = jax.lax.scan(bwd, (x_last, zero), (ys, panelLU, U12),
                                 reverse=True)
        x = jnp.concatenate([xs_rev.reshape(NB - 1, G, q, k),
                             x_last[None]], axis=0)
        return jnp.moveaxis(x, 0, 1).reshape(G, self.n_pad, k)

    def _pick_chunks(self, G, itemsize):
        """(C, Gc): chunk count and width for the G-chunked factorization,
        keeping a chunk's persistent factor slab (panelLU + U12) under
        BANDED_CHUNK_MB (the observed XLA temp footprint is a small
        multiple of that slab). When C*Gc > G (e.g. prime G) the batch is
        edge-padded with copies of the last group — factoring a duplicate
        is well-conditioned and its results are trimmed — so divisibility
        never degenerates chunking to size-1 sequential chunks. (When one
        group's factor slab alone exceeds the target, Gc still clamps to 1
        and factorization proceeds group-at-a-time: the target is a soft
        bound, exceeded only by indivisible per-group slabs.)"""
        target = float(config["linear algebra"].get(
            "BANDED_CHUNK_MB", "256")) * 1e6
        per_g = self.NB * (2 * self.q * self.q) * 2 * itemsize
        Gc = int(max(1, min(G, target // max(per_g, 1))))
        C = -(-G // Gc)
        if C <= 1:
            return 1, G
        Gc = -(-G // C)  # rebalance: padding stays below one chunk width
        return C, Gc

    @staticmethod
    def _pad_groups(arr, G_pad):
        """Edge-pad the leading (group) axis to G_pad."""
        pad = G_pad - arr.shape[0]
        if pad <= 0:
            return arr
        widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
        return jnp.pad(arr, widths, mode="edge")

    def _factor_core(self, bands, Vt, fused=False):
        """Factor one full-lattice band slab (any leading batch size).
        Returns (interior, Vt, YbT, CapLU, fsub) — a pytree safe to
        lax.map. `fused` additionally precomposes the matmul-substitution
        inverses (FUSED_SOLVE; the Woodbury E-solve below already runs on
        them, so fused factors are cheaper too)."""
        G = bands.shape[0]
        dtype = bands.dtype
        # identity pins at the pinned rows + padded diagonal
        ones = jnp.ones((G, len(self.pin_pos)), dtype=dtype)
        bands = bands.at[:, self.kl, self.pin_pos].set(ones)
        if self.n_pad > self.n:
            tail = jnp.ones((G, self.n_pad - self.n), dtype=dtype)
            bands = bands.at[:, self.kl, self.n:].set(tail)
        interior = self._factor_interior(bands)
        fsub = self._precompose_subst(interior) if fused else None
        if fused:
            # the fused solve consumes only fsub — dropping the pivoted
            # factors here (not just from the host-side aux) keeps the
            # incremental path's donated stores from materializing ~5q^2
            # of dead factors per step next to the ~7q^2 live operators
            interior = None
            if self._composition == "spike" and "FwdOp" in fsub:
                # BEFORE the Woodbury E-solve below: the E columns then
                # solve through the same restructured program
                self._attach_spike(fsub)
        YbT = CapLU = None
        if self.t:
            # Y = B~^-1 E  (E = one-hot columns at the pin positions)
            E = jnp.zeros((G, self.n_pad, self.t), dtype=dtype)
            E = E.at[:, self.pin_pos, jnp.arange(self.t)].set(1.0)
            Yb = self._solve_interior(interior, E, fsub=fsub)     # (G, n_pad, t)
            # capacitance: I + (Vt - E^T) Y
            Cap = (jnp.eye(self.t, dtype=dtype)
                   + jnp.einsum("gtn,gnk->gtk", Vt, Yb)
                   - Yb[:, self.pin_pos, :])
            # stored (G, t, n_pad): a trailing dim of t ~ 16 pads 8x under
            # TPU (8, 128) tiling; n_pad-minor tiles cleanly
            YbT = jnp.swapaxes(Yb, 1, 2)
            if fused:
                # the t x t capacitance solve becomes one GEMM too
                fsub["CapInv"] = jnp.linalg.inv(Cap)
            else:
                CapLU = jsl.lu_factor(Cap)
        if fused and self._ladder:
            # precision ladder (libraries/solvecomp.py): the whole
            # A'-solve — substitution operators AND Woodbury correction
            # — is stored and run in the low dtype (also halving the
            # persistent factor store); everything above computed at
            # native precision first so the low operators are rounded
            # versions of well-conditioned f64 factors. The f64
            # residual-matvec refinement in _solve_impl polishes each
            # solve back (sweep count scaled to the dtype gap).
            low = solvecomp.low_dtype(self._solve_plan.dtype, bands.dtype)
            fsub = jax.tree.map(lambda a: a.astype(low), fsub)
            Vt = Vt.astype(low)
            if YbT is not None:
                YbT = YbT.astype(low)
        return (interior, Vt, YbT, CapLU, fsub)

    def _aux_from_core(self, core, refine_aux):
        interior, Vt, YbT, CapLU, fsub = core
        # fused solves consume only the precomposed operators — dropping
        # the pivoted factors from the persistent aux frees ~4q^2 of the
        # 7q^2 per-step factor storage (they were transients of fsub)
        aux = {"Vt": Vt}
        if fsub is None:
            aux["interior"] = interior
        else:
            aux["fsub"] = fsub
        if YbT is not None:
            aux["YbT"] = YbT
        if CapLU is not None:
            aux["Cap"] = CapLU
        aux.update(refine_aux)
        return aux

    def _factor_impl(self, bands, Vt, refine_aux):
        """Shared factorization body; refine_aux supplies the residual
        matvec without persisting a combined matrix."""
        with jax.named_scope("dedalus/matsolve/banded.factor"):
            G = bands.shape[0]
            C, Gc = self._pick_chunks(G, bands.dtype.itemsize)
            self._g_chunks = C
            if C == 1:
                core = self._factor_core(bands, Vt)
            else:
                bands_c = self._pad_groups(bands, C * Gc).reshape(
                    C, Gc, self.nd, self.n_pad)
                Vt_c = self._pad_groups(Vt, C * Gc).reshape(
                    C, Gc, Vt.shape[1], self.n_pad)
                core = jax.lax.map(lambda xs: self._factor_core(*xs),
                                   (bands_c, Vt_c))
            return self._aux_from_core(core, refine_aux)

    def _combine_ml(self, mb, lb, mv, lv, g, a, b, dM, dL, dtype):
        """a*M + b*L as a full-lattice (bands, Vt) pair at the re-blocked
        factor width (the SINGLE implementation shared by the fused and
        incremental factor paths; inputs are assembled-width slabs)."""
        ns = self.n_store
        bands = jnp.zeros((g, self.nd, self.n_pad), dtype=dtype)
        bands = bands.at[:, dM, :ns].add(a * mb)
        bands = bands.at[:, dL, :ns].add(b * lb)
        Vt = jnp.zeros((g, self.t, self.n_pad), dtype=dtype)
        if mv is not None:
            Vt = Vt.at[:, :, :ns].add(a * mv)
        if lv is not None:
            Vt = Vt.at[:, :, :ns].add(b * lv)
        return bands, Vt

    def factor(self, A):
        """Factor a matrix already resident in banded storage."""
        self._ensure_q(A.bands.shape[0], A.bands.dtype.itemsize)
        bands, Vt = self.expand(A)
        return self._factor_impl(bands, Vt, {"A": A})

    def factor_lincomb(self, a, M, b, L):
        """Factor a*M + b*L WITHOUT persisting the combined bands: the
        combination is a transient of the factorization (built per G-chunk
        when chunking is active), and the refinement residual uses matvecs
        of the already-resident trimmed M and L (saves one full band store
        at large S)."""
        G = M.bands.shape[0]
        dtype = M.bands.dtype
        self._ensure_q(G, dtype.itemsize)
        C, Gc = self._pick_chunks(G, dtype.itemsize)
        self._g_chunks = C
        dM = np.asarray(M.dsel)
        dL = np.asarray(L.dsel)

        ns = self.n_store

        def combine(mb, lb, mv, lv, g):
            return self._combine_ml(mb, lb, mv, lv, g, a, b, dM, dL, dtype)

        # M and L themselves are NOT stored in the aux: the jitted factor
        # would return copies of both full band stores; the refinement
        # matvec receives them via solve(..., mats=(M, L))
        fused = self._fused_solve
        if C == 1:
            bands, Vt = combine(M.bands, L.bands, M.Vt, L.Vt, G)
            core = self._factor_core(bands, Vt, fused=fused)
        else:
            G_pad = C * Gc
            has_mv = M.Vt is not None
            has_lv = L.Vt is not None
            xs = [self._pad_groups(M.bands, G_pad).reshape(C, Gc, -1, ns),
                  self._pad_groups(L.bands, G_pad).reshape(C, Gc, -1, ns)]
            if has_mv:
                xs.append(self._pad_groups(M.Vt, G_pad).reshape(
                    C, Gc, self.t, ns))
            if has_lv:
                xs.append(self._pad_groups(L.Vt, G_pad).reshape(
                    C, Gc, self.t, ns))

            def one(xs):
                mb, lb = xs[0], xs[1]
                i = 2
                mv = xs[i] if has_mv else None
                i += has_mv
                lv = xs[i] if has_lv else None
                bands, Vt = combine(mb, lb, mv, lv, Gc)
                return self._factor_core(bands, Vt, fused=fused)

            if active_pencil_mesh() is not None:
                # distributed factor: XLA's SPMD partitioner miscompiles
                # the chunk-level lax.map (s64/s32 index mismatch in the
                # scan's dynamic_update_slice under x64 — the 2048x1024
                # north-star regime), and the factor outputs' group dims
                # vary per leaf so a manual shard_map reassembly is
                # ambiguous. C is static and small: unroll the chunk
                # loop into C chunk programs instead (the memory bound
                # lax.map provided is preserved by XLA's serial
                # scheduling of the independent chunk subgraphs).
                cores = [one(jax.tree.map(lambda s, _i=i: s[_i],
                                          tuple(xs)))
                         for i in range(C)]
                core = jax.tree.map(lambda *ls: jnp.stack(ls), *cores)
            else:
                core = jax.lax.map(one, tuple(xs))
        return self._aux_from_core(core, {"ab": (a, b)})

    # ------------------------------------------------ incremental factor

    def use_incremental_factor(self, G, itemsize):
        """Whether to factor chunk-by-chunk in SEPARATE device dispatches
        with donated accumulation (caps the transient HBM peak at roughly
        store + M/L + one chunk, vs the fused program's store + M/L + all
        scan temps). Engaged automatically when the factor output alone
        exceeds BANDED_INCREMENTAL_GB (the RB 2048x1024 regime: ~5.5 GB of
        factors on a 16 GB chip)."""
        self._ensure_q(G, itemsize)
        mode = config["linear algebra"].get(
            "BANDED_FACTOR_MODE", "auto").lower()
        if mode in ("fused", "incremental"):
            return mode == "incremental"
        C, Gc = self._pick_chunks(G, itemsize)
        if C <= 1:
            return False
        thresh = float(config["linear algebra"].get(
            "BANDED_INCREMENTAL_GB", "2.0")) * 1e9
        out_bytes = G * self.NB * (2 * self.q * self.q) * 2 * itemsize
        return out_bytes > thresh

    def factor_lincomb_incremental(self, a, M, L, b_scale):
        """factor_lincomb(a, M, b, L) as C separate device dispatches: each
        chunk is combined + factored by a small jitted program whose result
        is written into donated (C, Gc, ...) stores, so the full-batch scan
        temps never coexist with the finished factors. Returns the same
        chunked aux `solve` already consumes. Host-level: call OUTSIDE jit."""
        import functools
        if b_scale is None:
            raise ValueError("factor_lincomb_incremental requires b_scale "
                             "(the coefficient multiplying L).")
        b = b_scale
        G = M.bands.shape[0]
        dtype = M.bands.dtype
        self._ensure_q(G, dtype.itemsize)
        C, Gc = self._pick_chunks(G, dtype.itemsize)
        C = max(C, 2)  # incremental mode implies chunked aux layout
        Gc = -(-G // C)
        self._g_chunks = C
        dM = np.asarray(M.dsel)
        dL = np.asarray(L.dsel)
        has_mv = M.Vt is not None
        has_lv = L.Vt is not None
        rd = np.dtype(dtype)
        a = jnp.asarray(a, dtype=rd)
        b = jnp.asarray(b, dtype=rd)

        ns = self.n_store

        def chunk_core(mb, lb, mv, lv, a, b):
            bands, Vt = self._combine_ml(mb, lb, mv, lv, Gc, a, b,
                                         dM, dL, dtype)
            return self._factor_core(bands, Vt, fused=self._fused_solve)

        shapes = jax.eval_shape(
            chunk_core,
            jax.ShapeDtypeStruct((Gc, len(dM), ns), dtype),
            jax.ShapeDtypeStruct((Gc, len(dL), ns), dtype),
            jax.ShapeDtypeStruct((Gc, self.t, ns), dtype)
            if has_mv else None,
            jax.ShapeDtypeStruct((Gc, self.t, ns), dtype)
            if has_lv else None,
            jax.ShapeDtypeStruct((), rd), jax.ShapeDtypeStruct((), rd))
        store = jax.tree.map(
            lambda s: jnp.zeros((C,) + s.shape, dtype=s.dtype), shapes)

        @functools.partial(jax.jit, donate_argnums=0)
        def write(store, i, mb, lb, mv, lv, a, b):
            core = chunk_core(mb, lb, mv, lv, a, b)
            return jax.tree.map(
                lambda s, c: jax.lax.dynamic_update_index_in_dim(s, c, i, 0),
                store, core)

        def chunk_of(arr, i):
            if arr is None:
                return None
            lo = i * Gc
            hi = min(lo + Gc, G)
            sl = arr[lo:hi]
            if hi - lo < Gc:
                sl = self._pad_groups(sl, Gc)  # edge-pad the final chunk
            return sl

        for i in range(C):
            store = write(store, i,
                          chunk_of(M.bands, i), chunk_of(L.bands, i),
                          chunk_of(M.Vt, i) if has_mv else None,
                          chunk_of(L.Vt, i) if has_lv else None, a, b)
        jax.block_until_ready(store)
        return self._aux_from_core(store, {"ab": (a, b)})

    def _aux_matvec(self, aux, x, mats):
        if "A" in aux:
            return self.matvec(aux["A"], x)
        a, b = aux["ab"]
        M, L = mats
        if self._fused_matvec:
            # one-pass pair (bitwise-identical components): the
            # refinement residual's two matvecs share permute/pad/scatter
            MX, LX = self.matvec_pair(M, L, x)
            return a * MX + b * LX
        return a * self.matvec(M, x) + b * self.matvec(L, x)

    def _solve_core(self, auxc, fp):
        fsub = auxc.get("fsub")
        if fsub is not None and fsub["lastOp"].dtype != fp.dtype:
            # precision ladder: the factors are stored low — run the
            # whole inner solve low; _solve_once casts the result back
            # and _solve_impl refines against the f64 M/L matvec
            fp = fp.astype(fsub["lastOp"].dtype)
        if fsub is not None and "FwdOp" in fsub and self._pallas:
            # experimental: the whole substitution as one Pallas kernel
            # per group (no block-row round-trips; core/fusedstep.py)
            from ..core.fusedstep import pallas_substitution
            y = pallas_substitution(fsub, fp, self.q)
        else:
            y = self._solve_interior(auxc.get("interior"), fp[..., None],
                                     fsub=fsub)[..., 0]
        if self.t:
            Vy = (jnp.einsum("gtn,gn->gt", auxc["Vt"], y)
                  - y[:, self.pin_pos])
            if fsub is not None and "CapInv" in fsub:
                z = jnp.einsum("gij,gj->gi", fsub["CapInv"], Vy)
            else:
                z = jsl.lu_solve(auxc["Cap"], Vy)
            y = y - jnp.einsum("gtn,gt->gn", auxc["YbT"], z)
        return y

    def _solve_once(self, aux, rhs):
        G = rhs.shape[0]
        fp = rhs[:, self.row_perm]
        fp = zeropad(fp, ((0, 0), (0, self.n_pad - self.n)))
        # chunking is read off the aux's own stacked shapes ((G, q, q)
        # unchunked, (C, Gc, q, q) chunked) — instance state would go
        # stale across auxes factored under different configs
        probe = (aux["fsub"]["lastOp"] if "fsub" in aux
                 else aux["interior"][-1])
        C = probe.shape[0] if probe.ndim == 4 else 1
        if C == 1:
            y = self._solve_core(aux, fp)
        else:
            Gc = probe.shape[1]
            fp = self._pad_groups(fp, C * Gc)   # match factor-time padding
            auxc = {k: aux[k] for k in ("interior", "Vt", "YbT", "Cap",
                                        "fsub")
                    if k in aux}
            fpr = fp.reshape(C, Gc, self.n_pad)

            def chunked_solve(auxc, fpr):
                return jax.lax.map(
                    lambda xs: self._solve_core(xs[0], xs[1]),
                    (auxc, fpr))

            y = self._shard_chunked(chunked_solve, (auxc, fpr), Gc)
            y = y.reshape(-1, self.n_pad)[:G]
        xp = y[:, :self.n]
        out = xp[:, self.pos_col]
        if out.dtype != rhs.dtype:
            out = out.astype(rhs.dtype)   # ladder: back to the rhs dtype
        return out

    def _shard_chunked(self, fn, args, Gc):
        """Run a chunk-mapped factor/solve (`fn(*args)`, every traced
        leaf a (C, Gc, ...) slab) with the per-chunk GROUP axis (dim 1)
        sharded over the active pencil mesh, inside manual shard_map.
        Two reasons: the t x t capacitance LU custom calls stay
        device-local (GSPMD cannot partition them), and XLA's SPMD
        partitioner miscompiles the chunk scan's dynamic_update_slice
        under x64 (s64/s32 index mismatch, verifier failure after
        spmd-partitioning — observed on the 2048x1024 north-star banded
        step). Falls back to the plain GSPMD call when no mesh context
        is active, the chunk width does not tile the mesh, or any leaf
        does not carry the (C, Gc, ...) layout."""
        state = active_pencil_mesh()
        if state is not None:
            mesh, name = state
            n = mesh.shape[name]
            spec = PartitionSpec(None, name)

            def spec_of(leaf):
                ndim = getattr(leaf, "ndim", 0)
                if ndim == 0:
                    return PartitionSpec()
                if ndim >= 2 and leaf.shape[1] == Gc:
                    return spec
                return None

            in_specs = jax.tree.map(spec_of, args)
            if Gc % n == 0 and not any(
                    s is None for s in jax.tree.leaves(
                        in_specs, is_leaf=lambda x: x is None)):
                return shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=spec)(*args)
        return fn(*args)

    def _solve_impl(self, aux, rhs, mats=None):
        with jax.named_scope("dedalus/matsolve/banded.solve"):
            x = self._solve_once(aux, rhs)
            if mats is None and "A" not in aux:
                return x  # lincomb factor without mats: no refinement possible
            sweeps = self._refine_sweeps if self._refine_sweeps is not None \
                else self.refine
            if sweeps <= 0:
                return x
            tol = self._refine_tol

            def sweep(x, _):
                # f64 residual matvec against the assembled M/L (never
                # the low-dtype factors) — the correction solve runs in
                # the solve dtype, the polish at native precision
                r = rhs - self._aux_matvec(aux, x, mats)
                dx = self._solve_once(aux, r)
                if tol > 0.0:
                    # tolerance-terminated: converged groups freeze
                    # (masked update — fixed trip count, retrace-free)
                    rn = jnp.max(jnp.abs(r), axis=1, keepdims=True)
                    bn = jnp.max(jnp.abs(rhs), axis=1, keepdims=True)
                    return jnp.where(rn > tol * bn, x + dx, x), None
                return x + dx, None

            x, _ = jax.lax.scan(sweep, x, None, length=sweeps)
            return x

    def solve_report(self, aux, rhs, mats=None):
        """Diagnostic solve + achieved relative residual as a device
        scalar (None when the aux carries no residual matvec) — the
        flush-time `precision` telemetry probe and the benchmark
        accuracy rows. Never called on the step path."""
        x = self.solve(aux, rhs, mats=mats)
        if mats is None and "A" not in aux:
            return x, None
        r = rhs - self._aux_matvec(aux, x, mats)
        scale = jnp.max(jnp.abs(rhs))
        rel = jnp.max(jnp.abs(r)) / jnp.where(scale == 0, 1.0, scale)
        return x, rel
