"""
Structured batched pencil operators: the device-side representation of the
per-group LHS matrices and their factorization/solve algorithms.

The reference solves each pencil's sparse matrix with pivoted SuperLU on the
host (reference: dedalus/libraries/matsolvers.py:126-194, ScipyBanded :187,
Woodbury :285). The TPU-native equivalents here treat the pencil index G as
an MXU batch dimension and exploit structure instead of general sparsity:

  DenseOps  — (G, S, S) dense matrices; factor/solve delegate to the
              registered batched matsolvers (inverse / LU / refined).
  BandedOps — the mode-interleaved, matching-aligned permutation
              (core/subsystems.MatrixStructure) makes every true row
              banded; dense rows (BCs, gauges) are replaced by identity
              "pin" rows and restored by a rank-t Woodbury correction
              (reference Woodbury: libraries/matsolvers.py:285-316).
              Storage is (G, D, n) diagonals plus the pinned-row block
              Vt (G, t, n). The banded factorization is a blocked
              windowed-partial-pivoting LU (the batched analogue of
              LAPACK dgbtrf, reference matsolver ScipyBanded) over
              q-wide blocks via lax.scan; solves are two block
              substitution scans plus the t x t capacitance solve.
              Optional iterative-refinement sweeps polish the result
              using cheap banded matvecs.

All methods are pure jnp functions safe to trace inside jit; the structure
metadata (permutations, band offsets, block size, pin positions) is
host-static.
"""

import numpy as np
import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .matsolvers import get_solver


class DenseOps:
    """Dense (G, S, S) pencil operators (small problems / fallback)."""

    kind = "dense"

    def __init__(self, matsolver=None):
        self.solver_cls = get_solver(matsolver)

    def to_device(self, host_mat, dtype):
        return jnp.asarray(host_mat, dtype=dtype)

    def matvec(self, A, X):
        return jnp.einsum("gij,gj->gi", A, X)

    def lincomb(self, a, A, b, B):
        return a * A + b * B

    def scale(self, a, A):
        return a * A

    def factor(self, A):
        return self.solver_cls.factor(A)

    def solve(self, aux, rhs):
        return self.solver_cls.solve(aux, rhs)

    def densify_host(self, host_mat, g):
        return np.asarray(host_mat[g])


class BandedOps:
    """
    Banded + pinned-row pencil operators.

    Host representation per matrix name (core/subsystems.build_banded_arrays):
        bands : (G, D, n_pad)  diagonals of the matched (true-banded) rows,
                offsets -kl..ku; bands[g, d, p] = A'[g, p, p + d - kl]
        Vt    : (G, t, n_pad)  true content of the pinned rows

    with A' the row/column-permuted matrix. The represented matrix is
    A' = B + sum_i e_{p_i} Vt_i^T where B carries zero rows at the pin
    positions. Factorization pins those rows (B~ = B + sum_i e_{p_i}
    e_{p_i}^T, well-conditioned: pins constrain the coefficients the
    boundary rows would otherwise leave free) and applies Woodbury:
        A'^-1 = B~^-1 - B~^-1 E (I + (Vt - E^T) B~^-1 E)^-1 (Vt - E^T) B~^-1
    """

    kind = "banded"

    def __init__(self, structure, refine=1):
        st = structure
        self.st = st
        self.refine = int(refine)
        self.q = st.q
        self.NB = st.NB
        self.n = st.S                  # true system size
        self.n_pad = st.NB * st.q
        self.t = st.t_pins
        self.kl = st.kl
        self.ku = st.ku
        self.nd = st.kl + st.ku + 1    # number of stored diagonals
        # static permutation index arrays
        self.row_perm = np.asarray(st.row_perm)   # permuted pos -> orig index
        self.col_perm = np.asarray(st.col_perm)
        self.pos_col = np.argsort(self.col_perm)  # orig index -> permuted pos
        self.pin_pos = np.asarray(st.pinned_positions)
        # static block-gather indices: block[o][i, ri, ci] reads
        # bands[:, o*q + ci - ri + kl, i*q + ri]
        q, NB, kl = self.q, self.NB, self.kl
        ri = np.arange(q)[:, None]
        ci = np.arange(q)[None, :]
        self._blk_idx = {}
        for o in (-1, 0, 1):
            d = o * q + ci - ri + kl                 # (q, q)
            valid = (d >= 0) & (d < self.nd)
            rows = np.arange(NB)[:, None, None] * q + ri[None]   # (NB, q, q)
            self._blk_idx[o] = (np.where(valid, d, 0)[None].repeat(NB, 0),
                                rows + 0 * ci[None],
                                valid)

    # ------------------------------------------------------------ host side

    def to_device(self, host_arrs, dtype):
        return {k: jnp.asarray(v, dtype=dtype) for k, v in host_arrs.items()}

    def densify_host(self, host_arrs, g):
        """Reconstruct the original-ordering dense (S, S) matrix (host)."""
        S = self.n
        Ap = np.zeros((self.n_pad, self.n_pad), dtype=host_arrs["bands"].dtype)
        bands = host_arrs["bands"][g]
        for d in range(self.nd):
            off = d - self.kl
            rr = np.arange(max(0, -off), min(self.n_pad, self.n_pad - off))
            Ap[rr, rr + off] = bands[d, rr]
        if self.t:
            Ap[self.pin_pos, :] += host_arrs["Vt"][g]
        Ap = Ap[:S, :S]
        # un-permute: Ap[i, j] = A[row_perm[i], col_perm[j]]
        A = np.zeros_like(Ap)
        A[np.ix_(self.row_perm, self.col_perm)] = Ap
        return A

    # ----------------------------------------------------------- device ops

    def lincomb(self, a, A, b, B):
        return jax.tree.map(lambda x, y: a * x + b * y, A, B)

    def scale(self, a, A):
        return jax.tree.map(lambda x: a * x, A)

    def _band_mv(self, bands, x):
        """y[g, p] = sum_d bands[g, d, p] * x[g, p + d - kl]; x (G, n_pad)."""
        xpad = jnp.pad(x, ((0, 0), (self.kl, self.ku)))
        y = jnp.zeros_like(x)
        for d in range(self.nd):
            y = y + bands[:, d, :] * jax.lax.slice_in_dim(
                xpad, d, d + self.n_pad, axis=1)
        return y

    def matvec(self, A, X):
        """Full A @ X in the ORIGINAL slot ordering; X (G, S)."""
        xp = X[:, self.col_perm]
        xp = jnp.pad(xp, ((0, 0), (0, self.n_pad - self.n)))
        yp = self._band_mv(A["bands"], xp)
        if self.t:
            pin_vals = jnp.einsum("gtn,gn->gt", A["Vt"], xp)
            yp = yp.at[:, self.pin_pos].add(pin_vals)
        # yp[p] = (A @ X)[row_perm[p]]
        out = jnp.zeros_like(X)
        return out.at[:, self.row_perm].set(yp[:, :self.n])

    def _blocks(self, bands):
        """Band storage -> block tridiagonal (Dg, Lo, Up).
        Dg (G, NB, q, q); Lo/Up (G, NB-1, q, q) are blocks (i+1, i)/(i, i+1)."""
        out = {}
        for o in (-1, 0, 1):
            d_idx, r_idx, valid = self._blk_idx[o]
            blk = bands[:, d_idx, r_idx] * jnp.asarray(valid, dtype=bands.dtype)
            out[o] = blk
        Dg = out[0]
        Up = out[1][:, :-1]   # block (i, i+1) read at block-row i
        Lo = out[-1][:, 1:]   # block (i+1, i) read at block-row i+1
        return Dg, Lo, Up

    def _factor_interior(self, bands):
        """
        Blocked banded LU with windowed partial pivoting (the batched-TPU
        analogue of LAPACK dgbtrf, reference matsolver ScipyBanded:
        libraries/matsolvers.py:187): at block column i the (2q x q) panel
        [S_i; Lo_i] is factored with row pivoting (pivots confined to the
        window, exactly LAPACK's banded pivot range for kl <= q), the
        permutation + elimination are applied to the (2q x 2q) trailing
        window, and the upper fill (bandwidth ku + kl <= 2q) is stored in
        a (q x 2q) U12 block per step. Unconditionally stable where the
        no-pivot block elimination breaks on constraint rows.

        Returns aux tuple (perms, L1, L2, U11, U12, lastP, lastL, lastU).
        """
        G = bands.shape[0]
        q, NB = self.q, self.NB
        dtype = bands.dtype
        Dg, Lo, Up = self._blocks(bands)
        if NB == 1:
            lu, _, perm = jax.lax.linalg.lu(Dg[:, 0])
            lastL = jnp.tril(lu, -1) + jnp.eye(q, dtype=dtype)
            lastU = jnp.triu(lu)
            return (None, None, None, None, None, perm, lastL, lastU)

        eye_q = jnp.eye(q, dtype=dtype)
        zero_qq = jnp.zeros((G, q, q), dtype=dtype)

        def step(carry, xs):
            A11, A12 = carry              # (G,q,q), (G,q,2q): cols i+1, i+2
            Lo_i, D_n, Up_n = xs          # rows i+1: cols i, i+1, i+2
            panel = jnp.concatenate([A11, Lo_i], axis=1)          # (G,2q,q)
            lu, _, perm = jax.lax.linalg.lu(panel)
            L1 = jnp.tril(lu[:, :q, :], -1) + eye_q               # (G,q,q)
            L2 = lu[:, q:, :]                                     # (G,q,q)
            U11 = jnp.triu(lu[:, :q, :])                          # (G,q,q)
            T = jnp.concatenate(
                [A12, jnp.concatenate([D_n, Up_n], axis=2)], axis=1)  # (G,2q,2q)
            T = jnp.take_along_axis(T, perm[:, :, None], axis=1)
            U12 = jsl.solve_triangular(L1, T[:, :q, :], lower=True,
                                       unit_diagonal=True)        # (G,q,2q)
            Tn = T[:, q:, :] - L2 @ U12                           # (G,q,2q)
            carry = (Tn[:, :, :q],
                     jnp.concatenate([Tn[:, :, q:], zero_qq], axis=2))
            return carry, (perm, L1, L2, U11, U12)

        xs = (jnp.moveaxis(Lo, 1, 0),
              jnp.moveaxis(Dg[:, 1:], 1, 0),
              jnp.moveaxis(jnp.concatenate([Up[:, 1:], zero_qq[:, None]],
                                           axis=1), 1, 0))
        A12_0 = jnp.concatenate([Up[:, 0], zero_qq], axis=2)
        (A11_f, _), (perms, L1, L2, U11, U12) = jax.lax.scan(
            step, (Dg[:, 0], A12_0), xs)
        lu, _, lastP = jax.lax.linalg.lu(A11_f)
        lastL = jnp.tril(lu, -1) + eye_q
        lastU = jnp.triu(lu)
        return (perms, L1, L2, U11, U12, lastP, lastL, lastU)

    def _solve_interior(self, interior_aux, f):
        """Solve B~ x = f for f (G, n_pad, k) via the pivoted block factors."""
        perms, L1, L2, U11, U12, lastP, lastL, lastU = interior_aux
        G, _, k = f.shape
        q, NB = self.q, self.NB
        fb = jnp.moveaxis(f.reshape(G, NB, q, k), 1, 0)   # (NB, G, q, k)
        if NB == 1:
            w = jnp.take_along_axis(fb[0], lastP[:, :, None], axis=1)
            y = jsl.solve_triangular(lastL, w, lower=True, unit_diagonal=True)
            x = jsl.solve_triangular(lastU, y, lower=False)
            return jnp.moveaxis(x[None], 0, 1).reshape(G, self.n_pad, k)

        # forward: eliminate with pivots; carry the updated next block
        def fwd(w_cur, xs):
            f_next, perm, L1_i, L2_i = xs
            w = jnp.concatenate([w_cur, f_next], axis=1)          # (G,2q,k)
            w = jnp.take_along_axis(w, perm[:, :, None], axis=1)
            y = jsl.solve_triangular(L1_i, w[:, :q], lower=True,
                                     unit_diagonal=True)
            w_next = w[:, q:] - L2_i @ y
            return w_next, y

        w_f, ys = jax.lax.scan(fwd, fb[0], (fb[1:], perms, L1, L2))
        w = jnp.take_along_axis(w_f, lastP[:, :, None], axis=1)
        yl = jsl.solve_triangular(lastL, w, lower=True, unit_diagonal=True)
        x_last = jsl.solve_triangular(lastU, yl, lower=False)     # (G,q,k)

        # backward: x_i = U11_i^-1 (y_i - U12_i @ [x_{i+1}; x_{i+2}])
        zero = jnp.zeros_like(x_last)

        def bwd(carry, xs):
            x1, x2 = carry                                        # x_{i+1}, x_{i+2}
            y_i, U11_i, U12_i = xs
            rhs = y_i - U12_i @ jnp.concatenate([x1, x2], axis=1)
            x = jsl.solve_triangular(U11_i, rhs, lower=False)
            return (x, x1), x

        _, xs_rev = jax.lax.scan(bwd, (x_last, zero), (ys, U11, U12),
                                 reverse=True)
        x = jnp.concatenate([xs_rev, x_last[None]], axis=0)
        return jnp.moveaxis(x, 0, 1).reshape(G, self.n_pad, k)

    def factor(self, A):
        """Factor the combined LHS; returns the aux pytree for solve()."""
        G = A["bands"].shape[0]
        dtype = A["bands"].dtype
        bands = A["bands"]
        # identity pins at the pinned rows + padded diagonal
        ones = jnp.ones((G, len(self.pin_pos)), dtype=dtype)
        bands = bands.at[:, self.kl, self.pin_pos].set(ones)
        if self.n_pad > self.n:
            tail = jnp.ones((G, self.n_pad - self.n), dtype=dtype)
            bands = bands.at[:, self.kl, self.n:].set(tail)
        interior = self._factor_interior(bands)
        aux = {"interior": interior, "A": A}
        if self.t:
            # Y = B~^-1 E  (E = one-hot columns at the pin positions)
            E = jnp.zeros((G, self.n_pad, self.t), dtype=dtype)
            E = E.at[:, self.pin_pos, jnp.arange(self.t)].set(1.0)
            Yb = self._solve_interior(interior, E)                # (G, n_pad, t)
            # capacitance: I + (Vt - E^T) Y
            Cap = (jnp.eye(self.t, dtype=dtype)
                   + jnp.einsum("gtn,gnk->gtk", A["Vt"], Yb)
                   - Yb[:, self.pin_pos, :])
            aux["Yb"] = Yb
            aux["Cap"] = jsl.lu_factor(Cap)
        return aux

    def _solve_once(self, aux, rhs):
        fp = rhs[:, self.row_perm]
        fp = jnp.pad(fp, ((0, 0), (0, self.n_pad - self.n)))
        y = self._solve_interior(aux["interior"], fp[..., None])[..., 0]
        if self.t:
            Vy = (jnp.einsum("gtn,gn->gt", aux["A"]["Vt"], y)
                  - y[:, self.pin_pos])
            z = jsl.lu_solve(aux["Cap"], Vy)
            y = y - jnp.einsum("gnt,gt->gn", aux["Yb"], z)
        xp = y[:, :self.n]
        return xp[:, self.pos_col]

    def solve(self, aux, rhs):
        x = self._solve_once(aux, rhs)
        for _ in range(self.refine):
            r = rhs - self.matvec(aux["A"], x)
            x = x + self._solve_once(aux, r)
        return x
