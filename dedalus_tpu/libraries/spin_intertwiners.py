"""
Regularity <-> spin intertwiners for spherical (3D) tensors
(reference: dedalus/libraries/dedalus_sphere/spin_operators.py:276
Intertwiner).

A rank-r tensor field on the ball/shell decomposes, for each spherical
harmonic degree ell, into *regularity components* indexed by tuples
a in {-1, 0, +1}^r: the combinations whose radial dependence is
r^(ell + sum(a)) * (analytic in r^2), which is what the Zernike radial
bases expand. The orthogonal matrix Q(ell) maps regularity components to
*spin components* (the frame in which the colatitude SWSH transforms act).

The coupling coefficients obey a first-index recursion (a Clebsch-Gordan
ladder): with sigma = spin[0], a = reg[0], tau = spin[1:], b = reg[1:],
J = ell + sum(b),

    R = sum_i [ (tau_i == -sigma) * -Q[tau|_i->0, b]
              + (tau_i ==  0    ) * +Q[tau|_i->sigma, b] ]
        - k(sigma, sum(tau)) * Q[tau, b],
    k(mu, s) = -mu sqrt((ell - s mu)(ell + s mu + 1)/2),

    Q[spin, reg] = (Q[tau,b]*J - R)/sqrt(J(2J+1))          if a == -1
                 = sigma*R/sqrt(J(J+1))                    if a ==  0
                 = (Q[tau,b]*(J+1) + R)/sqrt((J+1)(2J+1))  if a == +1

(with Q[tau,b] zeroed for sigma != 0 in the a = +-1 branches), seeded by
Q[(), ()] = 1 and zero for forbidden spins (|sum(spin)| > ell) and forbidden
regularities (the degree walk ell + partial sums dropping below zero or
stalling at (0,0)).
"""

import numpy as np
from itertools import product

from ..tools.cache import cached_function

SPIN_ORDERING = (-1, +1, 0)  # matches SphericalCoordinates component ordering


def _forbidden_spin(ell, spin):
    return ell < abs(sum(spin))


def _forbidden_regularity(ell, regularity):
    if ell >= len(regularity):
        return False
    walk = (ell,)
    for r in regularity[::-1]:
        walk += (walk[-1] + r,)
        if walk[-1] < 0 or walk[-2:] == (0, 0):
            return True
    return False


def _coefficient(ell, spin, regularity, memo):
    key = (spin, regularity)
    if key in memo:
        return memo[key]
    if len(spin) == 0:
        return 1.0
    if _forbidden_spin(ell, spin) or _forbidden_regularity(ell, regularity):
        memo[key] = 0.0
        return 0.0
    sigma, a = spin[0], regularity[0]
    tau, b = spin[1:], regularity[1:]

    def sub(t):
        return _coefficient(ell, t, b, memo)

    R = 0.0
    for i, t in enumerate(tau):
        if t + sigma == 0:
            R -= sub(tau[:i] + (0,) + tau[i + 1:])
        if t == 0:
            R += sub(tau[:i] + (sigma,) + tau[i + 1:])
    Q = sub(tau)
    s_tau = sum(tau)
    k = -sigma * np.sqrt(max((ell - s_tau * sigma) * (ell + s_tau * sigma + 1), 0) / 2)
    R -= k * Q
    J = ell + sum(b)
    if sigma != 0:
        Q = 0.0
    if a == -1:
        val = (Q * J - R) / np.sqrt(J * (2 * J + 1))
    elif a == 0:
        val = sigma * R / np.sqrt(J * (J + 1))
    else:
        val = (Q * (J + 1) + R) / np.sqrt((J + 1) * (2 * J + 1))
    if abs(val) < 1e-12:
        val = 0.0
    memo[key] = val
    return val


@cached_function
def regularity_to_spin(ell, rank, ordering=SPIN_ORDERING):
    """
    Q(ell): (3^rank, 3^rank) orthogonal matrix, spin rows x regularity
    columns, both flattened in `ordering` per index
    (reference: core/coords.py:359 SphericalCoordinates._Q_backward).
    """
    if rank == 0:
        return np.array([[1.0]])
    memo = {}
    tuples = list(product(ordering, repeat=rank))
    Q = np.zeros((3 ** rank, 3 ** rank))
    for i, spin in enumerate(tuples):
        for j, reg in enumerate(tuples):
            Q[i, j] = _coefficient(ell, spin, reg, memo)
    return Q


def spin_to_regularity(ell, rank, ordering=SPIN_ORDERING):
    """Inverse (transpose) intertwiner
    (reference: core/coords.py:356 _Q_forward)."""
    return regularity_to_spin(ell, rank, ordering).T


def valid_regularities(ell, rank, ordering=SPIN_ORDERING):
    """Boolean flat mask of allowed regularity tuples at this ell."""
    tuples = list(product(ordering, repeat=rank))
    return np.array([not _forbidden_regularity(ell, reg) for reg in tuples])


def regularity_degree_shifts(rank, ordering=SPIN_ORDERING):
    """sum(a) for each flattened regularity tuple: the shift of the radial
    degree l = ell + sum(a) used by the Zernike expansion."""
    tuples = list(product(ordering, repeat=rank))
    return np.array([sum(reg) for reg in tuples])
