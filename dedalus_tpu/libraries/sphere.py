"""
Spin-weighted spherical harmonics (SWSH) toolbox
(reference: dedalus/libraries/dedalus_sphere/sphere.py — same capabilities,
different construction).

For azimuthal order m and spin weight s, the colatitude functions are

    Y_{l,(m,s)}(z) = phase * sqrt((1-z)^a (1+z)^b) * Phat_n^{(a,b)}(z)

with z = cos(theta), (a, b) = (|m+s|, |m-s|), n = l - l_min,
l_min = max(|m|, |s|), phase = (-1)^max(m, -s), and Phat the *orthonormal*
Jacobi polynomials from tools.jacobi. The functions are orthonormal under
plain dz on [-1, 1] (the envelope absorbs the measure); together with
e^{i m phi} / sqrt(2 pi) they are orthonormal on the unit sphere.

Design note: instead of the reference's lazy sparse operator algebra
(dedalus_sphere/operators.py), every operator matrix here is assembled by
Gauss-Jacobi quadrature of the *analytic differential operator* applied to
recurrence-evaluated basis functions. Because each result lies exactly in
the target SWSH space, quadrature of sufficient degree is exact to
roundoff, and the assembly is automatically consistent with whatever phase
conventions the basis functions use.

Spin ladder ("covariant derivative") operators, for f = g(theta) e^{i m phi}
of spin s on the unit sphere:

    D_{+1} g = (1/sqrt(2)) (d/dtheta - (m + s cos)/sin) g   -> spin s+1
    D_{-1} g = (1/sqrt(2)) (d/dtheta + (m + s cos)/sin) g   -> spin s-1

These are (-1/sqrt(2)) times the standard edth / edth-bar operators; the
gradient of a scalar has spin components (grad f)_{+-} = D_{+-} f / radius,
and the spin-weighted Laplacian is (D_{+1} D_{-1} + D_{-1} D_{+1}) / r^2
with eigenvalues -(l(l+1) - s^2)/r^2.
"""

import numpy as np

from ..tools import jacobi
from ..tools.cache import cached_function


def lmin(m, s):
    return max(abs(m), abs(s))


def spin2jacobi(Lmax, m, s):
    """(n, a, b): number of polynomials and Jacobi parameters for (m, s)
    (reference: dedalus_sphere/sphere.py:23 spin2Jacobi)."""
    n = Lmax + 1 - lmin(m, s)
    return n, abs(m + s), abs(m - s)


@cached_function
def quadrature(Lmax):
    """Gauss-Legendre nodes/weights in z = cos(theta), ascending in z.
    Exact for polynomials of degree <= 2*Lmax + 1
    (reference: dedalus_sphere/sphere.py:8 quadrature)."""
    z = jacobi.build_grid(Lmax + 1, 0, 0)
    w = jacobi.build_weights(Lmax + 1, 0, 0)
    return z, w


def _envelope(a, b, z):
    return np.sqrt((1 - z) ** a * (1 + z) ** b)


def harmonics(Lmax, m, s, z):
    """
    SWSH colatitude functions at points z: array (n, len(z)), rows l = l_min
    .. Lmax (reference: dedalus_sphere/sphere.py:43 harmonics).
    """
    z = np.atleast_1d(np.asarray(z, dtype=np.float64))
    n, a, b = spin2jacobi(Lmax, m, s)
    if n <= 0:
        return np.zeros((0, z.size))
    phase = (-1.0) ** max(m, -s)
    P = jacobi.build_polynomials(n, a, b, z)
    return phase * _envelope(a, b, z) * P


def _harmonics_and_theta_derivatives(Lmax, m, s, z):
    """(Y, dY/dtheta) at z; both (n, len(z)). Interior points only."""
    z = np.atleast_1d(np.asarray(z, dtype=np.float64))
    n, a, b = spin2jacobi(Lmax, m, s)
    if n <= 0:
        return np.zeros((0, z.size)), np.zeros((0, z.size))
    phase = (-1.0) ** max(m, -s)
    env = _envelope(a, b, z)
    P = jacobi.build_polynomials(n, a, b, z)
    dP = jacobi.build_polynomial_derivatives(n, a, b, z)
    sin = np.sqrt(1 - z * z)
    # dY/dtheta = -sin * dY/dz;  denv/dz = env * (-a/(2(1-z)) + b/(2(1+z)))
    denv_term = (a * (1 + z) - b * (1 - z)) / (2 * sin)  # = -sin * env'/env
    Y = phase * env * P
    dY = phase * env * (-sin * dP + denv_term * P)
    return Y, dY


def ladder_values(Lmax, m, s, ds, z):
    """
    Values of D_{ds} applied to each (m, s) harmonic, at interior points z.
    Shape (n_in, len(z)).
    """
    assert ds in (+1, -1)
    z = np.atleast_1d(np.asarray(z, dtype=np.float64))
    Y, dY = _harmonics_and_theta_derivatives(Lmax, m, s, z)
    sin = np.sqrt(1 - z * z)
    connection = (m + s * z) / sin
    return (dY - ds * connection * Y) / np.sqrt(2)


def _project(Lmax, m, s_out, values_fn, n_in, extra=2):
    """
    Project function values onto the (m, s_out) SWSH space by Gauss-Jacobi
    quadrature: M[j, i] = <Y_out_j, F_i>_dz for F_i = values_fn(z)[i].
    Exact when each F_i lies in the output space.
    """
    n_out, a, b = spin2jacobi(Lmax, m, s_out)
    if n_out <= 0 or n_in <= 0:
        return np.zeros((max(n_out, 0), max(n_in, 0)))
    Nq = max(n_out, n_in) + extra
    zq = jacobi.build_grid(Nq, a, b)
    wq = jacobi.build_weights(Nq, a, b)
    env = _envelope(a, b, zq)
    # Y_out / env and F / env are polynomials; weight (1-z)^a (1+z)^b is in wq.
    Yout = harmonics(Lmax, m, s_out, zq)
    F = values_fn(zq)
    return (Yout / env * (wq / env)) @ F.T


def _selection_mask(Lmax, m, s_out, s_in, dl):
    """
    Analytic selection rule |l_out - l_in| <= dl as a boolean mask over the
    (m, s_out) x (m, s_in) coefficient spaces. Quadrature assembly leaves
    ~1e-15 dirt outside the rule that grows with Lmax and defeats band
    detection; masking restores exact sparsity.
    """
    l_out = np.arange(lmin(m, s_out), Lmax + 1)
    l_in = np.arange(lmin(m, s_in), Lmax + 1)
    return np.abs(l_out[:, None] - l_in[None, :]) <= dl


@cached_function
def ladder_matrix(Lmax, m, s, ds):
    """
    Coefficient-space matrix of D_{ds}: (m, s) -> (m, s + ds).
    Shape (n_out, n_in); diagonal in l (rectangular with offset).
    (reference: dedalus_sphere/sphere.py:120 SphereOperator.__D)
    """
    n_in = spin2jacobi(Lmax, m, s)[0]
    M = _project(Lmax, m, s + ds, lambda z: ladder_values(Lmax, m, s, ds, z), n_in)
    return M * _selection_mask(Lmax, m, s + ds, s, 0)


@cached_function
def cos_matrix(Lmax, m, s):
    """Multiplication by cos(theta) within the (m, s) space, truncated at
    Lmax: (n, n), tridiagonal in l (reference: sphere.py 'Cos' operator)."""
    n_in = spin2jacobi(Lmax, m, s)[0]
    M = _project(Lmax, m, s, lambda z: z * harmonics(Lmax, m, s, z), n_in)
    return M * _selection_mask(Lmax, m, s, s, 1)


@cached_function
def sin_matrix(Lmax, m, s_out, s_in):
    """
    Multiplication by sin(theta) mapping spin-s_in coefficients into the
    spin-s_out space (|s_out - s_in| = 1): the spin-mixing half of
    meridional (ez-type) couplings, banded with |l_out - l_in| <= 1.
    Quadrature-exact: sin(theta) = (1-z)^(1/2) (1+z)^(1/2) shifts the
    Jacobi envelope exponents by exactly the spin change, so the projected
    integrand stays polynomial (reference: the Gaunt/Clenshaw couplings of
    core/arithmetic.py:359-558 specialized to one sin(theta) factor).
    """
    if abs(s_out - s_in) != 1:
        raise ValueError("sin_matrix requires |s_out - s_in| = 1.")
    n_in = spin2jacobi(Lmax, m, s_in)[0]
    M = _project(Lmax, m, s_out,
                 lambda z: np.sqrt(1 - z * z) * harmonics(Lmax, m, s_in, z),
                 n_in)
    return M * _selection_mask(Lmax, m, s_out, s_in, 1)


@cached_function
def forward_matrix(Lmax, m, s, Ng=None):
    """
    Forward colatitude transform: values on the Ng-point Gauss-Legendre grid
    -> SWSH coefficients l = l_min..Lmax. Shape (n, Ng).
    """
    if Ng is None:
        Ng = Lmax + 1
    z, w = quadrature(Ng - 1)
    return harmonics(Lmax, m, s, z) * w


@cached_function
def backward_matrix(Lmax, m, s, Ng=None):
    """Backward colatitude transform: coefficients -> Ng grid values. (Ng, n)."""
    if Ng is None:
        Ng = Lmax + 1
    z, _ = quadrature(Ng - 1)
    return harmonics(Lmax, m, s, z).T


def interpolation_row(Lmax, m, s, theta0):
    """Row (1, n): evaluate each harmonic at colatitude theta0."""
    return harmonics(Lmax, m, s, np.array([np.cos(theta0)]))[:, 0][None, :]


@cached_function
def triple_product_matrix(Lmax, m, s_out, s_mid, s_in, L):
    """
    Coupling matrix of multiplication by the axisymmetric spin-s_mid
    harmonic Y_{L,(0,s_mid)}: W[l', l] = <Y_{l',(m,s_out)}, Y_{L,(0,s_mid)}
    Y_{l,(m,s_in)}>_dz over l' = lmin(m, s_out)..Lmax, l = lmin(m, s_in)
    ..Lmax. This is the quadrature route to the Gaunt/Clenshaw couplings the
    reference builds recursively (reference: dedalus/core/basis.py:611-628
    Clenshaw matrices inside core/arithmetic.py:359-406 prep_nccs): exact
    because the three-envelope product is again a polynomial times an
    integer-power envelope, integrated with 1.5x-degree Gauss-Legendre.
    Selection rule |l' - l| <= L is imposed analytically to clear
    quadrature dirt. Spin balance (s_out = s_mid + s_in) is NOT assumed;
    callers pass balanced triples, where the integral is generically
    nonzero.
    """
    n_out, a_o, b_o = spin2jacobi(Lmax, m, s_out)
    n_in, a_i, b_i = spin2jacobi(Lmax, m, s_in)
    n_mid = spin2jacobi(L, 0, s_mid)[0]
    if n_out <= 0 or n_in <= 0 or n_mid <= 0 or L < lmin(0, s_mid):
        return np.zeros((max(n_out, 0), max(n_in, 0)))
    # Gauss-Legendre of degree covering l' + L + l <= 2 Lmax + L plus the
    # (integer) envelope powers: 3 (Lmax + 1) points are always enough.
    Nq = 3 * (Lmax + 1)
    zq = jacobi.build_grid(Nq, 0, 0)
    wq = jacobi.build_weights(Nq, 0, 0)
    Yo = harmonics(Lmax, m, s_out, zq)
    Yi = harmonics(Lmax, m, s_in, zq)
    g = harmonics(L, 0, s_mid, zq)[L - lmin(0, s_mid)]
    W = (Yo * (wq * g)) @ Yi.T
    return W * _selection_mask(Lmax, m, s_out, s_in, L)


def ell_range(Lmax, m, s):
    """The l values carried by the (m, s) coefficient vector."""
    return np.arange(lmin(m, s), Lmax + 1)
