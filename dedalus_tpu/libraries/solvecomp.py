"""
Solve compositions: log-depth restructurings of the banded substitution
recurrences, and the mixed-precision solve ladder (ROADMAP item 5's
precision half; JAXMg in PAPERS.md is the XLA-native precedent for
restructuring a structured solve into batched matmuls).

The PR-12 fused substitution made every scan STEP one batched GEMM, but
the scan itself still runs NB-1 *sequential* steps per sweep — O(N)
dependent dispatches that serialize exactly the dimension an MXU wants
to batch, and that per-step fusion cannot hide (the measured remaining
floor of the rb256x64 step). Both sweeps are affine recurrences over
factor-time-constant operators:

    forward:   w_{i+1} = A_i @ w_i + B_i @ f_{i+1}
               y_i     = C_i @ w_i + D_i @ f_{i+1}
    backward:  z_i     = A'_i @ z_{i+1} + B'_i @ y_i     (z = [x_i; x_{i+1}])

where (A, B, C, D) are slices of the precomposed FwdOp/BwdOp GEMM
operators (libraries/pencilops.BandedOps._precompose_subst). Two
restructurings of that recurrence live here, selected by
`[fusion] SOLVE_COMPOSITION` (resolved ONCE per solver build, folded
into the assembly-cache/pool keys like every PR-12/13 knob):

  ascan — the textbook parallel prefix: `lax.associative_scan` over
          (A, b) pairs with the matmul combine
          (A2, b2) o (A1, b1) = (A2 @ A1, A2 @ b1 + b2).
          Depth O(log N); flops O(N log N * s^3) because the combine
          multiplies s x s operator blocks — the composition wins where
          depth is the cost (latency-bound accelerators), and loses
          where flops are (CPU). No `lax.scan` survives in the lowered
          program at all.

  spike — the chunk-partitioned SPIKE analogue: the step axis splits
          into C chunks whose within-chunk transfer operators are
          PRECOMPOSED AT FACTOR TIME into dense block-triangular
          per-chunk GEMM operators, so the solve is
              outs_c = Y_c @ f_c + YH_c @ v_in_c        (batched GEMMs)
              v_in_{c+1} = T_c @ v_in_c + P_c @ f_c     (C-step reduced scan)
          — one batched GEMM program over all chunks at once, coupled
          through a C-length reduced recurrence. Sequential depth C
          (~sqrt(N) by default), flops ~(N/C) x the sequential sweep's,
          amortized into large GEMMs instead of N tiny scan steps.

The precision ladder (`[precision] SOLVE_DTYPE = f32|bf16`) casts the
factor-time substitution/Woodbury operators to the low dtype so every
solve GEMM runs low, then polishes with the existing f64
residual-matvec refinement loop (fixed trip count, residual-tolerance
masked — retrace-free) back to a configurable tolerance. `REFINE_SWEEPS
= auto` scales the sweep count to the dtype gap; accuracy is recorded
per benchmark row (benchmarks/fusion.py) and in the `precision`
telemetry block.

Everything here is pure jnp, traced inside the existing
`AdjointSolveOps.solve` custom_vjp funnel (so adjoints transpose the
SAME restructured linear algebra via jax.vjp), and composes under vmap
(EnsembleSolver) and shard_map. Config is read only in the resolve_*
functions, at solver-build time — never on the step path (DTL008).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..tools.config import config

__all__ = ["SolvePlan", "resolve_solve_plan", "solve_plan_token",
           "solve_knobs_pinned", "apply_decision",
           "resolve_solve_plan_for_ops", "low_dtype", "spike_chunk_count",
           "ascan_apply", "spike_precompose", "spike_apply",
           "COMPOSITIONS", "SOLVE_DTYPES"]

COMPOSITIONS = ("sequential", "ascan", "spike")
SOLVE_DTYPES = ("native", "f32", "bf16")

# refinement sweeps per solve dtype when REFINE_SWEEPS = auto; None =
# defer to the ops' own default polish (BandedOps.refine — the PR-12
# fused tolerance class is calibrated against exactly that count).
# f32: 2 sweeps measured to hold the rb256x64 trajectory at the f64
# class (state err ~1e-14, probe residual ~1e-12) while keeping the
# ladder's speedup (benchmarks/fusion.py sweep rows); raise REFINE_TOL/
# REFINE_SWEEPS for stiffer operators. bf16's weaker per-sweep
# contraction (~eps_bf16 * cond) needs the deeper schedule.
_AUTO_SWEEPS = {"native": None, "f32": 2, "bf16": 6}


class SolvePlan:
    """Resolved solve composition + precision ladder (immutable per
    solver build; the `[fusion]`/`[precision]` analogue of FusionPlan).
    `sweeps=None` means "keep the ops' own refinement count"."""

    __slots__ = ("composition", "spike_chunks", "dtype", "sweeps", "tol",
                 "mmt_dtype")

    def __init__(self, composition="sequential", spike_chunks=0,
                 dtype="native", sweeps=None, tol=0.0, mmt_dtype="native"):
        self.composition = composition
        self.spike_chunks = int(spike_chunks)
        self.dtype = dtype
        self.sweeps = sweeps
        self.tol = float(tol)
        self.mmt_dtype = mmt_dtype

    def token(self):
        """Stable content token for the assembly-cache solver key (and
        through it the serving pool key): the RESOLVED composition and
        ladder, so a knob flip can never alias a compiled program built
        under another composition/precision."""
        return ("solve-v1", self.composition, self.spike_chunks,
                self.dtype, self.sweeps, self.tol, self.mmt_dtype)

    def __repr__(self):
        bits = [self.composition]
        if self.dtype != "native":
            bits.append(f"{self.dtype}+refine")
        return f"SolvePlan({'+'.join(bits)})"


def _choice(section, key, default, allowed):
    raw = config[section].get(key, default) \
        if config.has_section(section) else default
    val = raw.strip().lower()
    if val not in allowed:
        # unknown values must FAIL the build, not silently resolve to
        # auto: the compositions sit in different tolerance classes and
        # different depth contracts (the PR-12 config discipline)
        raise ValueError(
            f"[{section}] {key} = {raw!r} is not a recognized value "
            f"({'/'.join(allowed)})")
    return val


# the tunable solve knobs: any non-auto value here means the user has
# PINNED the plan, and the empirical autotuner (tools/autotune.py) must
# stand down for that build (`plan_source: config`)
_TUNABLE_KEYS = (("fusion", "SOLVE_COMPOSITION"),
                 ("fusion", "SPIKE_CHUNKS"),
                 ("precision", "SOLVE_DTYPE"),
                 ("precision", "REFINE_SWEEPS"))


def solve_knobs_pinned():
    """True when any tunable solve knob carries an explicit (non-auto)
    value — explicit config always beats a tuned decision."""
    for section, key in _TUNABLE_KEYS:
        raw = config[section].get(key, "auto") \
            if config.has_section(section) else "auto"
        if raw.strip().lower() not in ("auto", ""):
            return True
    return False


def apply_decision(plan, cell):
    """A tuned plan: `cell` (an autotune decision's plan cell) layered
    over the heuristic `plan`. tol/mmt_dtype are not tuned and carry
    over; sweeps fall back to the dtype's auto schedule when the cell
    does not pin them."""
    dtype = cell.get("solve_dtype") or plan.dtype
    if dtype == "f64":
        dtype = "native"
    sweeps = cell.get("refine_sweeps")
    if sweeps is None:
        sweeps = _AUTO_SWEEPS.get(dtype, plan.sweeps)
    return SolvePlan(composition=cell.get("composition")
                     or plan.composition,
                     spike_chunks=cell.get("spike_chunks",
                                           plan.spike_chunks) or 0,
                     dtype=dtype, sweeps=sweeps, tol=plan.tol,
                     mmt_dtype=plan.mmt_dtype)


def resolve_solve_plan(decision=None):
    """Resolve `[fusion] SOLVE_COMPOSITION`/`SPIKE_CHUNKS` and the
    `[precision]` section against the active backend. Called once per
    solver build (core/solvers._build_pencil_system) BEFORE
    assembly_cache.solver_key seals the result into the cache/pool keys.
    `auto` semantics: composition stays `sequential` (the measured
    default — benchmarks/fusion.py sweeps the alternatives and records
    where each wins), SOLVE_DTYPE stays native, REFINE_SWEEPS scales to
    the dtype gap, REFINE_TOL 0 (fixed sweeps, always applied).

    `decision` (a tools.autotune.Decision) supplies the measured tuned
    cell AHEAD of those heuristics — but only when every tunable knob is
    auto: explicit config always wins."""
    comp = _choice("fusion", "SOLVE_COMPOSITION", "auto",
                   ("auto",) + COMPOSITIONS)
    if comp == "auto":
        comp = "sequential"
    raw_chunks = config["fusion"].get("SPIKE_CHUNKS", "auto") \
        if config.has_section("fusion") else "auto"
    raw_chunks = raw_chunks.strip().lower()
    if raw_chunks in ("auto", ""):
        spike_chunks = 0
    else:
        try:
            spike_chunks = int(raw_chunks)
        except ValueError:
            raise ValueError(
                f"[fusion] SPIKE_CHUNKS = {raw_chunks!r} is not a "
                "recognized value (auto or an integer >= 2)")
        if spike_chunks < 2:
            raise ValueError(
                f"[fusion] SPIKE_CHUNKS = {spike_chunks} must be >= 2 "
                "(1 chunk is the sequential composition)")
    dtype = _choice("precision", "SOLVE_DTYPE", "auto",
                    ("auto", "f64") + SOLVE_DTYPES)
    if dtype in ("auto", "f64"):
        dtype = "native"
    raw_sweeps = config["precision"].get("REFINE_SWEEPS", "auto") \
        if config.has_section("precision") else "auto"
    raw_sweeps = raw_sweeps.strip().lower()
    if raw_sweeps in ("auto", ""):
        sweeps = _AUTO_SWEEPS[dtype]
    else:
        try:
            sweeps = int(raw_sweeps)
        except ValueError:
            raise ValueError(
                f"[precision] REFINE_SWEEPS = {raw_sweeps!r} is not a "
                "recognized value (auto or an integer >= 0)")
        if sweeps < 0:
            raise ValueError(
                f"[precision] REFINE_SWEEPS = {sweeps} must be >= 0")
    raw_tol = config["precision"].get("REFINE_TOL", "auto") \
        if config.has_section("precision") else "auto"
    raw_tol = raw_tol.strip().lower()
    if raw_tol in ("auto", ""):
        tol = 0.0
    else:
        try:
            tol = float(raw_tol)
        except ValueError:
            raise ValueError(
                f"[precision] REFINE_TOL = {raw_tol!r} is not a "
                "recognized value (auto or a float >= 0)")
        if tol < 0.0:
            raise ValueError(
                f"[precision] REFINE_TOL = {tol} must be >= 0")
    mmt = _choice("precision", "MMT_DTYPE", "auto",
                  ("auto",) + SOLVE_DTYPES)
    if mmt == "auto":
        mmt = "native"
    plan = SolvePlan(composition=comp, spike_chunks=spike_chunks,
                     dtype=dtype, sweeps=sweeps, tol=tol, mmt_dtype=mmt)
    cell = getattr(decision, "cell", None)
    if cell is not None and not solve_knobs_pinned():
        plan = apply_decision(plan, cell)
    return plan


def resolve_solve_plan_for_ops(kind, n):
    """Tuner-aware plan resolution for BARE ops constructions
    (BandedOps/DenseOps built without a solver threading a plan in,
    libraries/pencilops.py fallback paths): the same heuristics as
    `resolve_solve_plan`, but layered with any in-process autotune
    decision registered for (`kind`, system size `n`) — so a bare-ops
    build and a solver build can never silently pick different plans for
    the same shape."""
    decision = None
    if not solve_knobs_pinned():
        try:
            from ..tools import autotune
            decision = autotune.ops_decision(kind, n)
        except Exception:
            decision = None
    return resolve_solve_plan(decision=decision)


def solve_plan_token():
    """The solve-plan component of assembly-cache content keys (used
    when the solver carries no resolved plan — standalone builds)."""
    return resolve_solve_plan().token()


def low_dtype(name, native):
    """The storage dtype for ladder operators: `name` ('native'/'f32'/
    'bf16') applied to the problem's native pencil dtype. Complex
    problems map f32 -> complex64; bf16 has no complex variant and
    raises (at factor time — still inside the solver build)."""
    native = np.dtype(native)
    if name == "native":
        return native
    complex_ = np.issubdtype(native, np.complexfloating)
    if name == "f32":
        return np.dtype(np.complex64) if complex_ else np.dtype(np.float32)
    if name == "bf16":
        if complex_:
            raise ValueError(
                "[precision] SOLVE_DTYPE = bf16 has no complex variant; "
                "use f32 for complex pencil systems")
        return jnp.bfloat16
    raise ValueError(f"unknown solve dtype {name!r}")


def spike_chunk_count(m, configured):
    """Chunk count for a SPIKE partition of m recurrence steps:
    `configured` (from [fusion] SPIKE_CHUNKS; 0 = auto) clamped to the
    step count; auto targets sqrt(m) — the depth/flops balance point
    (depth C + GEMMs of size (m/C); both ~sqrt at the optimum)."""
    if m < 4:
        return 1        # degenerate: the sequential sweep is already flat
    if configured:
        return max(2, min(int(configured), m))
    return max(2, min(int(round(np.sqrt(m))), m))


# --------------------------------------------------------- parallel prefix

def ascan_apply(A, B, C, D, u, v0):
    """Solve the affine recurrence/output system

        v_{j+1} = A_j @ v_j + B_j @ u_j,   v_0 = v0
        out_j   = C_j @ v_j + D_j @ u_j            (v_j = PRE-step state)

    for all j = 0..m-1 as a parallel prefix over (A, b) pairs via
    `lax.associative_scan` with the matmul combine — O(log m) sequential
    depth, no `lax.scan` in the lowered program. Shapes: A (m, G, s, s),
    B (m, G, s, kin), C (m, G, o, s), D (m, G, o, kin), u (m, G, kin, k),
    v0 (G, s, k). Returns (outs (m, G, o, k), v_final (G, s, k))."""
    b = B @ u                                   # (m, G, s, k)
    # fold v0 into the first element so prefix b-components ARE the states
    b = jnp.concatenate([(A[0] @ v0 + b[0])[None], b[1:]], axis=0)

    def combine(prev, nxt):
        A1, b1 = prev
        A2, b2 = nxt
        return A2 @ A1, A2 @ b1 + b2

    _, states = jax.lax.associative_scan(combine, (A, b), axis=0)
    # states[j] = v_{j+1}; outputs consume the PRE-step states v_0..v_{m-1}
    v_pre = jnp.concatenate([v0[None], states[:-1]], axis=0)
    return C @ v_pre + D @ u, states[-1]


# ------------------------------------------------------------------- SPIKE

def spike_precompose(A, B, C, D, n_chunks):
    """Factor-time SPIKE operators for the affine system of
    `ascan_apply`: the m steps split into C chunks of L = ceil(m/C)
    (identity-padded), and the within-chunk transfer products fold into
    dense per-chunk GEMM operators

        Y  (C, G, L*o, L*kin)  block-lower-triangular input->output map
        YH (C, G, L*o, s)      chunk-inflow -> output correction
        P  (C, G, s, L*kin)    input -> chunk-end particular state
        T  (C, G, s, s)        chunk transfer (propagator product)

    so `spike_apply` solves all chunks as one batched GEMM program
    coupled through a C-step reduced recurrence. The builder is pure jnp
    (traced at factor time, vmap/chunk-map safe); cost O(L^2) batched
    (s x s) matmuls — factor-time, amortized over the step loop."""
    m, G = A.shape[:2]
    s = A.shape[2]
    kin = B.shape[3]
    o = C.shape[2]
    L = -(-m // n_chunks)
    m_pad = n_chunks * L
    dtype = A.dtype

    def pad(arr, fill_eye=False):
        if m_pad == m:
            return arr
        tail_shape = (m_pad - m, G) + arr.shape[2:]
        if fill_eye:
            tail = jnp.broadcast_to(jnp.eye(s, dtype=dtype), tail_shape)
        else:
            tail = jnp.zeros(tail_shape, dtype=dtype)
        return jnp.concatenate([arr, tail], axis=0)

    def chunked(arr):
        # (m_pad, G, r, c) -> (C, L, G, r, c): local step j = axis 1
        return arr.reshape((n_chunks, L, G) + arr.shape[2:])

    Ac = chunked(pad(A, fill_eye=True))
    Bc = chunked(pad(B))
    Cc = chunked(pad(C))
    Dc = chunked(pad(D))
    zero_blk = jnp.zeros((n_chunks, G, o, kin), dtype=dtype)
    rows = []
    yh = []
    carr = []   # carr[r] = (prod_{r < i <= j} A_i) @ B_r, per chunk/group
    H = jnp.broadcast_to(jnp.eye(s, dtype=dtype), (n_chunks, G, s, s))
    for j in range(L):
        Aj, Bj, Cj, Dj = Ac[:, j], Bc[:, j], Cc[:, j], Dc[:, j]
        row = [Cj @ c for c in carr] + [Dj] + [zero_blk] * (L - 1 - j)
        rows.append(jnp.concatenate(row, axis=-1))    # (C, G, o, L*kin)
        yh.append(Cj @ H)
        carr = [Aj @ c for c in carr] + [Bj]
        H = Aj @ H
    Y = jnp.stack(rows, axis=2).reshape(n_chunks, G, L * o, L * kin)
    YH = jnp.stack(yh, axis=2).reshape(n_chunks, G, L * o, s)
    P = jnp.concatenate(carr, axis=-1)                # (C, G, s, L*kin)
    return {"Y": Y, "YH": YH, "P": P, "T": H}


def spike_apply(ops, u, v0):
    """Solve the `ascan_apply` system against factor-time SPIKE
    operators: two batched GEMMs over all chunks plus the C-step reduced
    recurrence — the only sequential scan left, length C (the DTP106
    depth contract). u (m, G, kin, k), v0 (G, s, k); returns
    (outs (m, G, o, k), v_final (G, s, k))."""
    Y, YH, P, T = ops["Y"], ops["YH"], ops["P"], ops["T"]
    m, G, kin, k = u.shape
    n_chunks = Y.shape[0]
    s = T.shape[-1]
    L = P.shape[-1] // kin
    o = Y.shape[2] // L
    m_pad = n_chunks * L
    if m_pad > m:
        u = jnp.concatenate(
            [u, jnp.zeros((m_pad - m, G, kin, k), dtype=u.dtype)], axis=0)
    # (m_pad, G, kin, k) -> (C, G, L*kin, k) in local-step-major order
    uc = u.reshape(n_chunks, L, G, kin, k).transpose(0, 2, 1, 3, 4)
    uc = uc.reshape(n_chunks, G, L * kin, k)
    pend = P @ uc                                     # (C, G, s, k)

    def body(v, xs):
        Tc, pc = xs
        return Tc @ v + pc, v                         # emit chunk INFLOW

    v_final, v_in = jax.lax.scan(body, v0.astype(u.dtype), (T, pend))
    outs = Y @ uc + YH @ v_in                         # (C, G, L*o, k)
    outs = outs.reshape(n_chunks, G, L, o, k).transpose(0, 2, 1, 3, 4)
    outs = outs.reshape(m_pad, G, o, k)[:m]
    return outs, v_final
