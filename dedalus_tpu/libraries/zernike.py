"""
Zernike / generalized-Gegenbauer radial polynomials for the disk (dim=2) and
ball (dim=3) (reference: dedalus/libraries/dedalus_sphere/zernike.py — same
capabilities, different construction).

Radial coordinate r on [0, 1] (the basis applies an affine radius scaling),
spectral variable z = 2 r^2 - 1. For weight parameter k and generalized
degree l, the radial functions are

    Q_n^{(k,l)}(r) = c * r^l * Phat_n^{(k, b)}(z),    b = l + dim/2 - 1,
    c = 2^{(k + b)/2 + 1},

with Phat the orthonormal Jacobi polynomials of tools.jacobi. They are
orthonormal under the dim-D radial measure

    integral_0^1 Q_n Q_n' (1 - r^2)^k r^{dim-1} dr = delta_{nn'}.

As in libraries.sphere, every operator matrix is assembled by Gauss-Jacobi
quadrature of the analytic operator applied to recurrence-evaluated basis
functions — exact to roundoff, convention-proof.

Radial ladder operators with connection exponent mu (for the disk,
mu = m + s; for the ball, the regularity machinery supplies mu):

    D_{+-} g = (1/sqrt(2)) (d/dr -+ mu/r) g

which map degree l -> l +- 1 (whichever of |mu +- 1| applies) and raise the
weight k -> k+1 (reference: dedalus_sphere/zernike.py ZernikeOperator.__D).
"""

import numpy as np

from ..tools import jacobi
from ..tools.cache import cached_function


def _b(dim, l):
    return l + dim / 2 - 1


def _norm_constant(dim, k, l):
    return 2.0 ** ((k + _b(dim, l)) / 2 + 1)


def _measure_logfactor(dim, k, l):
    """log2 of the z-measure prefactor: dmu = (1-z)^k (1+z)^{dim/2-1} dz / 2^f
    with the envelope (1+z)^l split off."""
    return l + k + dim / 2 + 1


@cached_function
def quadrature(dim, N, k=0):
    """
    Nodes z and weights w with sum(w f(z)) = integral_0^1 f(z(r))
    (1-r^2)^k r^{dim-1} dr, exact for polynomial f of degree < 2N
    (reference: dedalus_sphere/zernike.py:11 quadrature).
    """
    b = dim / 2 - 1
    z = jacobi.build_grid(N, k, b)
    w = jacobi.build_weights(N, k, b) / 2 ** (k + dim / 2 + 1)
    return z, w


def grid(dim, N, k=0):
    """Radial grid points r in (0, 1), ascending."""
    z, _ = quadrature(dim, N, k)
    return np.sqrt((1 + z) / 2)


def polynomials(dim, n, k, l, z):
    """
    Evaluate Q_0..Q_{n-1}^{(k,l)} at points z. Shape (n, len(z))
    (reference: dedalus_sphere/zernike.py:27 polynomials).
    """
    z = np.atleast_1d(np.asarray(z, dtype=np.float64))
    if n <= 0:
        return np.zeros((0, z.size))
    env = ((1 + z) / 2) ** (l / 2)
    P = jacobi.build_polynomials(n, k, _b(dim, l), z)
    return _norm_constant(dim, k, l) * env * P


def polynomials_and_r_derivatives(dim, n, k, l, z):
    """(Q, dQ/dr) at z; both (n, len(z)). Interior points only (r > 0)."""
    z = np.atleast_1d(np.asarray(z, dtype=np.float64))
    if n <= 0:
        return np.zeros((0, z.size)), np.zeros((0, z.size))
    r = np.sqrt((1 + z) / 2)
    b = _b(dim, l)
    env = ((1 + z) / 2) ** (l / 2)
    P = jacobi.build_polynomials(n, k, b, z)
    dP = jacobi.build_polynomial_derivatives(n, k, b, z)
    c = _norm_constant(dim, k, l)
    Q = c * env * P
    # dz/dr = 4r; d(env)/dr = (l/r) env
    dQ = (l / r) * Q + c * env * dP * 4 * r
    return Q, dQ


def _project(dim, n_out, k_out, l_out, values_fn, n_in, extra=2):
    """
    M[j, i] = <Q_out_j, F_i>_{mu_{k_out}} by Gauss-Jacobi quadrature, where
    F_i = values_fn(z)[i] must equal r^{l_out} * polynomial.
    """
    if n_out <= 0 or n_in <= 0:
        return np.zeros((max(n_out, 0), max(n_in, 0)))
    b = _b(dim, l_out)
    Nq = max(n_out, n_in) + extra
    zq = jacobi.build_grid(Nq, k_out, b)
    wq = jacobi.build_weights(Nq, k_out, b)
    env = ((1 + zq) / 2) ** (l_out / 2)
    Pout = jacobi.build_polynomials(n_out, k_out, b, zq)
    F = values_fn(zq)
    factor = _norm_constant(dim, k_out, l_out) / 2 ** _measure_logfactor(dim, k_out, l_out)
    return factor * (Pout * wq) @ (F / env).T


@cached_function
def conversion_matrix(dim, n, k, l, dk=1):
    """Connection matrix (k, l) -> (k + dk, l), shape (n, n)
    (reference: ZernikeOperator.__E)."""
    return _project(dim, n, k + dk, l, lambda z: polynomials(dim, n, k, l, z), n)


@cached_function
def ladder_matrix(dim, n, k, l_in, l_out, mu, ds):
    """
    Matrix of D_{ds} = (1/sqrt(2)) (d/dr - ds*mu/r): (k, l_in) -> (k+1, l_out),
    shape (n, n). l_out must be l_in +- 1 consistent with |mu + ds|.
    """
    assert ds in (+1, -1)
    assert l_out in (l_in + 1, l_in - 1)

    def values(z):
        Q, dQ = polynomials_and_r_derivatives(dim, n, k, l_in, z)
        r = np.sqrt((1 + z) / 2)
        return (dQ - ds * mu / r * Q) / np.sqrt(2)

    return _project(dim, n, k + 1, l_out, values, n)


@cached_function
def r2_multiplication_matrix(dim, n, k, l):
    """Multiplication by r^2 within (k, l): (n, n), tridiagonal in n."""
    def values(z):
        return (1 + z) / 2 * polynomials(dim, n, k, l, z)
    return _project(dim, n, k, l, values, n)


@cached_function
def interpolation_row(dim, n, k, l, r0=1.0):
    """Row (1, n): evaluate Q_n^{(k,l)} at radius r0 (e.g. the boundary)."""
    z0 = 2 * r0 ** 2 - 1
    return polynomials(dim, n, k, l, np.array([z0]))[:, 0][None, :]


@cached_function
def integration_row(dim, n, k, l):
    """Row (1, n): integral of each Q against the unweighted dim-D measure
    r^{dim-1} dr (for Integrate/Average). The r^l envelope is absorbed into
    the quadrature weight so half-integer powers (odd l) stay exact."""
    b_env = dim / 2 - 1 + l / 2
    Nq = n + 2
    z = jacobi.build_grid(Nq, 0, b_env)
    w = jacobi.build_weights(Nq, 0, b_env)
    P = jacobi.build_polynomials(n, k, _b(dim, l), z)
    factor = _norm_constant(dim, k, l) / 2 ** (l / 2 + dim / 2 + 1)
    return factor * (P @ w)[None, :]
