"""
Batched pencil matrix solvers (reference: dedalus/libraries/matsolvers.py).

The reference solves each pencil serially with SuperLU/UMFPACK on CPU
(libraries/matsolvers.py:71-285). Here the pencil index is a batch
dimension: factorizations and solves are batched dense LU on device (MXU),
with a banded/block-tridiagonal path as the large-N perf option.

Functional API so factorizations flow through jit as pytrees:
    aux = Solver.factor(matrices)   # (G, S, S) -> pytree of arrays
    x   = Solver.solve(aux, rhs)    # (G, S) -> (G, S)
"""

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from ..tools.metrics import scoped as _scoped

matsolvers = {}


def add_solver(cls):
    """Register a solver class by lowercase name (reference:
    libraries/matsolvers.py:11 add_solver), phase-labeling its factor/solve
    entry points for profiler traces."""
    for meth in ("factor", "solve", "solve_multi"):
        raw = cls.__dict__.get(meth)
        label = f"dedalus/matsolve/{cls.__name__}.{meth}"
        if isinstance(raw, staticmethod):
            setattr(cls, meth, staticmethod(_scoped(raw.__func__, label)))
        elif isinstance(raw, classmethod):
            setattr(cls, meth, classmethod(_scoped(raw.__func__, label)))
    matsolvers[cls.__name__.lower()] = cls
    return cls


@add_solver
class BatchedLUFactorized:
    """Batched dense LU with partial pivoting (default; the TPU analogue of
    the reference's SuperluColamdFactorizedTranspose default)."""

    @staticmethod
    def factor(matrices):
        return jsl.lu_factor(matrices)

    @staticmethod
    def solve(aux, rhs):
        return jsl.lu_solve(aux, rhs[..., None])[..., 0]

    @staticmethod
    def solve_multi(aux, rhs):
        return jsl.lu_solve(aux, rhs)


@add_solver
class BatchedInverse:
    """Precomputed batched inverse: each solve is one batched matmul on the
    MXU (reference SparseInverse/DenseInverse, libraries/matsolvers.py:223).
    Fastest per-step for moderate S; factorization cost is ~3x LU."""

    @staticmethod
    def factor(matrices):
        return jnp.linalg.inv(matrices)

    @staticmethod
    def solve(inv, rhs):
        return jnp.einsum("gij,gj->gi", inv, rhs)

    @staticmethod
    def solve_multi(inv, rhs):
        return jnp.matmul(inv, rhs)


@add_solver
class BatchedInverseRefined:
    """
    Mixed-precision solver for 64-bit problems on TPU: TPU LuDecomposition
    only implements F32/C64, so the inverse is computed in the low dtype
    and each solve is polished by iterative refinement with 64-bit
    residual matvecs (supported via emulation). The sweep count and the
    residual tolerance are CLASS attributes bound per solver build
    (`refined_ladder` below / `get_solver`) from the `[precision]` config
    — resolved at build time, never read inside traced code — and the
    refinement runs as a fixed-trip `lax.fori_loop` with
    tolerance-masked updates, so programs stay retrace-free while
    converged groups freeze. `residual()` is the telemetry probe
    (achieved relative residual per group).
    """

    iterations = 3        # overridden per build via refined_ladder()
    tol = 0.0             # 0: apply every sweep (the legacy behavior)
    low_name = "f32"      # 'f32' or 'bf16' (libraries/solvecomp.py)

    @classmethod
    def _low(cls, dtype):
        from .solvecomp import low_dtype
        return low_dtype(cls.low_name, dtype)

    @classmethod
    def factor(cls, matrices):
        inv_low = jnp.linalg.inv(matrices.astype(cls._low(matrices.dtype)))
        return (matrices, inv_low)

    @classmethod
    def solve(cls, aux, rhs):
        A, inv_low = aux
        low = cls._low(rhs.dtype)
        x = jnp.einsum("gij,gj->gi", inv_low,
                       rhs.astype(low)).astype(rhs.dtype)
        tol = cls.tol

        def sweep(_, x):
            r = rhs - jnp.einsum("gij,gj->gi", A, x)
            dx = jnp.einsum("gij,gj->gi", inv_low,
                            r.astype(low)).astype(rhs.dtype)
            if tol > 0.0:
                rn = jnp.max(jnp.abs(r), axis=-1, keepdims=True)
                bn = jnp.max(jnp.abs(rhs), axis=-1, keepdims=True)
                return jnp.where(rn > tol * bn, x + dx, x)
            return x + dx

        if cls.iterations > 0:
            # static bounds: lowers as a fixed-length loop (retrace-free
            # and reverse-mode differentiable through the adjoint funnel)
            x = jax.lax.fori_loop(0, cls.iterations, sweep, x)
        return x

    @classmethod
    def residual(cls, aux, x, rhs):
        """Achieved relative residual per group (device values; the
        `precision` telemetry/benchmark probe — off the step path)."""
        A, _ = aux
        r = rhs - jnp.einsum("gij,gj->gi", A, x)
        bn = jnp.max(jnp.abs(rhs), axis=-1)
        return jnp.max(jnp.abs(r), axis=-1) / jnp.where(bn == 0, 1.0, bn)


def refined_ladder(plan):
    """A per-build BatchedInverseRefined subclass bound to the resolved
    `[precision]` plan (libraries/solvecomp.SolvePlan): the dense arm of
    the precision ladder. Class attributes carry the schedule so the
    traced factor/solve bodies never read config (DTL008)."""
    low = plan.dtype if plan.dtype != "native" else "f32"
    sweeps = plan.sweeps if plan.sweeps is not None \
        else BatchedInverseRefined.iterations
    return type("BatchedInverseLadder", (BatchedInverseRefined,),
                {"iterations": int(sweeps), "tol": float(plan.tol),
                 "low_name": low})


@add_solver
class BatchedDenseSolve:
    """Factor-per-solve (reference ScipyDenseLU analogue); aux = matrices."""

    @staticmethod
    def factor(matrices):
        return matrices

    @staticmethod
    def solve(matrices, rhs):
        return jnp.linalg.solve(matrices, rhs[..., None])[..., 0]

    @staticmethod
    def solve_multi(matrices, rhs):
        return jnp.linalg.solve(matrices, rhs)


@add_solver
class DummySolver:
    """Testing solver returning zeros (reference: libraries/matsolvers.py:32)."""

    @staticmethod
    def factor(matrices):
        return matrices

    @staticmethod
    def solve(aux, rhs):
        return jnp.zeros_like(rhs)


def get_solver(spec):
    if spec is None:
        spec = "BatchedLUFactorized"
    cls = matsolvers[spec.lower()] if isinstance(spec, str) else spec
    if cls is BatchedInverseRefined:
        # bind the [precision] refinement schedule at build time (the
        # sweep count used to be a hardcoded class attribute): get_solver
        # runs in ops construction, before any program traces
        from .solvecomp import resolve_solve_plan
        return refined_ladder(resolve_solve_plan())
    return cls
