"""
Batched pencil matrix solvers (reference: dedalus/libraries/matsolvers.py).

The reference solves each pencil serially with SuperLU/UMFPACK on CPU
(libraries/matsolvers.py:71-285). Here the pencil index is a batch
dimension: factorizations and solves are batched dense LU on device (MXU),
with a banded/block-tridiagonal path as the large-N perf option.

Functional API so factorizations flow through jit as pytrees:
    aux = Solver.factor(matrices)   # (G, S, S) -> pytree of arrays
    x   = Solver.solve(aux, rhs)    # (G, S) -> (G, S)
"""

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from ..tools.metrics import scoped as _scoped

matsolvers = {}


def add_solver(cls):
    """Register a solver class by lowercase name (reference:
    libraries/matsolvers.py:11 add_solver), phase-labeling its factor/solve
    entry points for profiler traces."""
    for meth in ("factor", "solve", "solve_multi"):
        raw = cls.__dict__.get(meth)
        if isinstance(raw, staticmethod):
            label = f"dedalus/matsolve/{cls.__name__}.{meth}"
            setattr(cls, meth, staticmethod(_scoped(raw.__func__, label)))
    matsolvers[cls.__name__.lower()] = cls
    return cls


@add_solver
class BatchedLUFactorized:
    """Batched dense LU with partial pivoting (default; the TPU analogue of
    the reference's SuperluColamdFactorizedTranspose default)."""

    @staticmethod
    def factor(matrices):
        return jsl.lu_factor(matrices)

    @staticmethod
    def solve(aux, rhs):
        return jsl.lu_solve(aux, rhs[..., None])[..., 0]

    @staticmethod
    def solve_multi(aux, rhs):
        return jsl.lu_solve(aux, rhs)


@add_solver
class BatchedInverse:
    """Precomputed batched inverse: each solve is one batched matmul on the
    MXU (reference SparseInverse/DenseInverse, libraries/matsolvers.py:223).
    Fastest per-step for moderate S; factorization cost is ~3x LU."""

    @staticmethod
    def factor(matrices):
        return jnp.linalg.inv(matrices)

    @staticmethod
    def solve(inv, rhs):
        return jnp.einsum("gij,gj->gi", inv, rhs)

    @staticmethod
    def solve_multi(inv, rhs):
        return jnp.matmul(inv, rhs)


@add_solver
class BatchedInverseRefined:
    """
    Mixed-precision solver for 64-bit problems on TPU: TPU LuDecomposition
    only implements F32/C64, so the inverse is computed in 32-bit and each
    solve is polished by iterative refinement with 64-bit residual matvecs
    (supported via emulation). 3 refinement sweeps recover ~f64 accuracy for
    condition numbers well past the f32 limit.
    """

    iterations = 3

    @staticmethod
    def _low(dtype):
        return jnp.complex64 if jnp.issubdtype(dtype, jnp.complexfloating) \
            else jnp.float32

    @staticmethod
    def factor(matrices):
        inv32 = jnp.linalg.inv(matrices.astype(
            BatchedInverseRefined._low(matrices.dtype)))
        return (matrices, inv32)

    @staticmethod
    def solve(aux, rhs):
        A, inv32 = aux
        low = BatchedInverseRefined._low(rhs.dtype)
        x = jnp.einsum("gij,gj->gi", inv32, rhs.astype(low)).astype(rhs.dtype)
        for _ in range(BatchedInverseRefined.iterations):
            r = rhs - jnp.einsum("gij,gj->gi", A, x)
            dx = jnp.einsum("gij,gj->gi", inv32, r.astype(low)).astype(rhs.dtype)
            x = x + dx
        return x


@add_solver
class BatchedDenseSolve:
    """Factor-per-solve (reference ScipyDenseLU analogue); aux = matrices."""

    @staticmethod
    def factor(matrices):
        return matrices

    @staticmethod
    def solve(matrices, rhs):
        return jnp.linalg.solve(matrices, rhs[..., None])[..., 0]

    @staticmethod
    def solve_multi(matrices, rhs):
        return jnp.linalg.solve(matrices, rhs)


@add_solver
class DummySolver:
    """Testing solver returning zeros (reference: libraries/matsolvers.py:32)."""

    @staticmethod
    def factor(matrices):
        return matrices

    @staticmethod
    def solve(aux, rhs):
        return jnp.zeros_like(rhs)


def get_solver(spec):
    if spec is None:
        spec = "BatchedLUFactorized"
    if isinstance(spec, str):
        return matsolvers[spec.lower()]
    return spec
