"""
Double-double (f32 x 2) arithmetic for emulated float64 on TPU.

The reference framework is float64/complex128 end-to-end (reference:
dedalus/tools/config.py dtype defaults; SURVEY.md §7 hard part 7). TPU
hardware has no f64 matrix unit — XLA:TPU emulates f64 on the scalar/
vector path at a large slowdown, and the MXU only speaks bf16/int8 — so
`dtype=np.float64` problems route their pencil compute through this
module: values travel as unevaluated sums hi + lo of two float32s
(~49 mantissa bits), elementwise operations evaluate in (emulated) f64
VALUE space, and matrix products run on the MXU via an Ozaki-style int8
slice decomposition with exact int32 accumulation.

Representation: a `DD` pytree holding (hi, lo) f32 arrays with
|lo| <= ulp(hi)/2. All functions are pure jnp and safe under jit/vmap/scan.

Design note — why value-space f64 instead of error-free transformations:
the classical EFT formulations (Knuth two-sum, Dekker split/product) are
algebraically-exact cancellation patterns, and this XLA backend breaks
them under jit: optimization barriers are stripped, producers are
rematerialized into consumer fusions with different contraction, and
mixed f32/f64 convert chains are excess-precision-folded — each of which
silently zeroes the captured rounding term (observed: a hard 3.7e-8
error floor on scalar-operand dd_mul, identical across three EFT
variants). Computing each elementwise op as

    v = f64(a.hi) + f64(a.lo) (exact)  ->  op in f64  ->  split back
    hi = f32(v), lo = f32(v - f64(hi))

has no fragile cancellation: one f64 rounding per op (2^-53, below the
pair's 2^-49 capacity) and the split is compiler-stable (verified under
jit against scalar, splat, and array operands). The pair format is kept
as the storage/interchange type because the matmul path needs it.

dd_matmul — C = A @ B in ~f64 precision: each operand is row/column
exponent-normalized and sliced into SLICES signed-7-bit int8 planes
(slice p carries bits [7p, 7p+7)); slice-pair products run as int8
dot_generals with int32 accumulation (exact for k <= 2^16), and the
int32 partial sums are recombined in f64 with per-level power-of-two
scales. MXU cost: SLICES*(SLICES+1)/2 int8 matmuls.

References (public literature): Dekker 1971; Hida, Li & Bailey 2001 (qd);
Ozaki et al. 2012 / Ootomo & Yokota 2022 (error-free matmul slicing on
low-precision units).
"""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "DD", "dd_from_f64", "dd_to_f64", "dd_zeros",
    "two_sum", "quick_two_sum", "two_prod",
    "dd_add", "dd_sub", "dd_neg", "dd_mul", "dd_scale", "dd_div",
    "dd_add_f32", "dd_mul_f32", "dd_abs_hi",
    "dd_matmul", "dd_slices_from_f64",
]

_F32 = jnp.float32
# this library IS the f64 emulation layer: the wide dtype is its subject,
# not a precision-funnel bypass
_F64 = jnp.float64  # dedalus-lint: disable=DTL004


@jax.tree_util.register_pytree_node_class
class DD:
    """Unevaluated f32 sum hi + lo (|lo| <= ulp(hi)/2 when normalized)."""

    __slots__ = ("hi", "lo")

    def __init__(self, hi, lo):
        self.hi = hi
        self.lo = lo

    @property
    def shape(self):
        return jnp.shape(self.hi)

    @property
    def ndim(self):
        return jnp.ndim(self.hi)

    def __getitem__(self, idx):
        return DD(self.hi[idx], self.lo[idx])

    def reshape(self, *shape):
        return DD(jnp.reshape(self.hi, shape), jnp.reshape(self.lo, shape))

    def tree_flatten(self):
        return (self.hi, self.lo), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"DD(hi={self.hi!r}, lo={self.lo!r})"


# ------------------------------------------------------ value-space bridge

def _to64(a):
    """DD -> f64 value (exact: both components are f32)."""
    return jnp.asarray(a.hi, _F64) + jnp.asarray(a.lo, _F64)


def _from64(v):
    """f64 value -> normalized DD (exact two-term split)."""
    hi = v.astype(_F32)
    lo = (v - hi.astype(_F64)).astype(_F32)
    return DD(hi, lo)


def dd_split_host(x):
    """Host float64 numpy -> (hi, lo) f32 NUMPY pair (exact split). The
    single implementation of the split convention — device-array callers
    use dd_from_f64/_from64, which share it semantically."""
    x = np.asarray(x, dtype=np.float64)
    hi = x.astype(np.float32)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def dd_from_f64(x):
    """Host float64 numpy -> DD of f32 pairs (exact 2-term split)."""
    hi, lo = dd_split_host(x)
    return DD(jnp.asarray(hi), jnp.asarray(lo))


def dd_to_f64(a):
    """DD -> host float64 numpy (for verification / output)."""
    return (np.asarray(a.hi, dtype=np.float64)
            + np.asarray(a.lo, dtype=np.float64))


def dd_zeros(shape):
    z = jnp.zeros(shape, dtype=_F32)
    return DD(z, z)


# ------------------------------------------------------------ error-free ops
# Kept for compatibility/tests; implemented through the f64 bridge (the
# returned (s, e) pair represents a+b / a*b to f64 accuracy).

def two_sum(a, b):
    """a + b = s + e (s = f32 round, e = the f64-exact remainder)."""
    v = jnp.asarray(a, _F64) + jnp.asarray(b, _F64)
    s = v.astype(_F32)
    e = (v - s.astype(_F64)).astype(_F32)
    return s, e


quick_two_sum = two_sum


def two_prod(a, b):
    """a * b = p + e exactly (f32 products are exact in f64)."""
    v = jnp.asarray(a, _F64) * jnp.asarray(b, _F64)
    p = v.astype(_F32)
    e = (v - p.astype(_F64)).astype(_F32)
    return p, e


# --------------------------------------------------------------- dd algebra

def dd_add(a, b):
    return _from64(_to64(a) + _to64(b))


def dd_neg(a):
    return DD(-a.hi, -a.lo)


def dd_sub(a, b):
    return _from64(_to64(a) - _to64(b))


def dd_add_f32(a, b):
    """DD + f32 array/scalar."""
    return _from64(_to64(a) + jnp.asarray(b, _F64))


def dd_mul(a, b):
    """DD * DD."""
    return _from64(_to64(a) * _to64(b))


def dd_mul_f32(a, b):
    """DD * f32 array/scalar."""
    return _from64(_to64(a) * jnp.asarray(b, _F64))


def dd_scale(a, pow2):
    """DD * exact power of two (exact; no renormalization needed)."""
    return DD(a.hi * pow2, a.lo * pow2)


def dd_div(a, b):
    """DD / DD."""
    return _from64(_to64(a) / _to64(b))


def dd_abs_hi(a):
    return jnp.abs(a.hi)


# --------------------------------------------------- Ozaki int8 slice matmul

SLICE_BITS = 7          # signed slice width: values in [-64, 64]
DEFAULT_SLICES = 8      # 8 * 7 = 56 bits >= f64's 53


def _exact_pow2(n):
    """2^n as f32 for integer array n in [-126, 127], EXACTLY — via the
    exponent bit field. (jnp.exp2 is a polynomial approximation and is
    NOT exact even at integer arguments; an inexact scale here breaks
    the error-free slice decomposition.)"""
    n = jnp.clip(n, -126, 127)
    return jax.lax.bitcast_convert_type(
        ((n + 127) << 23).astype(jnp.int32), jnp.float32)


def _exponent_scale(mag):
    """For f64 mag = max |value| along the contraction axis: returns an
    exact power-of-two f64 s with s * mag <= 1/2 (1 where mag == 0).
    Lines whose magnitude exceeds the f32-representable scale range
    (|v| >= 2^125, where the needed s would clip) poison to NaN so a
    blown-up state reads as non-finite instead of int8-wrapped garbage."""
    _, e = jnp.frexp(mag)
    s = _exact_pow2(-(e + 1)).astype(_F64)
    s = jnp.where(mag >= 2.0 ** 125, jnp.float64(np.nan), s)  # dedalus-lint: disable=DTL004
    return jnp.where(mag > 0, s, jnp.float64(1.0))  # dedalus-lint: disable=DTL004


def _dd_slices(x, axis, slices):
    """Exponent-normalize DD `x` along `axis` and slice into int8 planes.

    Returns (planes, inv_scale): planes int8 (slices,) + x.shape with
    plane p holding rint(R_p * 2^(7(p+1))) for the running remainder R,
    and inv_scale f32 per-line factor such that
        value = inv_scale * sum_p planes[p] * 2^-(7(p+1)).
    The extraction runs in f64 value space (exact: power-of-two scales,
    integer-valued subtractions; |R_p| <= 2^-(7p+1))."""
    v = _to64(x)
    mag = jnp.max(jnp.abs(v), axis=axis, keepdims=True)
    s = _exponent_scale(mag)
    r = v * s                                # exact pow2 scale, |r| <= 1/2
    planes = []
    for p in range(slices):
        sc = np.float64(2.0 ** (SLICE_BITS * (p + 1)))
        q = jnp.rint(r * sc)                 # |q| <= 64
        planes.append(q.astype(jnp.int8))
        r = r - q / sc                       # exact
    planes = jnp.stack(planes)
    return planes, (1.0 / s).astype(_F32)


def dd_slices_from_f64(M, slices=DEFAULT_SLICES, axis=-1):
    """HOST-side exact slice decomposition of a float64 numpy matrix for
    reuse across many dd_matmul calls (e.g. cached transform matrices).

    Returns (planes int8 (slices,)+M.shape, inv_scale f32 per-line).
    Normalization is along `axis` (the contraction axis of the intended
    product)."""
    M = np.asarray(M, dtype=np.float64)
    mag = np.max(np.abs(M), axis=axis, keepdims=True)
    with np.errstate(divide="ignore"):
        e = np.ceil(np.log2(mag, where=mag > 0,
                            out=np.zeros_like(mag))) + 1
    s = np.where(mag > 0, 2.0 ** -e, 1.0)
    # ensure s*mag <= 1/2 despite log2 edge cases (mag an exact pow2)
    bad = s * mag > 0.5
    s = np.where(bad, s / 2, s)
    r = M * s
    planes = np.empty((slices,) + M.shape, dtype=np.int8)
    for p in range(slices):
        sc = 2.0 ** (SLICE_BITS * (p + 1))
        q = np.rint(r * sc)
        planes[p] = q.astype(np.int8)
        r = r - q / sc
    return planes, (1.0 / s).astype(np.float32)


def _plane_dot(ap, bp, dims):
    return jax.lax.dot_general(ap, bp, dims,
                               preferred_element_type=jnp.int32)


def dd_matmul(A, B, slices=DEFAULT_SLICES, b_planes=None, a_planes=None):
    """C = A @ B in ~f64 precision. A: DD (..., m, k), B: DD (..., k, n)
    — 2-D or batched 3-D with matching leading dims.

    Either operand may be pre-sliced (pass (planes, inv_scale) from
    `dd_slices_from_f64` via a_planes/b_planes; planes must already be
    device arrays or lifted constants). Exactness budget: int32
    accumulation is exact for k <= 2^16 with 7-bit slices; levels
    p+q >= `slices` are dropped (below 2^-(7*slices) relative).
    """
    nd = A.ndim if a_planes is None else a_planes[0].ndim - 1
    if a_planes is None:
        ap, a_inv = _dd_slices(A, axis=-1, slices=slices)
    else:
        ap, a_inv = a_planes
    if b_planes is None:
        bp, b_inv = _dd_slices(B, axis=-2, slices=slices)
    else:
        bp, b_inv = b_planes
    batch = tuple(range(nd - 2))
    # contraction over k: A (..., m, k) x B (..., k, n); planes prepend a
    # slice axis which we index in python (static small loop)
    dims = (((nd - 1,), (nd - 2,)), (batch, batch))
    # sum int32 plane products per level (exact), recombine in f64 from
    # the lowest-order level up so small terms are absorbed first
    level_terms = {}
    for p in range(slices):
        for q in range(slices - p):
            d = _plane_dot(ap[p], bp[q], dims)
            level_terms.setdefault(p + q, []).append(d)
    C = None
    for lev in sorted(level_terms, reverse=True):
        tot = level_terms[lev][0]
        for extra in level_terms[lev][1:]:
            tot = tot + extra              # int32 adds: exact
        term = tot.astype(_F64) * np.float64(2.0 ** (-SLICE_BITS * (lev + 2)))
        C = term if C is None else C + term
    # undo the per-line normalizations: rows of A (axis -2 of C), cols of B
    a_inv_c = jnp.squeeze(jnp.asarray(a_inv, _F64), axis=-1)[..., :, None]
    b_inv_c = jnp.squeeze(jnp.asarray(b_inv, _F64), axis=-2)[..., None, :]
    return _from64(C * a_inv_c * b_inv_c)
