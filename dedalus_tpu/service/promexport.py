"""
Prometheus text-exposition rendering of the daemon's stats surface.

`render_stats(stats, hists)` turns the `SolverService.stats()` dict —
request/error counters, warm-pool occupancy, fault/breaker/queue state,
continuous-batching occupancy, per-error-code counts — plus the
daemon's LogHistograms (tools/tracing.py) into Prometheus text
exposition format 0.0.4: the pull-side contract a replica router or any
standard scraper consumes (`stats --prom` frame, or GET /metrics on
`[service] METRICS_PORT`; docs/observability.md#scraping-the-daemon has
the metric-name reference table).

LogHistograms map to NATIVE Prometheus histograms, not summaries: the
log-bucket upper bound `_LOG_FLOOR * _LOG_BASE**b` becomes the `le`
label, counts are re-emitted cumulatively, `+Inf` carries the total and
`_sum` the accumulated seconds — so `histogram_quantile()` works on the
scrape exactly like `LogHistogram.percentile()` works in-process.

`validate_exposition(text)` is the in-repo format validator (no
external deps by policy): HELP/TYPE discipline, name/label/value
syntax, duplicate sample detection, and histogram completeness
(cumulative non-decreasing buckets, a `+Inf` bucket equal to `_count`,
a `_sum` sample). Tests pin every rendered surface through it.
"""

import math
import re

from ..tools import tracing

__all__ = ["render_stats", "render_router_stats", "render_histogram",
           "validate_exposition"]

_PREFIX = "dedalus"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# one sample line: name{labels} value — labels optional, timestamp not
# emitted by this module (and rejected lax-ly by the validator)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+))?$")
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"\s*(?:,|$)')


def _fmt_value(value):
    if value is None:
        return None
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "+Inf" if value > 0 else "-Inf"
        return repr(value)
    return None


def _escape_label(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _Writer:
    """Accumulates one exposition: HELP/TYPE header then samples, one
    family at a time (the format requires family grouping)."""

    def __init__(self):
        self.lines = []

    def family(self, name, mtype, help_text, samples):
        """samples: [(labels dict or None, value), ...]; None values are
        skipped (a stats field a build lacks simply is not exported)."""
        rendered = []
        for labels, value in samples:
            text = _fmt_value(value)
            if text is None:
                continue
            if labels:
                body = ",".join(f'{k}="{_escape_label(v)}"'
                                for k, v in sorted(labels.items()))
                rendered.append(f"{name}{{{body}}} {text}")
            else:
                rendered.append(f"{name} {text}")
        if not rendered:
            return
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {mtype}")
        self.lines.extend(rendered)

    def text(self):
        return "\n".join(self.lines) + "\n" if self.lines else "\n"


def _bucket_upper(bucket):
    """Upper bound of LogHistogram bucket b (its `le` label): bucket 0
    holds <= _LOG_FLOOR, bucket b holds (floor*base^(b-1), floor*base^b].
    """
    return tracing._LOG_FLOOR * tracing._LOG_BASE ** bucket


def _hist_fields(hist):
    """(counts, total, sum) off a LogHistogram or a snapshot dict of one
    (the server snapshots under its counters lock; tests pass dicts)."""
    if isinstance(hist, dict):
        counts = hist.get("counts") or {}
        return ({int(k): int(v) for k, v in counts.items()},
                int(hist.get("total") or 0), float(hist.get("sum") or 0.0))
    return (dict(hist.counts), hist.total, hist.sum)


def render_histogram(writer, name, hist, help_text):
    """One native Prometheus histogram family from a LogHistogram:
    cumulative `_bucket{le=...}` samples at the log-bucket upper bounds,
    `+Inf` = `_count` = total observations, `_sum` = accumulated
    seconds. An empty histogram still renders (all-zero scrape targets
    beat absent ones for rate() continuity)."""
    counts, total, total_sum = _hist_fields(hist)
    writer.lines.append(f"# HELP {name} {help_text}")
    writer.lines.append(f"# TYPE {name} histogram")
    seen = 0
    for bucket in sorted(counts):
        seen += counts[bucket]
        le = _fmt_value(_bucket_upper(bucket))
        writer.lines.append(f'{name}_bucket{{le="{le}"}} {seen}')
    writer.lines.append(f'{name}_bucket{{le="+Inf"}} {total}')
    writer.lines.append(f"{name}_sum {_fmt_value(float(total_sum))}")
    writer.lines.append(f"{name}_count {total}")


def render_stats(stats, hists=None):
    """The whole exposition from one `SolverService.stats()` dict plus
    optional {suffix: LogHistogram-or-snapshot} latency histograms."""
    stats = stats or {}
    pool = stats.get("pool") or {}
    faults = stats.get("faults") or {}
    breaker = faults.get("breaker") or {}
    batching = (stats.get("serving") or {}).get("batching") or {}
    w = _Writer()
    p = _PREFIX

    w.family(f"{p}_up", "gauge",
             "1 while the daemon is serving.", [(None, 1)])
    w.family(f"{p}_uptime_seconds", "gauge",
             "Seconds since the daemon bound its socket.",
             [(None, stats.get("uptime_sec"))])
    w.family(f"{p}_draining", "gauge",
             "1 once a graceful drain began (new work is refused).",
             [(None, stats.get("draining") is not None)])
    w.family(f"{p}_requests_served_total", "counter",
             "Run requests completed successfully.",
             [(None, stats.get("requests_served"))])
    w.family(f"{p}_errors_total", "counter",
             "Requests answered with a structured error frame.",
             [(None, stats.get("errors"))])
    w.family(f"{p}_errors_by_code_total", "counter",
             "Structured error frames by protocol error code.",
             [({"code": code}, count)
              for code, count in sorted(
                  (faults.get("error_codes") or {}).items())])

    # ---- warm pool
    w.family(f"{p}_pool_entries", "gauge",
             "Warm solver entries currently pooled.",
             [(None, len(pool.get("entries") or ())
               if "entries" in pool else None)])
    w.family(f"{p}_pool_capacity", "gauge",
             "Configured warm-pool capacity.", [(None, pool.get("size"))])
    w.family(f"{p}_pool_hits_total", "counter",
             "Pool acquisitions served warm (hit or warm-cache).",
             [(None, pool.get("hits"))])
    w.family(f"{p}_pool_misses_total", "counter",
             "Pool acquisitions that required a cold build.",
             [(None, pool.get("misses"))])
    w.family(f"{p}_pool_evictions_total", "counter",
             "Pool entries evicted (LRU or memory watermark).",
             [(None, pool.get("evictions"))])
    w.family(f"{p}_pool_resets_total", "counter",
             "Pooled solver state resets between requests.",
             [(None, pool.get("resets"))])

    # ---- admission / faults
    w.family(f"{p}_queue_depth_limit", "gauge",
             "Admission queue depth limit.",
             [(None, faults.get("queue_depth"))])
    w.family(f"{p}_queued_runs", "gauge",
             "Run requests currently queued for the executor.",
             [(None, faults.get("queued"))])
    w.family(f"{p}_shed_total", "counter",
             "Requests refused at admission (queue full).",
             [(None, faults.get("shed"))])
    w.family(f"{p}_deadline_exceeded_total", "counter",
             "Requests dropped for exceeding their deadline.",
             [(None, faults.get("deadline_exceeded"))])
    w.family(f"{p}_watchdog_fires_total", "counter",
             "Executor watchdog fires (wedged run abandoned).",
             [(None, faults.get("watchdog_fires"))])
    w.family(f"{p}_client_drops_total", "counter",
             "Client connections lost mid-run.",
             [(None, faults.get("client_drops"))])
    w.family(f"{p}_mem_evictions_total", "counter",
             "Warm entries evicted by the RSS watermark.",
             [(None, faults.get("mem_evictions"))])
    w.family(f"{p}_replays_total", "counter",
             "Idempotent retries served from the result cache.",
             [(None, faults.get("replays"))])
    w.family(f"{p}_result_cache_entries", "gauge",
             "Completed results held for idempotent replay.",
             [(None, faults.get("result_cache"))])

    # ---- circuit breaker
    w.family(f"{p}_breaker_opens_total", "counter",
             "Circuit-breaker opens (per-spec failure threshold hit).",
             [(None, breaker.get("opens"))])
    w.family(f"{p}_breaker_closes_total", "counter",
             "Circuit-breaker closes after a cool-off probe succeeded.",
             [(None, breaker.get("closes"))])
    w.family(f"{p}_breaker_fastfails_total", "counter",
             "Requests fast-failed by an open circuit.",
             [(None, breaker.get("fastfails"))])
    w.family(f"{p}_breaker_open_circuits", "gauge",
             "Spec circuits currently open.",
             [(None, len(breaker.get("open") or ())
               if "open" in breaker else None)])

    # ---- continuous batching occupancy
    w.family(f"{p}_batching_enabled", "gauge",
             "1 when the continuous batcher dispatches runs.",
             [(None, bool(batching.get("enabled")))])
    if batching.get("enabled"):
        w.family(f"{p}_batch_capacity", "gauge",
                 "Maximum members per fused batch.",
                 [(None, batching.get("batch_max"))])
        w.family(f"{p}_batch_peak_members", "gauge",
                 "Peak members seated in one batch.",
                 [(None, batching.get("peak_members"))])
        w.family(f"{p}_batches_total", "counter",
                 "Fused batches dispatched.",
                 [(None, batching.get("batches"))])
        w.family(f"{p}_batch_members_total", "counter",
                 "Members seated across all batches.",
                 [(None, batching.get("members"))])
        w.family(f"{p}_batch_late_joins_total", "counter",
                 "Members that joined a running batch at a boundary.",
                 [(None, batching.get("late_joins"))])
        w.family(f"{p}_batch_blocks_total", "counter",
                 "Fixed-size step blocks executed by the batcher.",
                 [(None, batching.get("blocks"))])
        w.family(f"{p}_batch_detached_total", "counter",
                 "Members detached from a batch, by cause.",
                 [({"cause": cause}, count)
                  for cause, count in sorted(
                      (batching.get("detached") or {}).items())])

    for suffix, (hist, help_text) in sorted((hists or {}).items()):
        render_histogram(w, f"{p}_{suffix}", hist, help_text)
    return w.text()


def render_router_stats(stats, hists=None):
    """The router's exposition from one `RouterService.stats()` dict:
    traffic counters under `dedalus_router_*`, fleet health under
    `dedalus_fleet_*` (per-replica gauges labeled `replica=...`), plus
    the forward-latency histogram. Served by the router's `stats` frame
    with `prom: true`; pinned through `validate_exposition` like every
    other rendered surface (docs/observability.md#scraping-the-daemon)."""
    stats = stats or {}
    router = stats.get("router") or {}
    fleet = stats.get("fleet") or {}
    breaker = router.get("breaker") or {}
    replicas = fleet.get("replicas") or {}
    w = _Writer()
    p = _PREFIX

    w.family(f"{p}_router_up", "gauge",
             "1 while the router is serving.", [(None, 1)])
    w.family(f"{p}_router_uptime_seconds", "gauge",
             "Seconds since the router bound its socket.",
             [(None, stats.get("uptime_sec"))])
    w.family(f"{p}_router_draining", "gauge",
             "1 once the router began draining (new work is refused).",
             [(None, stats.get("draining") is not None)])
    w.family(f"{p}_router_forwarded_total", "counter",
             "Run requests relayed to a replica result.",
             [(None, router.get("forwarded"))])
    w.family(f"{p}_router_failovers_total", "counter",
             "Runs re-dispatched to a sibling after a replica fault.",
             [(None, router.get("failovers"))])
    w.family(f"{p}_router_shed_total", "counter",
             "Runs refused fleet-wide (every routable replica refused "
             "or faulted).", [(None, router.get("shed"))])
    w.family(f"{p}_router_refusals_total", "counter",
             "Per-replica refusals absorbed during routing.",
             [(None, router.get("refusals"))])
    w.family(f"{p}_router_replica_faults_total", "counter",
             "Replica faults observed mid-relay (EOF, watchdog, "
             "connect failure).", [(None, router.get("replica_faults"))])
    w.family(f"{p}_router_client_drops_total", "counter",
             "Clients that vanished while the router held their run.",
             [(None, router.get("client_drops"))])
    w.family(f"{p}_router_acks_suppressed_total", "counter",
             "Duplicate replica acks hidden from clients on failover.",
             [(None, router.get("acks_suppressed"))])
    w.family(f"{p}_router_errors_by_code_total", "counter",
             "Error frames relayed or emitted, by protocol code.",
             [({"code": code}, count)
              for code, count in sorted(
                  (router.get("error_codes") or {}).items())])
    w.family(f"{p}_router_ring_members", "gauge",
             "Replicas currently routable on the hash ring.",
             [(None, len(router.get("ring_members") or ())
               if "ring_members" in router else None)])
    w.family(f"{p}_router_breaker_opens_total", "counter",
             "Per-replica circuit opens.", [(None, breaker.get("opens"))])
    w.family(f"{p}_router_breaker_fastfails_total", "counter",
             "Routing attempts fast-failed by an open replica circuit.",
             [(None, breaker.get("fastfails"))])
    w.family(f"{p}_router_breaker_open_circuits", "gauge",
             "Replica circuits currently open.",
             [(None, len(breaker.get("open") or ())
               if "open" in breaker else None)])

    # ---- fleet health
    w.family(f"{p}_fleet_replicas", "gauge",
             "Replicas under supervision, by state.",
             [({"state": state}, count)
              for state, count in sorted(
                  (fleet.get("states") or {}).items())])
    w.family(f"{p}_fleet_restarts_total", "counter",
             "Replica restarts performed by the supervisor.",
             [(None, fleet.get("restarts"))])
    w.family(f"{p}_fleet_crashes_total", "counter",
             "Replica process exits detected.",
             [(None, fleet.get("crashes"))])
    w.family(f"{p}_fleet_wedges_total", "counter",
             "Replicas declared wedged (stats probes timed out).",
             [(None, fleet.get("wedges"))])
    w.family(f"{p}_fleet_watchdog_fires_total", "counter",
             "Watchdog postmortems reported across the fleet.",
             [(None, fleet.get("watchdog_fires"))])
    w.family(f"{p}_fleet_replica_up", "gauge",
             "1 while the named replica answers its health probe.",
             [({"replica": name}, r.get("state") == "up")
              for name, r in sorted(replicas.items())])
    w.family(f"{p}_fleet_replica_draining", "gauge",
             "1 while the named replica reports a drain in progress.",
             [({"replica": name}, bool(r.get("draining")))
              for name, r in sorted(replicas.items())])
    w.family(f"{p}_fleet_replica_restarts_total", "counter",
             "Restarts of the named replica.",
             [({"replica": name}, r.get("restarts"))
              for name, r in sorted(replicas.items())])

    for suffix, (hist, help_text) in sorted((hists or {}).items()):
        render_histogram(w, f"{p}_{suffix}", hist, help_text)
    return w.text()


# ------------------------------------------------------------- validation

def _parse_value(text):
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)   # raises ValueError on garbage


def validate_exposition(text):
    """Validate Prometheus text format 0.0.4. Raises ValueError on the
    first violation; returns {family: {"type", "samples"}} on success.

    Checked: HELP/TYPE syntax and one-TYPE-per-family discipline,
    metric/label name grammar, label quoting/escapes, float-parsable
    values, duplicate (name, labelset) samples, and — for every
    `histogram` family — cumulative non-decreasing `le` buckets, a
    mandatory `+Inf` bucket, and `_count` == the `+Inf` bucket with a
    `_sum` present."""
    families = {}      # family -> {"type": str|None, "samples": int}
    samples_seen = set()
    hist = {}          # family -> {"buckets": [(le, v)], "count": v,
                       #            "sum": v}

    def family_of(name):
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[:-len(suffix)]
                if families.get(base, {}).get("type") == "histogram":
                    return base
        return name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                raise ValueError(f"line {lineno}: malformed {parts[1]}")
            _, keyword, name, rest = parts
            if not _NAME_RE.match(name):
                raise ValueError(f"line {lineno}: bad metric name {name!r}")
            entry = families.setdefault(name,
                                        {"type": None, "samples": 0})
            if keyword == "TYPE":
                if entry["type"] is not None:
                    raise ValueError(
                        f"line {lineno}: duplicate TYPE for {name}")
                if entry["samples"]:
                    raise ValueError(
                        f"line {lineno}: TYPE for {name} after samples")
                if rest not in ("counter", "gauge", "histogram",
                                "summary", "untyped"):
                    raise ValueError(
                        f"line {lineno}: unknown type {rest!r}")
                entry["type"] = rest
                if rest == "histogram":
                    hist[name] = {"buckets": [], "count": None,
                                  "sum": None}
            continue
        if line.startswith("#"):
            continue                      # free-form comment
        match = _SAMPLE_RE.match(line)
        if not match:
            raise ValueError(f"line {lineno}: unparsable sample {line!r}")
        name = match.group("name")
        labels = {}
        raw = match.group("labels")
        if raw is not None:
            pos = 0
            while pos < len(raw):
                pair = _LABEL_PAIR_RE.match(raw, pos)
                if not pair:
                    raise ValueError(
                        f"line {lineno}: bad labels {raw!r}")
                key = pair.group("key")
                if not _LABEL_RE.match(key):
                    raise ValueError(
                        f"line {lineno}: bad label name {key!r}")
                if key in labels:
                    raise ValueError(
                        f"line {lineno}: duplicate label {key!r}")
                labels[key] = pair.group("val")
                pos = pair.end()
        try:
            value = _parse_value(match.group("value"))
        except ValueError:
            raise ValueError(f"line {lineno}: bad value "
                             f"{match.group('value')!r}")
        sample_key = (name, tuple(sorted(labels.items())))
        if sample_key in samples_seen:
            raise ValueError(f"line {lineno}: duplicate sample {name} "
                             f"{labels}")
        samples_seen.add(sample_key)
        base = family_of(name)
        families.setdefault(base, {"type": None, "samples": 0})
        families[base]["samples"] += 1
        if base in hist:
            if name == f"{base}_bucket":
                if "le" not in labels:
                    raise ValueError(
                        f"line {lineno}: histogram bucket without le")
                hist[base]["buckets"].append(
                    (_parse_value(labels["le"]), value))
            elif name == f"{base}_count":
                hist[base]["count"] = value
            elif name == f"{base}_sum":
                hist[base]["sum"] = value
            elif name == base:
                raise ValueError(
                    f"line {lineno}: bare sample for histogram {base}")

    for base, data in hist.items():
        buckets = data["buckets"]
        if not buckets or not math.isinf(buckets[-1][0]):
            raise ValueError(f"histogram {base}: missing +Inf bucket")
        les = [le for le, _ in buckets]
        if les != sorted(les):
            raise ValueError(f"histogram {base}: le not increasing")
        counts = [v for _, v in buckets]
        if any(b > a for b, a in zip(counts, counts[1:])):
            raise ValueError(f"histogram {base}: buckets not cumulative")
        if data["count"] is None or data["sum"] is None:
            raise ValueError(f"histogram {base}: missing _count/_sum")
        if data["count"] != buckets[-1][1]:
            raise ValueError(
                f"histogram {base}: _count != +Inf bucket")
    return families
