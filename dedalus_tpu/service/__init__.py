"""
Warm-pool solver service: a long-running daemon holding an LRU pool of
live, compiled solvers keyed by the persistent assembly-cache content
key, serving problem specs + initial conditions over a local socket.

    python -m dedalus_tpu serve --port 8751 --pool-size 4   # daemon
    python -m dedalus_tpu submit --port 8751 --spec ... --dt ...

Modules:
  protocol.py — spec schema, frame codec, npz field payloads, registry
  pool.py     — LRU of warm solvers (reset, eviction, hit/miss counters)
  server.py   — accept loop, admission control, dispatch, watchdog,
                graceful SIGTERM/SIGINT drain
  client.py   — blocking client + `submit` CLI (no solver-stack import;
                jittered retries, idempotent request ids)
  faults.py   — request-path fault tolerance: per-spec circuit breaker,
                idempotent result cache, hung-dispatch watchdog
  batching.py — continuous batching: concurrent same-spec requests
                coalesced into one vmapped ensemble micro-batch with
                member-level fault isolation (`serve --batch`)

See docs/serving.md for the protocol reference, the failure-modes
runbook, and the operations guide.
"""

from .protocol import (PROBLEMS, ProtocolError, ServiceError, SpecError,
                       register_problem, spec_digest, spec_name)
from .client import RunResult, ServiceClient

__all__ = ["PROBLEMS", "ProtocolError", "RunResult", "ServiceClient",
           "ServiceError", "SpecError", "register_problem", "spec_digest",
           "spec_name"]
