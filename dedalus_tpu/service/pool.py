"""
LRU pool of live, compiled solvers — the warm tier behind
`python -m dedalus_tpu serve`.

Entries are keyed by the PR-5 assembly-cache content key
(tools/assembly_cache.pool_key: the equation-tree/NCC-data/basis/config
fingerprint the persistent matrix cache already uses, composed with the
timestepper scheme the step program compiled for), with the normalized
spec digest as a fast-path alias — so two textually different specs that
build the same problem converge on ONE warm entry. A pool miss pays the
(assembly-cached) cold start once; every later request for the same spec
shape reuses the built matrices, factorizations, AND the compiled step
programs, so it starts in milliseconds.

Reset discipline: a pooled solver is reset to its just-built state
before EVERY request (state and RHS-parameter fields zeroed, clocks and
timestepper history cleared, evaluator handlers restored to the build-
time set, health/metrics accounting re-zeroed) and the request's initial
conditions are applied on top. The compiled step programs are closures
on the (unchanged) timestepper instance, so reset costs microseconds and
never retraces — and because reset + IC install performs exactly the
same field assignments a fresh in-process run would, served results are
bit-identical to direct solves (tests/test_service.py asserts this).
"""

import logging
import threading
import time
from collections import OrderedDict

import numpy as np

from . import protocol
from ..tools import assembly_cache
from ..tools.config import cfg_get
from ..tools.lint.threadcheck import named_lock

logger = logging.getLogger(__name__)

__all__ = ["PoolEntry", "SolverPool"]


class PoolEntry:
    """One warm solver plus the build-time snapshot reset restores.
    `fleet` caches the entry's serving EnsembleSolver (service/
    batching.py): None until the first batch, False when the template
    cannot fleet, else the live fleet whose compiled programs ride this
    entry's lifetime — eviction or quarantine drops both together."""

    __slots__ = ("key", "spec", "solver", "build_sec", "base_handlers",
                 "base_schedule", "base_extras", "created_ts",
                 "last_used_ts", "uses", "fleet")

    def __init__(self, key, spec, solver, build_sec):
        self.key = key
        self.spec = spec
        self.solver = solver
        self.build_sec = build_sec
        self.fleet = None
        # build-time data of every RHS extra operand. Reset RESTORES
        # these rather than zeroing: user parameter fields the builder
        # left empty still start at zero (the documented contract), but
        # equation-internal operands — BC constants, backgrounds — keep
        # their built values. Zeroing them changed the PROBLEM: a served
        # Rayleigh-Benard run lost its b(z=0)=Lz boundary constant and
        # silently solved different physics than the same spec solved
        # in-process.
        self.base_extras = [np.asarray(f.coeff_data()).copy()
                            for f in solver.eval_F.extra_fields]
        # the handler set present at registration (usually empty): per-
        # request additions (the resilient loop's checkpoint FileHandler)
        # are dropped by reset so one request's checkpoint cadence can
        # never leak into the next
        self.base_handlers = list(solver.evaluator.handlers)
        self.base_schedule = [h.schedule_state() for h in self.base_handlers]
        self.created_ts = time.time()
        self.last_used_ts = self.created_ts
        self.uses = 0

    def describe(self):
        return {
            "key": self.key[:16],
            "spec": protocol.spec_name(self.spec),
            "pencil_shape": list(self.solver.pencil_shape),
            "build_sec": round(self.build_sec, 4),
            "uses": self.uses,
            "age_sec": round(time.time() - self.created_ts, 1),
        }


class SolverPool:
    """
    Bounded LRU of PoolEntry. SOLVERS are single-owner (only the service
    worker thread acquires/resets/steps them), but the bookkeeping dicts
    are read by `stats()` from the server's per-connection reader
    threads, so every entries/aliases mutation and the stats snapshot
    take `_lock` (never held across a build or a solver reset).
    `acquire(spec)` returns a reset-and-ready entry plus the pool
    verdict — "hit" (warm solver reused), "warm-cache" (fresh build that
    hit the persistent assembly cache), or "cold" (fresh build, fresh
    assembly). Hit/miss/eviction/reset counters feed the `stats` reply
    and the service telemetry records.
    """

    def __init__(self, size=None, allow_imports=False):
        self.size = max(int(size if size is not None
                            else cfg_get("service", "POOL_SIZE", "4")), 1)
        self.allow_imports = bool(allow_imports)
        self._entries = OrderedDict()   # pool key -> PoolEntry
        self._aliases = {}              # spec digest -> pool key
        self._lock = named_lock("service/pool.py:SolverPool._lock")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.resets = 0

    def __len__(self):
        # reader threads size the pool (server._shed_memory, stats
        # surfaces) while the worker mutates it; the lock is never held
        # at a len(self) call site (the _build log line sits outside
        # its bookkeeping block), so this cannot self-deadlock
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------------ lookup

    def acquire(self, spec):
        """Warm (or build) the solver for `spec`, reset it to a fresh-run
        state, and return (entry, verdict, build_sec). Raises SpecError
        for invalid specs; build failures propagate."""
        spec = protocol.normalize_spec(spec)
        digest = protocol.spec_digest(spec)
        with self._lock:
            key = self._aliases.get(digest)
            entry = self._entries.get(key) if key else None
            if entry is not None:
                self.hits += 1
                self._entries.move_to_end(entry.key)
        if entry is not None:
            verdict, build_sec = "hit", 0.0
        else:
            entry, verdict, build_sec = self._build(spec, digest)
        entry.uses += 1
        entry.last_used_ts = time.time()
        self.reset_entry(entry)
        return entry, verdict, build_sec

    def peek(self, spec):
        """Non-mutating lookup (no reset, no counters): the entry that
        `acquire` would hit, or None."""
        digest = protocol.spec_digest(spec)
        with self._lock:
            key = self._aliases.get(digest)
            return self._entries.get(key) if key else None

    def _build(self, spec, digest):
        build = protocol.resolve_builder(spec,
                                         allow_imports=self.allow_imports)
        t0 = time.perf_counter()
        solver = build()        # the long part: outside the lock
        build_sec = time.perf_counter() - t0
        verdict = ("warm-cache"
                   if solver.build_phases.cache == "hit" else "cold")
        key = assembly_cache.pool_key(solver) or f"spec:{digest}"
        with self._lock:
            self.misses += 1
            existing = self._entries.get(key)
            if existing is not None:
                # a textually new spec converged on an already-warm
                # problem: keep the warm entry (its step programs are
                # compiled), let the duplicate build be garbage-
                # collected, and alias the new digest so the NEXT
                # occurrence is a plain hit
                logger.info(f"pool: spec {digest[:8]} aliases warm entry "
                            f"{key[:12]}")
                self._aliases[digest] = key
                self._entries.move_to_end(key)
                return existing, verdict, build_sec
            entry = PoolEntry(key, spec, solver, build_sec)
            self._entries[key] = entry
            self._aliases[digest] = key
            self._evict()
        logger.info(
            f"pool: built {protocol.spec_name(spec)} ({verdict}, "
            f"{build_sec:.2f}s, key {key[:12]}); {len(self)}/{self.size}")
        return entry, verdict, build_sec

    def _evict(self):
        """Drop LRU entries above the budget (caller holds _lock)."""
        while len(self._entries) > self.size:
            self._pop_lru()

    def _remove(self, key):
        """Drop one entry + its aliases and count the eviction (caller
        holds _lock). The single bookkeeping point behind LRU eviction,
        trim, and watchdog quarantine."""
        entry = self._entries.pop(key)
        self._aliases = {d: k for d, k in self._aliases.items()
                         if k != key}
        self.evictions += 1
        return entry

    def _pop_lru(self):
        """Evict the single least-recently-used entry (caller holds
        _lock)."""
        key = next(iter(self._entries))
        entry = self._remove(key)
        logger.info(f"pool: evicted {protocol.spec_name(entry.spec)} "
                    f"(key {key[:12]}, {entry.uses} uses)")

    def discard(self, digest):
        """Quarantine the entry aliased by a spec digest: the watchdog's
        path when it abandons a run. The stale executor may still be
        inside a dispatch on this entry's solver, so the pool must drop
        its reference — the replacement executor then builds a FRESH
        solver for the spec instead of sharing (and racing) the wedged
        one. Returns True when an entry was removed."""
        with self._lock:
            key = self._aliases.get(digest)
            if key is None or key not in self._entries:
                return False
            entry = self._remove(key)
            logger.warning(
                f"pool: quarantined {protocol.spec_name(entry.spec)} "
                f"(key {key[:12]}) — its executor was abandoned by the "
                "watchdog; the next request builds fresh")
            return True

    def trim(self, keep=1):
        """Evict LRU entries down to `keep` — the memory-watermark
        shedding path (server._shed_memory): each entry pins one
        problem's matrices, factorizations, and compiled programs, so
        trimming is what turns an approaching OOM into cold starts
        instead of a dead daemon. Returns the number evicted."""
        keep = max(int(keep), 0)
        n = 0
        with self._lock:
            while len(self._entries) > keep:
                self._pop_lru()
                n += 1
        return n

    # ------------------------------------------------------------- reset

    def reset_entry(self, entry):
        """Rewind one pooled solver to its just-built state. Everything a
        run mutates is restored; the compiled step programs (closures on
        the surviving timestepper/ops instances) are untouched, so the
        next request never retraces."""
        solver = entry.solver
        # state: zero in coefficient layout (exact; the request's IC
        # payload overwrites the fields it names). RHS extra operands:
        # restored to their BUILD-time data (entry.base_extras) — zero
        # for parameter fields the builder left empty, the built values
        # for equation constants/backgrounds a request must never lose.
        for var in solver.state:
            var["c"] = 0
        for field, base in zip(solver.eval_F.extra_fields,
                               entry.base_extras):
            field.preset_coeff(base)
            field.mark_modified()
        # clocks and stop criteria
        solver.sim_time = solver.initial_sim_time = 0.0
        solver.iteration = solver.initial_iteration = 0
        solver.dt = None
        solver.problem.sim_time = 0.0
        solver.stop_sim_time = np.inf
        solver.stop_iteration = np.inf
        solver.stop_wall_time = np.inf
        solver.start_time = time.time()
        solver.warmup_time = None
        solver._metrics_warm_pending = False
        # timestepper: the scheme owns its per-run state and the reset
        # that mirrors its __init__ (core/timesteppers.py reset_run —
        # which also documents why the LHS factorization cache SURVIVES:
        # keeping it takes one factor dispatch out of every warm-hit
        # time-to-first-step)
        solver.timestepper.reset_run()
        # evaluator: drop per-request handlers (resilient checkpointing),
        # restore build-time schedules
        solver.evaluator.handlers[:] = list(entry.base_handlers)
        for handler, state in zip(entry.base_handlers, entry.base_schedule):
            handler.restore_schedule_state(state)
        # per-run accounting: health latch + forensic ring, metrics
        # counters/loop window, stale resilience summary
        solver.resilience = None
        solver.health.reset_run()
        solver.metrics.reset_run()
        # reset_entry runs on the worker OUTSIDE _lock (never held
        # across a reset — class docstring), but the counter it bumps
        # is read by stats() from reader threads: the increment itself
        # takes the lock or concurrent stats snapshots lose counts
        with self._lock:
            self.resets += 1

    # ------------------------------------------------------------- stats

    def stats(self):
        """Snapshot for the `stats` reply — called from the server's
        reader threads while the worker may be mutating the pool, hence
        the lock around the entries iteration."""
        with self._lock:
            return {
                "size": self.size,
                "entries": [e.describe()
                            for e in self._entries.values()],
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "resets": self.resets,
            }
