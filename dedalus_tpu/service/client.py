"""
Blocking client for the warm-pool solver service, plus the
`python -m dedalus_tpu submit` CLI.

Deliberately lightweight: this module itself imports only the protocol
codecs (json/socket/numpy) and the host-side retry classification — it
never touches the solver stack; no fields, bases, or compiled programs
load on the client side. (Reaching it through the `dedalus_tpu` package
still executes the package root, which imports jax; the point is that
the DAEMON owns all solver state and compilation, so a client process
stays cheap after import.)

    from dedalus_tpu.service.client import ServiceClient
    client = ServiceClient(port=8751, retries=5)
    result = client.run({"problem": "diffusion", "params": {"size": 64}},
                        ics={"u": ("g", u0)}, dt=1e-3, stop_iteration=100)
    result.fields["u"]          # ('c', ndarray) final state, bit-exact
    result.record["serving"]    # queue_sec / pool_verdict / ttfs

Telemetry frames stream during the run; `run(on_record=...)` observes
them live, and every streamed record is kept on the RunResult.

Client-side resilience (`retries=` / `submit --retry`): connection
failures, dropped streams, daemon drains, and `overloaded` refusals are
retried with jittered exponential backoff (the tools/resilience
RetryPolicy errno classification decides which OSErrors are worth
retrying; an `overloaded` reply's `retry_after_sec` hint FLOORS the
exponential schedule without replacing it, and `--retry-max-delay`
caps both). Every RETRYING run carries an idempotent request
id (auto-generated when `retries > 0` and none is supplied; explicit
ids always work), so a retry after a dropped `result` frame replays the
completed outcome from the daemon's result cache instead of re-running
the solve — which is what makes a rolling daemon restart invisible to a
retrying client. Non-retrying runs send no id, so the daemon never pins
result payloads for clients that cannot come back. `circuit-open` is
NOT retried: fast-failing poisoned specs to the caller is the breaker's
point.
"""

import argparse
import json
import logging
import socket
import sys
import time
import uuid

import numpy as np

from . import protocol
from .protocol import ProtocolError, ServiceError
from ..tools.config import cfg_get
from ..tools.resilience import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["RunResult", "ServiceClient", "main"]

# structured error codes a retry can help with: the stream died before
# the result ("closed"), a rolling restart is in progress ("draining"),
# or admission control shed us ("overloaded", with a retry_after hint);
# "fleet-unavailable" is the router's whole-fleet outage refusal
# (service/router.py) — transient by construction, since the supervisor
# is already restarting the replicas behind it
_RETRYABLE_CODES = frozenset({"closed", "draining", "overloaded",
                              "fleet-unavailable"})


class RunResult:
    """Everything one run request produced, in arrival order."""

    def __init__(self):
        self.ack = None         # pool verdict + queue_sec frame
        self.progress = []      # streamed progress frames
        self.records = []       # streamed telemetry records
        self.result = None      # final result header
        self.fields = {}        # {name: (layout, ndarray)} final state
        self.attempts = 1       # connection attempts this run consumed

    @property
    def record(self):
        """The run's telemetry record (newest streamed one)."""
        return self.records[-1] if self.records else None

    @property
    def serving(self):
        return (self.result or {}).get("serving") or {}

    @property
    def replayed(self):
        """Whether the result came from the daemon's idempotent result
        cache (a retry after a dropped stream) rather than a fresh run."""
        return bool((self.result or {}).get("replayed"))


class ServiceClient:
    """One-request-per-connection blocking client (the daemon serializes
    execution on its worker thread; connections are cheap and keeping
    them one-shot keeps drain semantics trivial).

    Timeouts split connect from read ([service] CONNECT_TIMEOUT_SEC /
    READ_TIMEOUT_SEC config defaults); the legacy `timeout=` argument
    keeps setting the read timeout. `retries` enables jittered-backoff
    reconnect on transient failures (0 = fail on the first)."""

    def __init__(self, host="127.0.0.1", port=None, timeout=None,
                 connect_timeout=None, read_timeout=None, retries=0,
                 retry_base_delay=0.5, retry_max_delay=30.0):
        if port is None:
            raise ValueError("ServiceClient needs the daemon port (the "
                             "'ready' banner printed by `serve` names it)")
        self.host = host
        self.port = int(port)
        self.connect_timeout = float(
            connect_timeout if connect_timeout is not None
            else cfg_get("service", "CONNECT_TIMEOUT_SEC", "10"))
        self.read_timeout = float(
            read_timeout if read_timeout is not None
            else timeout if timeout is not None
            else cfg_get("service", "READ_TIMEOUT_SEC", "600"))
        self.retries = max(int(retries), 0)
        self.retry = RetryPolicy(max_attempts=self.retries + 1,
                                 base_delay=float(retry_base_delay),
                                 max_delay=float(retry_max_delay),
                                 jitter=0.25)

    # `timeout` kept readable for callers that used the old single knob
    @property
    def timeout(self):
        return self.read_timeout

    def _connect(self):
        conn = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        conn.settimeout(self.read_timeout)
        return conn, conn.makefile("rb"), conn.makefile("wb")

    @staticmethod
    def _retryable(exc):
        if isinstance(exc, ServiceError):
            return exc.code in _RETRYABLE_CODES
        if isinstance(exc, ProtocolError):
            # a torn frame mid-stream IS the daemon dying on us (SIGKILL
            # mid-write): the same retry/replay path as a clean close
            return True
        if isinstance(exc, TimeoutError):
            # a READ timeout means the reply is slower than our patience,
            # not that the daemon is gone — blindly re-submitting would
            # queue a duplicate behind the still-running original (and
            # under ON_CLIENT_DROP=abort, kill it). Surface it: the
            # caller chose read_timeout and should raise it.
            return False
        if isinstance(exc, OSError):
            return RetryPolicy.is_transient(exc)
        return False

    def _with_retries(self, fn, observe_attempt=None):
        """Run one request attempt, reconnecting with jittered backoff on
        transient failures. A structured `retry_after_sec` hint from the
        daemon (overload shedding) acts as a FLOOR under the exponential
        schedule — never a replacement for it: a hint that short-circuits
        backoff growth turns every saturated daemon into a retry-storm
        metronome, with the whole rejected cohort knocking again exactly
        when invited. The combined delay stays capped by `retry_max_delay`
        and jittered so cohorts decorrelate. The attempt budget lives in
        ONE place — the RetryPolicy's max_attempts (retries + 1)."""
        attempt = 0
        while True:
            try:
                return fn()
            except (ServiceError, ProtocolError, OSError) as exc:
                attempt += 1
                if attempt >= self.retry.max_attempts \
                        or not self._retryable(exc):
                    raise
                hint = getattr(exc, "retry_after_sec", None)
                base = self.retry.base_delay * 2 ** (attempt - 1)
                if hint:
                    base = max(base, float(hint))
                delay = self.retry.jittered(min(base,
                                                self.retry.max_delay))
                if observe_attempt is not None:
                    observe_attempt(attempt, exc)
                logger.warning(
                    f"service client: attempt {attempt}/{self.retries} "
                    f"failed ({exc}); retrying in {delay:.2f}s")
                time.sleep(delay)

    def _simple(self, request, expect, retryable=True):
        def attempt():
            conn, rfile, wfile = self._connect()
            try:
                protocol.send_frame(wfile, request)
                header, _payload = protocol.recv_frame(rfile)
                if header is None:
                    raise ServiceError("closed",
                                       "daemon closed the connection")
                if header.get("kind") == "error":
                    raise ServiceError(header.get("code", "error"),
                                       header.get("message", ""),
                                       frame=header)
                if header.get("kind") != expect:
                    raise ServiceError(
                        "protocol", f"expected {expect!r} reply, got "
                        f"{header.get('kind')!r}")
                return header
            finally:
                conn.close()
        if not retryable:
            return attempt()
        return self._with_retries(attempt)

    def ping(self):
        return self._simple({"kind": "ping"}, "pong")

    def stats(self):
        return self._simple({"kind": "stats"}, "stats")

    def stats_prom(self):
        """The daemon's stats surface as Prometheus text exposition
        (str). Cannot ride `_simple`, which discards the payload frame
        the text arrives in."""
        def attempt():
            conn, rfile, wfile = self._connect()
            try:
                protocol.send_frame(wfile, {"kind": "stats",
                                            "prom": True})
                header, payload = protocol.recv_frame(rfile)
                if header is None:
                    raise ServiceError("closed",
                                       "daemon closed the connection")
                if header.get("kind") == "error":
                    raise ServiceError(header.get("code", "error"),
                                       header.get("message", ""),
                                       frame=header)
                if header.get("kind") != "stats":
                    raise ServiceError(
                        "protocol", f"expected 'stats' reply, got "
                        f"{header.get('kind')!r}")
                return (payload or b"").decode("utf-8")
            finally:
                conn.close()
        return self._with_retries(attempt)

    def shutdown(self):
        """Ask the daemon to drain and exit (same path as SIGTERM).
        NEVER retried, whatever `retries` is set to: a shutdown whose
        ack was lost in the drain would be re-delivered to — and drain —
        the freshly relaunched daemon of a rolling restart."""
        return self._simple({"kind": "shutdown"}, "ok", retryable=False)

    def run(self, spec, ics=None, dt=None, stop_iteration=None,
            stop_sim_time=None, outputs=None, layout="c",
            progress_every=0, checkpoint=None, resume=False,
            deadline_sec=None, request_id=None, chaos=None,
            on_record=None, on_progress=None):
        """Submit one run and block until its result frame.

        `ics` maps field name -> (layout, array) or a bare array (grid
        layout). `deadline_sec` bounds the request end-to-end: expired in
        the queue it fails structurally, expired mid-run it stops
        gracefully (`stopped_by: "deadline-exceeded"`). An idempotent
        `request_id` makes the daemon cache the completed result for
        replay; a retrying client (`retries > 0`) auto-generates one, a
        non-retrying client sends none — no point pinning result
        payloads in the daemon's cache for a client that will never ask
        again. Raises ServiceError on a structured daemon error (e.g.
        code 'bad-spec', 'draining', 'overloaded', 'circuit-open',
        'deadline-exceeded', 'watchdog-timeout', 'health')."""
        if request_id is None and self.retries > 0:
            request_id = uuid.uuid4().hex[:16]
        header = {"kind": "run",
                  "spec": protocol.normalize_spec(spec,
                                                  check_registry=False),
                  "dt": dt, "layout": layout}
        if request_id is not None:
            header["id"] = str(request_id)
        if stop_iteration is not None:
            header["stop_iteration"] = int(stop_iteration)
        if stop_sim_time is not None:
            header["stop_sim_time"] = float(stop_sim_time)
        if outputs is not None:
            header["outputs"] = list(outputs)
        if progress_every:
            header["progress_every"] = int(progress_every)
        if deadline_sec is not None:
            header["deadline_sec"] = float(deadline_sec)
        if chaos is not None:
            header["chaos"] = dict(chaos)
        if checkpoint is not None:
            header["checkpoint"] = (checkpoint if isinstance(checkpoint,
                                                             dict)
                                    else {"dir": str(checkpoint)})
            header["resume"] = bool(resume)
        payload = None
        if ics:
            norm = {}
            for name, value in ics.items():
                if isinstance(value, tuple):
                    norm[name] = value
                else:
                    norm[name] = ("g", np.asarray(value))
            payload = protocol.encode_fields(norm)

        def attempt():
            out = RunResult()
            conn, rfile, wfile = self._connect()
            try:
                protocol.send_frame(wfile, header, payload=payload)
                while True:
                    frame, frame_payload = protocol.recv_frame(rfile)
                    if frame is None:
                        raise ServiceError(
                            "closed", "daemon closed the stream before "
                            "the result frame (see the daemon log)")
                    kind = frame.get("kind")
                    if kind == "error":
                        raise ServiceError(frame.get("code", "error"),
                                           frame.get("message", ""),
                                           frame=frame)
                    if kind == "ack":
                        out.ack = frame
                    elif kind == "progress":
                        out.progress.append(frame)
                        if on_progress is not None:
                            on_progress(frame)
                    elif kind == "result":
                        out.result = frame
                        if frame_payload:
                            out.fields = protocol.decode_fields(
                                frame_payload)
                        return out
                    else:
                        # telemetry: the metrics-sink record format IS the
                        # wire format (kind step_metrics today; forward-
                        # compatible with any future record kinds)
                        out.records.append(frame)
                        if on_record is not None:
                            on_record(frame)
            finally:
                conn.close()

        attempts = [1]

        def observe(attempt_n, exc):
            attempts[0] = attempt_n + 1

        out = self._with_retries(attempt, observe_attempt=observe)
        out.attempts = attempts[0]
        return out


# --------------------------------------------------------------- CLI

def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu submit",
        description="Submit one run to a `dedalus_tpu serve` daemon "
                    "(docs/serving.md). Prints the ack, streamed "
                    "telemetry summaries, and the result line; saves "
                    "final fields with --out.")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True,
                        help="daemon port (from its ready banner)")
    parser.add_argument("--spec", help="problem spec: inline JSON or a "
                                       "path to a JSON file")
    parser.add_argument("--ic", help="npz of initial conditions: members "
                                     "named '<g|c>__<field>' (bare names "
                                     "are taken as grid layout)")
    parser.add_argument("--dt", type=float, help="timestep")
    parser.add_argument("--stop-iteration", type=int, default=None)
    parser.add_argument("--stop-sim-time", type=float, default=None)
    parser.add_argument("--outputs", nargs="*", default=None,
                        help="state fields to return (default: all)")
    parser.add_argument("--layout", choices=("c", "g"), default="c",
                        help="output layout (default: coefficient — "
                             "bit-exact)")
    parser.add_argument("--progress-every", type=int, default=0,
                        help="stream a progress frame every N iterations")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="durable checkpoint directory for the served "
                             "run (enables drain-time checkpointing)")
    parser.add_argument("--resume", action="store_true",
                        help="resume from the newest valid checkpoint in "
                             "--checkpoint-dir before stepping")
    parser.add_argument("--deadline", type=float, default=None,
                        metavar="SEC",
                        help="per-request deadline: expired in queue fails "
                             "structurally, expired mid-run stops the "
                             "solve gracefully")
    parser.add_argument("--id", default=None,
                        help="idempotent request id (auto-generated when "
                             "omitted AND --retry > 0; resubmitting a "
                             "completed id replays the cached result)")
    parser.add_argument("--out", default=None,
                        help="write the returned fields to this npz path")
    parser.add_argument("--timeout", type=float, default=None,
                        help="stream read timeout in seconds (default: "
                             "[service] READ_TIMEOUT_SEC)")
    parser.add_argument("--connect-timeout", type=float, default=None,
                        help="connection timeout in seconds (default: "
                             "[service] CONNECT_TIMEOUT_SEC)")
    parser.add_argument("--retry", type=int, default=0, metavar="N",
                        help="retry transient failures (dropped stream, "
                             "draining daemon, overload shed) up to N "
                             "times with jittered backoff — makes rolling "
                             "daemon restarts invisible")
    parser.add_argument("--retry-delay", type=float, default=0.5,
                        help="backoff base seconds between retries "
                             "(default: %(default)s)")
    parser.add_argument("--retry-max-delay", type=float, default=30.0,
                        help="backoff ceiling seconds: caps both the "
                             "exponential schedule and any daemon "
                             "retry_after_sec hint (default: %(default)s)")
    parser.add_argument("--ping", action="store_true",
                        help="just ping the daemon and exit")
    parser.add_argument("--stats", action="store_true",
                        help="print daemon/pool stats JSON and exit")
    parser.add_argument("--prom", action="store_true",
                        help="with --stats: print the stats surface in "
                             "Prometheus text exposition format instead "
                             "of JSON (same text GET /metrics serves)")
    parser.add_argument("--shutdown", action="store_true",
                        help="ask the daemon to drain and exit")
    return parser


def _load_spec(text):
    if text is None:
        raise SystemExit("submit: --spec is required for a run")
    try:
        if text.lstrip().startswith("{"):
            return json.loads(text)
        with open(text) as f:
            return json.load(f)
    except (OSError, ValueError) as exc:
        raise SystemExit(f"submit: cannot load spec {text!r}: {exc}")


def _load_ics(path):
    if path is None:
        return None
    ics = {}
    with np.load(path, allow_pickle=False) as npz:
        for key in npz.files:
            layout, sep, name = key.partition("__")
            if sep == "__" and layout in ("g", "c") and name:
                ics[name] = (layout, npz[key])
            else:
                ics[key] = ("g", npz[key])
    return ics


def main(argv=None):
    args = build_parser().parse_args(argv)
    client = ServiceClient(host=args.host, port=args.port,
                           timeout=args.timeout,
                           connect_timeout=args.connect_timeout,
                           retries=args.retry,
                           retry_base_delay=args.retry_delay,
                           retry_max_delay=args.retry_max_delay)
    try:
        if args.ping:
            client.ping()
            print("pong")
            return 0
        if args.stats:
            if args.prom:
                sys.stdout.write(client.stats_prom())
            else:
                print(json.dumps(client.stats(), indent=2))
            return 0
        if args.shutdown:
            client.shutdown()
            print("draining")
            return 0
        if args.dt is None:
            print("submit: --dt is required for a run", file=sys.stderr)
            return 2
        result = client.run(
            _load_spec(args.spec), ics=_load_ics(args.ic), dt=args.dt,
            stop_iteration=args.stop_iteration,
            stop_sim_time=args.stop_sim_time, outputs=args.outputs,
            layout=args.layout, progress_every=args.progress_every,
            checkpoint=args.checkpoint_dir, resume=args.resume,
            deadline_sec=args.deadline, request_id=args.id,
            on_progress=lambda f: print(
                f"progress: iteration={f['iteration']} "
                f"sim_time={f['sim_time']:.6e}", file=sys.stderr))
    except (ServiceError, OSError) as exc:
        print(f"submit: {exc}", file=sys.stderr)
        return 1
    ack = result.ack or {}
    serving = result.serving
    print(f"ack: pool={ack.get('pool_verdict')} "
          f"queue={ack.get('queue_sec')}s build={ack.get('build_sec')}s")
    ttfs = serving.get("time_to_first_step_sec")
    print(f"result: iteration={result.result['iteration']} "
          f"sim_time={result.result['sim_time']:.6e} "
          f"stopped_by={result.result['stopped_by']} "
          f"time_to_first_step={ttfs}s"
          + (" (replayed)" if result.replayed else ""))
    rec = result.record
    if rec:
        print(f"telemetry: {rec.get('iterations')} iters at "
              f"{rec.get('steps_per_sec')} steps/s "
              f"({rec.get('phase_samples', 0)} phase samples)")
    if args.out:
        np.savez(args.out, **{f"{layout}__{name}": arr
                              for name, (layout, arr)
                              in result.fields.items()})
        print(f"fields written: {args.out} "
              f"({', '.join(sorted(result.fields))})")
    return 0
