"""
Fault-isolated continuous batching: coalesce concurrent same-spec run
requests into ONE EnsembleSolver micro-batch.

The daemon's single executor and the ensemble fleet (core/ensemble.py)
finally meet: requests whose specs canonicalize to the same pool key
(members differ only in ICs / parameter fields / run length — all
batched operands) are seated as members of one vmapped fleet, advanced
by ONE compiled block dispatch, and streamed per-member ack / progress /
telemetry / result frames. This is what LLM inference servers do with
token streams, applied to PDE solves — the largest served-throughput
multiplier available when traffic repeats a spec shape.

The robustness contract is **blast-radius zero**, riding the per-member
machinery the fleet already has:

  * late arrivals join at the next block boundary (`attach_member` —
    membership is a value operand, zero post-warmup retraces; multistep
    joiners replay their own order build-up via `ramp_members` with the
    rest of the batch frozen, so a late join is bit-identical to a solo
    run);
  * a member hitting its per-request deadline stops gracefully at the
    boundary — durable per-member checkpoint when the request configured
    one, result frame `stopped_by: "deadline-exceeded"` — while the
    batch keeps stepping;
  * a diverging member (per-member NaN/growth probe each boundary) gets
    a structured `health` error and detaches; survivors never see its
    bits (vmap guarantees no cross-member reduction, and the freeze mask
    discards its lanes);
  * a dropped client detaches (ON_CLIENT_DROP=abort) or runs to
    completion with its result cached for idempotent replay (=complete);
  * the watchdog treats a wedged batch like a wedged solo run — the
    batch is abandoned, the pool entry (and its fleet) quarantined, and
    every SURVIVING member's request is REQUEUED for the replacement
    executor to re-run (idempotent ids make the replay safe);
  * admission control, per-spec circuit breakers, and idempotent replay
    all run per member at seat time, exactly as the solo path runs them
    at queue pop.

Bit-identity is the acceptance bar: every surviving member's served
result is bit-identical to a solo served run of the same request, under
every injected fault (tests/test_service_batching.py). The guarantee is
COMPOSITION INVARIANCE: a solo request on a batching daemon runs as a
batch of one through the SAME compiled fleet program, vmap lanes never
mix members, and membership/budgets are value operands — so a member's
trajectory cannot depend on who else rides the batch. Three mechanisms
make it exact rather than approximate: the per-member steps-remaining
operand (a member stops after exactly its requested number of steps,
mid-block, without leaving the compiled program), the multistep cohort
ramp (a joiner replays its own order build-up with the batch frozen),
and per-member Hermitian-projection phases — each member is re-projected
exactly where ITS OWN iteration count says a solo loop would, which
forces single-step dispatches around projection windows (block sizes
stay in {block, 1}, so exactly two compiled fleet programs exist).
Against a DIRECT in-process solve the diffusion-class problems are also
bit-exact; 2-D problems can differ at the ulp level because the vmapped
fleet program and the solo step program are different XLA executables
with different FMA contractions (~1e-12 over tens of steps on RB).

Scope: a request is batchable when it has a `stop_iteration` (not
`stop_sim_time` — fixed-dt step counts are exact; sim-time stops are
float-boundary-dependent), no `resume`, and at most the batch-safe
chaos keys. Everything else falls through to the solo executor path
unchanged. A batch shares one dt; a same-spec request with a different
dt waits for the next batch. Periodic mid-run checkpoints are a solo
feature — batched members write their durable checkpoint at graceful
stops (completion, deadline, drain) only.
"""

import collections
import logging
import threading
import time

import numpy as np

from . import faults, protocol
from ..tools import tracing
from ..tools.config import cfg_get
from ..tools.lint.threadcheck import named_lock

logger = logging.getLogger(__name__)

__all__ = ["BatchContext", "BatchDispatcher"]

# chaos keys a batched member may carry (aimed at ITSELF: `nan_field` +
# `nan_iteration` poison the member's own slice at ITS iteration N;
# `hang_*` stall the boundary — the watchdog drill). Anything else is a
# solo-only fault and routes the request to the solo executor path.
BATCH_CHAOS_KEYS = frozenset({"seed", "nan_field", "nan_iteration",
                              "hang_iteration", "hang_sec"})


class BatchContext:
    """The watchdog-visible context of one running micro-batch (the
    batch-shaped sibling of faults.RunContext). `last_progress` is
    stamped at every block boundary after the per-member health probe's
    device sync — a wedged fleet dispatch blocks that sync, the stamp
    goes stale, and the watchdog fires. `loop` is self: the server's
    drain path calls `ctx.loop.request_stop(why)` on whatever run is
    active, and a batch honors it at the next boundary for every
    member."""

    is_batch = True

    __slots__ = ("request_id", "digest", "abandoned", "last_progress",
                 "started_ts", "stop_why", "seats", "client_gone",
                 "pending_item", "seated", "late", "blocks", "peak",
                 "detached")

    def __init__(self, batch_id, digest):
        self.request_id = batch_id
        self.digest = digest
        self.abandoned = threading.Event()
        self.last_progress = time.monotonic()
        self.started_ts = time.monotonic()
        self.stop_why = None
        self.seats = {}            # seat index -> _Seat
        self.client_gone = False   # solo-path compat (never all-gone)
        # the anchor item while the batch-level build runs (the watchdog
        # must cover a hung build/compile, same as solo — a fire in that
        # window answers THIS client instead of requeuing seats)
        self.pending_item = None
        # occupancy bookkeeping (read by run_batch's batch event)
        self.seated = 0
        self.late = 0
        self.blocks = 0
        self.peak = 0
        self.detached = collections.Counter()

    @property
    def loop(self):
        return self

    def request_stop(self, why="requested"):
        if self.stop_why is None:
            self.stop_why = str(why)


class _Seat:
    """One served request riding the batch."""

    __slots__ = ("item", "header", "conn", "wfile", "request_id",
                 "client_id", "seat", "params", "deadline_mono", "probe",
                 "queue_sec", "t_dispatch", "steps_total", "steps_done",
                 "progress_next", "ttfs", "client_gone", "active",
                 "released", "chaos", "chaos_fired", "late", "verdict",
                 "build_sec", "joined_iteration")

    def __init__(self, item, seat, request_id, params, verdict, build_sec,
                 late, joined_iteration):
        self.item = item
        self.header = item["header"]
        self.conn = item["conn"]
        self.wfile = item["wfile"]
        self.request_id = request_id
        self.client_id = self.header.get("id")
        self.seat = seat
        self.params = params
        self.deadline_mono = item.get("deadline_mono")
        self.probe = bool(item.get("probe"))
        self.t_dispatch = time.perf_counter()
        self.queue_sec = self.t_dispatch - item["t_accept"]
        self.steps_total = int(params["stop_iteration"])
        self.steps_done = 0
        self.progress_next = params["progress_every"] or 0
        self.ttfs = None
        self.client_gone = False
        self.active = True
        self.released = False
        self.chaos = self.header.get("chaos") or None
        self.chaos_fired = set()
        self.late = late
        self.verdict = verdict
        self.build_sec = build_sec
        self.joined_iteration = joined_iteration


class BatchDispatcher:
    """
    The continuous micro-batch scheduler. Owned by the SolverService and
    driven ON the executor thread (JAX dispatch stays single-threaded);
    only `on_watchdog` and `stats` run on other threads.

    Knobs ([service] section; None pulls the config default):
      batch_max     BATCH_MAX_MEMBERS  seats per fleet (default 8)
      batch_window  BATCH_WINDOW_SEC   coalescing wait after the first
                                       member seats (default 0.05 s;
                                       boundary joins make long windows
                                       unnecessary)
      batch_block   BATCH_BLOCK_ITERS  steady dispatch block (default 8)
    """

    def __init__(self, service, batch_max=None, batch_window=None,
                 batch_block=None):
        self.service = service
        self.batch_max = max(int(
            batch_max if batch_max is not None
            else cfg_get("service", "BATCH_MAX_MEMBERS", "8")), 1)
        self.batch_window = float(
            batch_window if batch_window is not None
            else cfg_get("service", "BATCH_WINDOW_SEC", "0.05"))
        self.batch_block = max(int(
            batch_block if batch_block is not None
            else cfg_get("service", "BATCH_BLOCK_ITERS", "8")), 1)
        self._batch_seq = 0
        self._lock = named_lock(          # stats vs executor mutation
            "service/batching.py:BatchDispatcher._lock")
        self.batches = 0
        self.members_seated = 0
        self.late_joins = 0
        self.blocks = 0
        self.detached = collections.Counter()
        self.peak_members = 0
        self.batch_events = collections.deque(maxlen=8)

    # ------------------------------------------------------------ routing

    @staticmethod
    def batchable(header):
        """Whether a run request may ride a micro-batch (solo otherwise):
        iteration-bounded, no resume, at most batch-safe chaos keys."""
        if header.get("resume"):
            return False
        if header.get("stop_iteration") is None \
                or header.get("stop_sim_time") is not None:
            return False
        chaos = header.get("chaos")
        if chaos is not None and (not isinstance(chaos, dict)
                                  or set(chaos) - BATCH_CHAOS_KEYS):
            return False
        return True

    def _matches(self, item, digest, dt):
        """Whether a queued item can join the running batch: same spec
        digest, same dt, batchable."""
        header = item.get("header") or {}
        if item.get("force_solo") or not self.batchable(header):
            return False
        if header.get("dt") != dt:
            return False
        return self.service._spec_digest(header) == digest

    # ------------------------------------------------------- fleet cache

    def _fleet_for(self, entry):
        """The (cached) EnsembleSolver riding one pool entry, or None
        when the template cannot fleet (unsupported scheme, dd runner) —
        the verdict is cached so the fallback is decided once. The fleet
        dies with its pool entry (eviction / watchdog quarantine), which
        is exactly the lifetime its compiled programs are valid for."""
        fleet = entry.fleet
        if fleet is False:
            return None
        if fleet is None:
            from ..core.ensemble import EnsembleSolver
            try:
                fleet = EnsembleSolver(entry.solver, self.batch_max,
                                       mesh=None, policy="drop")
            except Exception as exc:
                logger.warning(
                    f"batching: spec {protocol.spec_name(entry.spec)} "
                    f"cannot fleet ({exc}); serving it solo")
                entry.fleet = False
                return None
            for m in range(fleet.members):
                fleet.detach_member(m)
            entry.fleet = fleet
        return fleet

    # ------------------------------------------------------------- stats

    def stats(self):
        with self._lock:
            return {
                "enabled": True,
                "batch_max": self.batch_max,
                "block_iters": self.batch_block,
                "batches": self.batches,
                "members": self.members_seated,
                "late_joins": self.late_joins,
                "blocks": self.blocks,
                "peak_members": self.peak_members,
                "detached": dict(self.detached),
                "recent_batches": list(self.batch_events),
            }

    # ------------------------------------------------------ the dispatch

    def run_batch(self, first_item):
        """Form and drive one micro-batch starting from `first_item`
        (already popped by the executor). Returns the list of queue
        items popped at boundaries that could NOT join (different spec /
        dt / not batchable) — the executor handles them next, in order.
        Raises faults.AbandonedRun when the watchdog declared this batch
        dead (the surviving members were already requeued by the
        fire)."""
        svc = self.service
        deferred = []
        with self._lock:
            self._batch_seq += 1
            batch_id = f"batch-{self._batch_seq}"
        header = first_item["header"]
        digest = svc._spec_digest(header)
        dt = header.get("dt")
        try:
            spec = protocol.normalize_spec(header.get("spec"))
        except protocol.SpecError as exc:
            svc._count_error()
            svc._send_error(first_item["wfile"], "bad-spec", str(exc))
            self._close(first_item)
            return deferred
        ctx = BatchContext(batch_id, digest)
        # the anchor runs the SAME pre-build gauntlet as the solo pop
        # (replay re-check, params validation, breaker re-admit,
        # queued-deadline) BEFORE any solver work — an open circuit must
        # fast-fail without re-running an expensive failing build
        admitted = self._admit_member(ctx, first_item)
        if admitted is None:
            return deferred
        # registered BEFORE the build so the watchdog also covers a hung
        # build/compile, exactly like the solo path
        ctx.pending_item = first_item
        with svc._active_lock:
            svc._active_run = ctx
        t0 = time.perf_counter()
        try:
            # RSS watermark first, like the solo pop: a fleet build is
            # the largest allocation the request path makes
            svc._shed_memory()
            try:
                # the anchor's trace owns the batch-level pool acquire
                # (a cold build emits build/<phase> child spans under it)
                with tracing.resume(first_item.get("trace")):
                    with tracing.span("pool_acquire") as acq:
                        entry, verdict, build_sec = svc.pool.acquire(spec)
                        acq.set(verdict=verdict,
                                build_sec=round(build_sec, 4))
            except protocol.SpecError as exc:
                svc._count_error()
                svc._send_error(first_item["wfile"], "bad-spec", str(exc))
                if first_item.get("probe"):
                    svc.breaker.abandon_probe(digest)
                self._close(first_item)
                return deferred
            except Exception as exc:
                if ctx.abandoned.is_set():
                    raise faults.AbandonedRun(ctx.request_id)
                svc._count_error()
                logger.exception(f"batching: build for {batch_id} failed")
                svc.breaker.record_failure(digest)
                svc._send_error(first_item["wfile"], "build-failed",
                                f"{type(exc).__name__}: {exc}")
                self._close(first_item)
                return deferred
            if ctx.abandoned.is_set():
                # the watchdog fired during OUR build and already
                # answered the anchor client
                raise faults.AbandonedRun(ctx.request_id)
            fleet = self._fleet_for(entry)
            if fleet is None:
                # back to the executor as deferred work — which holds an
                # admission reservation, so the anchor's (consumed at
                # the worker's queue pop) must be re-taken or the
                # counter drifts negative and admission over-admits
                with svc._counters_lock:
                    svc._queued_runs += 1
                first_item["force_solo"] = True
                deferred.append(first_item)
                return deferred
            self._drive(ctx, entry, fleet, spec, digest, dt, first_item,
                        admitted, verdict, build_sec, deferred)
        except faults.AbandonedRun:
            # the watchdog already requeued the surviving members (or
            # answered the pending anchor) and quarantined the entry;
            # the deferred items still hold their admission reservations
            # — hand them straight back to the queue before unwinding
            for item in deferred:
                svc._queue.put(item)
            deferred = []
            raise
        except Exception as exc:
            # a batch-level blowup must not drop member connections
            # silently: every still-seated member gets a structured
            # `internal` reply, and the entry is discarded (its fleet
            # state is suspect)
            svc._count_error()
            logger.exception(f"batching: {batch_id} failed")
            svc.breaker.record_failure(digest)
            if ctx.pending_item is not None:
                svc._send_error(ctx.pending_item["wfile"], "internal",
                                f"{type(exc).__name__}: {exc}")
                self._close(ctx.pending_item)
                ctx.pending_item = None
            # seats exist only once _drive ran, so `fleet` is bound here
            for seat in list(ctx.seats.values()):
                if seat.active:
                    svc._send_error(seat.wfile, "internal",
                                    f"{type(exc).__name__}: {exc}")
                    self._release(ctx, fleet, seat, "internal")
            svc.pool.discard(digest)
        finally:
            with svc._active_lock:
                if svc._active_run is ctx:
                    svc._active_run = None
            with self._lock:
                self.batches += 1
                self.blocks += ctx.blocks
                event = {
                    "batch_id": batch_id,
                    "spec": protocol.spec_name(spec),
                    "members": ctx.seated,
                    "late_joins": ctx.late,
                    "blocks": ctx.blocks,
                    "peak_active": ctx.peak,
                    "detached": dict(ctx.detached),
                    "wall_sec": round(time.perf_counter() - t0, 4),
                    "abandoned": ctx.abandoned.is_set(),
                }
                self.batch_events.append(event)
        return deferred

    # ---------------------------------------------------------- the loop

    def _drive(self, ctx, entry, fleet, spec, digest, dt, first_item,
               admitted, verdict, build_sec, deferred):
        svc = self.service
        import jax
        template = entry.solver
        cadence = int(template.enforce_real_cadence or 0)
        sK = int(template.timestepper.steps)
        # any straggler seats from an abandoned predecessor batch on this
        # fleet are released (value operands only)
        for m in range(fleet.members):
            if fleet.active_host[m]:
                fleet.detach_member(m)
        fleet.set_fleet_dt(float(dt))
        # _seat itself manages ctx.pending_item: the item stays watchdog-
        # answerable through reset/IC-install/gather/attach, then
        # graduates to a requeue-able seat
        self._seat(ctx, entry, fleet, first_item, verdict, build_sec,
                   cadence, late=False, admitted=admitted)
        # opening coalescing window: requests that arrived together
        # batch together from block one (later arrivals still join at
        # boundaries)
        self._poll_joins(ctx, entry, fleet, digest, dt, cadence, deferred)
        # the reservation count is mutated by reader threads and the
        # drain sweep while this executor reads it — locked read (the
        # window decision only needs a point-in-time answer)
        with svc._counters_lock:
            queue_empty = svc._queued_runs == 0
        if self.batch_window > 0 and len(ctx.seats) == 1 and queue_empty:
            time.sleep(self.batch_window)
            self._poll_joins(ctx, entry, fleet, digest, dt, cadence,
                             deferred)

        def live_seats():
            return [s for s in ctx.seats.values() if s.active]

        def due(s):
            return cadence and s.steps_done % cadence < sK

        def window_dist(s):
            if not cadence:
                return 1 << 30
            r = s.steps_done % cadence
            return 1 if (r + 1) % cadence < sK else cadence - r

        while True:
            if ctx.abandoned.is_set():
                raise faults.AbandonedRun(ctx.request_id)
            live = live_seats()
            if not live:
                break
            if ctx.stop_why is not None:
                for s in live:
                    self._finish_member(ctx, entry, fleet, s,
                                        stopped_by=ctx.stop_why)
                break
            self._apply_chaos(ctx, entry, fleet, template, live)
            if ctx.abandoned.is_set():
                # a hang fault can out-sleep the watchdog: the batch was
                # declared dead mid-boundary
                raise faults.AbandonedRun(ctx.request_id)
            # per-member projection phase: exactly where each member's
            # own solo loop would project (block collapses to single
            # steps around projection windows — sizes stay {block, 1})
            project = [s.seat for s in live if due(s)]
            if project:
                fleet.project_members(project)
            n = self.batch_block if all(
                window_dist(s) >= self.batch_block for s in live) else 1
            t_block0 = time.perf_counter()
            taken = fleet.step_fleet(n)
            ctx.blocks += 1
            ctx.peak = max(ctx.peak, len(live))
            # boundary sync doubles as the health probe AND the watchdog
            # progress stamp: a wedged dispatch blocks here
            t_probe0 = time.perf_counter()
            nonfinite, max_abs = jax.device_get(fleet._probe())
            if tracing.enabled():
                # one block + boundary span per live member, so EVERY
                # member's exported trace shows the blocks it rode
                t_done = time.perf_counter()
                for s in live:
                    stctx = s.item.get("trace")
                    if stctx is None:
                        continue
                    blk = tracing.add_span(
                        "batch/block", t_done - t_block0, parent=stctx,
                        attrs={"block": ctx.blocks, "iters": int(n)})
                    tracing.add_span("batch/boundary", t_done - t_probe0,
                                     parent=blk)
            if ctx.abandoned.is_set():
                # the watchdog fired while we were stuck in the sync and
                # already requeued these members' sockets for the
                # replacement — touching them now would race it
                raise faults.AbandonedRun(ctx.request_id)
            ctx.last_progress = time.monotonic()
            now = time.monotonic()
            for s in live:
                s.steps_done += int(taken[s.seat])
                if s.ttfs is None and s.steps_done > 0:
                    s.ttfs = time.perf_counter() - s.t_dispatch
            for s in live:
                if ctx.abandoned.is_set():
                    raise faults.AbandonedRun(ctx.request_id)
                if not s.active:
                    continue
                bad = int(nonfinite[s.seat])
                grown = (np.isfinite(fleet.max_abs_limit)
                         and max_abs[s.seat] > fleet.max_abs_limit)
                if bad or grown:
                    reason = (f"non-finite state ({bad} entries)" if bad
                              else f"growth bound exceeded: max|coeff| = "
                                   f"{max_abs[s.seat]:.3e} > "
                                   f"{fleet.max_abs_limit:.3e}")
                    self._fail_member(ctx, entry, fleet, s, "health",
                                      f"run halted unrecoverably: {reason} "
                                      f"at iteration {s.steps_done}")
                elif s.steps_done >= s.steps_total:
                    self._finish_member(ctx, entry, fleet, s,
                                        stopped_by="completed")
                elif s.deadline_mono is not None \
                        and now >= s.deadline_mono:
                    svc._count("deadline_exceeded")
                    logger.warning(
                        f"batching: request {s.request_id} exceeded its "
                        f"{s.params['deadline_sec']}s deadline at "
                        f"iteration {s.steps_done}; stopping gracefully")
                    self._finish_member(ctx, entry, fleet, s,
                                        stopped_by="deadline-exceeded")
                elif s.progress_next and s.steps_done >= s.progress_next:
                    s.progress_next = (s.steps_done
                                       + s.params["progress_every"])
                    self._send_member(ctx, fleet, s, {
                        "kind": "progress", "id": s.request_id,
                        "iteration": s.steps_done,
                        "sim_time": float(fleet.sim_times[s.seat])})
            if ctx.stop_why is None and not ctx.abandoned.is_set():
                self._poll_joins(ctx, entry, fleet, digest, dt, cadence,
                                 deferred)

    # ---------------------------------------------------------- seating

    def _admit_member(self, ctx, item):
        """The pre-execution gauntlet one request passes before any
        solver work — the same sequence, in the same order, as the solo
        executor's queue pop: replay re-check, run-params (+ chaos
        gating) validation, circuit-breaker re-admit, queued-deadline.
        Returns {"request_id", "params", "probe"} on admission, or None
        when the request resolved here (replayed / refused / rejected —
        connection closed either way)."""
        svc = self.service
        header = item["header"]
        conn, wfile = item["conn"], item["wfile"]
        with svc._counters_lock:
            svc._request_seq += 1
            seq = svc._request_seq
        client_id = header.get("id")
        request_id = str(client_id or f"r{seq}")
        probe = bool(item.get("probe"))
        if client_id is not None and svc._send_replay(conn, wfile, header,
                                                      str(client_id)):
            if probe:
                svc.breaker.abandon_probe(ctx.digest)
            self._close(item)
            return None
        try:
            params = svc._run_params(header)
            chaos = header.get("chaos")
            if chaos is not None:
                if not svc.chaos_enabled:
                    raise protocol.SpecError(
                        "run: chaos injection is disabled on this daemon "
                        "(start it with --chaos; test deployments only)")
                self._validate_chaos(chaos)
        except protocol.SpecError as exc:
            svc._count_error()
            svc._send_error(wfile, "bad-spec", str(exc))
            if probe:
                svc.breaker.abandon_probe(ctx.digest)
            self._close(item)
            return None
        if not probe:
            allowed, retry_after, state = svc.breaker.admit(ctx.digest)
            if not allowed:
                svc._count_error()
                svc._send_error(
                    wfile, "circuit-open",
                    f"spec {ctx.digest[:12]} is cooling off after repeated "
                    f"failures; retry in ~{retry_after}s",
                    retry_after_sec=retry_after)
                self._close(item)
                return None
            probe = state == "probe"
            item["probe"] = probe
        deadline_mono = item.get("deadline_mono")
        if deadline_mono is not None and time.monotonic() >= deadline_mono:
            svc._count("deadline_exceeded")
            svc._count_error()
            svc._send_error(
                wfile, "deadline-exceeded",
                f"run: deadline_sec={params['deadline_sec']} elapsed "
                f"while queued")
            if probe:
                svc.breaker.abandon_probe(ctx.digest)
            self._close(item)
            return None
        return {"request_id": request_id, "params": params, "probe": probe}

    @staticmethod
    def _validate_chaos(chaos):
        """Structural validation of a batch chaos block at ADMISSION (the
        solo path's _build_chaos pre-coercion, for the batch keys): a
        malformed block must be a bad-spec reply to ITS request — never
        a mid-batch blowup that takes co-tenants down."""
        try:
            if "nan_field" in chaos:
                if not isinstance(chaos["nan_field"], str):
                    raise protocol.SpecError(
                        f"run: chaos nan_field must be a field name, got "
                        f"{chaos['nan_field']!r}")
                int(chaos.get("nan_iteration", 0))
            if "hang_iteration" in chaos:
                if "hang_sec" not in chaos:
                    raise protocol.SpecError(
                        "run: chaos hang_iteration requires hang_sec")
                int(chaos["hang_iteration"])
                float(chaos["hang_sec"])
        except (TypeError, ValueError) as exc:
            raise protocol.SpecError(f"run: bad chaos block: {exc}")

    def _seat(self, ctx, entry, fleet, item, verdict, build_sec,
              cadence, late, admitted=None):
        """Seat one request as a batch member: the admission gauntlet
        (unless the caller already ran it — the anchor admits BEFORE the
        batch-level build), then IC install on the (reset) template, row
        gather, attach, the multistep cohort ramp, and the ack. Returns
        the seat, or None when the request resolved without seating
        (connection closed either way)."""
        svc = self.service
        header = item["header"]
        wfile = item["wfile"]
        if admitted is None:
            admitted = self._admit_member(ctx, item)
            if admitted is None:
                return None
        request_id = admitted["request_id"]
        params = admitted["params"]
        probe = admitted["probe"]
        tctx = item.get("trace")
        t_seat0 = time.perf_counter()
        if tctx is not None:
            # the member's queue wait ends here, at its seat attempt
            tracing.add_span("queue",
                             time.perf_counter() - item["t_accept"],
                             parent=tctx)
        # from here until the seat registers in ctx.seats, the request
        # is covered as the PENDING item: a watchdog fire mid-seating
        # (wedged reset/gather/attach) answers this client instead of
        # leaving it neither requeued nor closed
        ctx.pending_item = item
        # ---- IC install on the reset template, then row gather
        template = entry.solver
        try:
            ics = (protocol.decode_fields(item["payload"])
                   if item["payload"] else {})
            svc.pool.reset_entry(entry)
            svc._install_ics(template, ics)
            svc._output_fields(template, params["outputs"])  # validate
        except protocol.SpecError as exc:
            svc._count_error()
            svc._send_error(wfile, "bad-spec", str(exc))
            if probe:
                svc.breaker.abandon_probe(ctx.digest)
            self._close(item)
            ctx.pending_item = None
            return None
        # seats are reusable: a detached member's seat frees up for the
        # next join (attach overwrites every per-seat row), so a long-
        # lived batch with churn never runs out
        seat_idx = next(m for m in range(fleet.members)
                        if not fleet.active_host[m])
        X_row = template.gather_fields()
        extras_rows = template.rhs_extra()
        fleet.attach_member(seat_idx, X_row, extras_rows=extras_rows,
                            sim_time=0.0, steps=params["stop_iteration"])
        seat = _Seat(item, seat_idx, request_id, params, verdict,
                     build_sec, late, fleet.iteration)
        # register the seat, THEN drop pending coverage: a fire landing
        # in between sees both and must not serve the request twice —
        # on_watchdog skips a seat whose item IS the answered pending
        ctx.seats[seat_idx] = seat
        ctx.pending_item = None
        ctx.seated += 1
        with self._lock:
            self.members_seated += 1
            if late:
                self.late_joins += 1
            self.peak_members = max(self.peak_members,
                                    sum(1 for s in ctx.seats.values()
                                        if s.active))
        if late:
            ctx.late += 1
        # seating IS progress: a join-heavy boundary (several resets +
        # IC installs + ramps back to back) must not read as a hung
        # dispatch to the watchdog
        ctx.last_progress = time.monotonic()
        # multistep cohort ramp: the joiner's own order build-up, solo-
        # projected, with everyone else frozen (bit-identity with solo)
        ramped = fleet.ramp_members([seat_idx], project=bool(cadence))
        seat.steps_done += min(ramped, seat.steps_total)
        if tctx is not None:
            # seat span covers reset + IC install + attach + ramp;
            # stamp the resolved plan + batch identity on the trace root
            tracing.add_span("batch/join" if late else "batch/seat",
                             time.perf_counter() - t_seat0, parent=tctx,
                             attrs={"batch_id": ctx.request_id,
                                    "seat": seat_idx, "late_join": late})
            tctx.attrs.setdefault("request_id", request_id)
            tctx.attrs.update(batch_id=ctx.request_id,
                              pool_verdict=seat.verdict)
            if hasattr(template, "plan_provenance"):
                tctx.attrs.update(plan=template.plan_provenance(),
                                  pool_key=str(entry.key)[:16])
        try:
            protocol.send_frame(wfile, {
                "kind": "ack", "id": request_id,
                "pool_verdict": seat.verdict,
                "queue_sec": round(seat.queue_sec, 6),
                "build_sec": round(seat.build_sec, 4),
                "batch": {"id": ctx.request_id, "seat": seat_idx,
                          "members": sum(1 for s in ctx.seats.values()
                                         if s.active),
                          "late_join": late}})
        except OSError:
            svc._count("client_drops")
            logger.warning(f"batching: client for {request_id} vanished "
                           "before the ack; member released")
            if probe:
                svc.breaker.abandon_probe(ctx.digest)
            self._release(ctx, fleet, seat, "client-drop")
        return seat

    def _poll_joins(self, ctx, entry, fleet, digest, dt, cadence,
                    deferred):
        """Boundary join point: drain currently-queued items; same-batch
        requests seat while seats remain, everything else defers to the
        executor (processed, in order, after this batch). FAIRNESS: once
        anything has been deferred, the batch stops coalescing entirely
        — continuous same-spec traffic could otherwise keep the batch
        alive forever while the deferred work starves. The batch then
        drains at the pace of its current members (bounded by their stop
        iterations/deadlines) and the executor serves the deferred items
        next."""
        svc = self.service
        import queue as queue_mod
        if deferred:
            return
        while fleet.n_active < fleet.members and svc._draining is None \
                and not ctx.abandoned.is_set():
            try:
                item = svc._queue.get_nowait()
            except queue_mod.Empty:
                return
            if item is None:
                # the drain sentinel: not ours to consume
                svc._queue.put(None)
                return
            if self._matches(item, digest, dt):
                # seated (or answered) right now: its admission
                # reservation is consumed here
                with svc._counters_lock:
                    svc._queued_runs -= 1
                self._seat(ctx, entry, fleet, item, "hit", 0.0,
                           cadence, late=True)
            else:
                # deferred work KEEPS its reservation — it is still in
                # the system, and admission control must keep counting
                # it against QUEUE_DEPTH until an executor handles it
                deferred.append(item)

    # --------------------------------------------------------- detaching

    def _apply_chaos(self, ctx, entry, fleet, template, live):
        """Per-member boundary faults (only reachable on a --chaos
        daemon; keys AND content validated at admission): each fires
        once, against the requesting member only. A fault body that
        still blows up (e.g. nan_field naming no state variable of THIS
        template — unknowable until the template exists) fails ONLY its
        member: blast radius zero applies to the chaos machinery too."""
        for s in live:
            ch = s.chaos
            if not ch or not s.active:
                continue
            try:
                if "nan_field" in ch and "nan" not in s.chaos_fired \
                        and s.steps_done >= int(ch.get("nan_iteration", 0)):
                    s.chaos_fired.add("nan")
                    from ..tools import chaos as chaos_mod
                    chaos_mod.poison_fleet_member(fleet, template, s.seat,
                                                  ch["nan_field"])
                    logger.warning(f"batching chaos: poisoned member "
                                   f"{s.request_id} (seat {s.seat}) at "
                                   f"iteration {s.steps_done}")
                if "hang_iteration" in ch and "hang" not in s.chaos_fired \
                        and s.steps_done >= int(ch["hang_iteration"]):
                    s.chaos_fired.add("hang")
                    logger.warning(f"batching chaos: hanging the batch "
                                   f"boundary for {ch['hang_sec']}s "
                                   f"(member {s.request_id})")
                    time.sleep(float(ch["hang_sec"]))
            except Exception as exc:
                logger.exception(f"batching chaos: fault body for "
                                 f"{s.request_id} failed")
                self._fail_member(ctx, entry, fleet, s, "bad-spec",
                                  f"run: chaos block failed to apply: "
                                  f"{type(exc).__name__}: {exc}")

    def _send_member(self, ctx, fleet, s, frame, payload=None):
        """One frame to one member's client; a dead socket marks the
        member ONCE and applies ON_CLIENT_DROP (abort detaches at this
        boundary, complete keeps stepping for the replay cache)."""
        svc = self.service
        if s.client_gone:
            return False
        try:
            protocol.send_frame(s.wfile, frame, payload=payload)
            return True
        except OSError:
            s.client_gone = True
            svc._count("client_drops")
            if svc.on_client_drop == "abort" and s.active:
                logger.warning(
                    f"batching: client for {s.request_id} disconnected; "
                    "detaching the member at this boundary "
                    "(ON_CLIENT_DROP = abort)")
                if s.probe:
                    # an aborted probe carries no verdict on the spec
                    svc.breaker.abandon_probe(ctx.digest)
                self._release(ctx, fleet, s, "client-drop")
            elif s.active:
                logger.warning(
                    f"batching: client for {s.request_id} disconnected; "
                    "member completes for the replay cache "
                    "(ON_CLIENT_DROP = complete)")
            return False

    def _member_record(self, ctx, fleet, s, entry):
        """The member's telemetry record (the step_metrics wire/sink
        format with the serving + batch occupancy fields)."""
        template = entry.solver
        wall = time.perf_counter() - s.t_dispatch
        serving = {
            "queue_sec": round(s.queue_sec, 6),
            "pool_verdict": s.verdict,
            "time_to_first_step_sec": (round(s.ttfs, 6)
                                       if s.ttfs is not None else None),
            "build_sec": round(s.build_sec, 4),
            "request_id": s.request_id,
            "batch": {
                "id": ctx.request_id,
                "seat": s.seat,
                "late_join": s.late,
                "members_active": sum(1 for x in ctx.seats.values()
                                      if x.active),
                "joined_iteration": s.joined_iteration,
            },
        }
        if s.params["deadline_sec"] is not None:
            serving["deadline_sec"] = s.params["deadline_sec"]
        tctx = s.item.get("trace")
        if tctx is not None:
            serving["trace_id"] = tctx.trace_id
        from ..tools import retrace as retrace_mod
        record = {
            "kind": "step_metrics",
            "ts": round(time.time(), 1),
            "config": f"{protocol.spec_name(entry.spec)}_served",
            "backend": fleet.metrics.meta.get("backend"),
            "dtype": str(np.dtype(template.pencil_dtype)),
            "pencil_shape": list(template.pencil_shape),
            "iterations": s.steps_done,
            "loop_wall_sec": round(wall, 6),
            "steps_per_sec": round(s.steps_done / wall, 4)
            if wall > 0 else 0.0,
            "retraces_post_warmup": retrace_mod.sentinel.post_arm_retraces,
            "serving": serving,
        }
        if hasattr(template, "plan_provenance"):
            # the fleet executes the template's resolved plan, vmapped
            record["plan"] = template.plan_provenance()
        return record, serving

    def _member_fields(self, fleet, entry, s):
        """Extract one member's final fields in the requested layout —
        the same field reads the solo reply path performs, against the
        member's rows (state scattered into the template; parameter
        operands re-presented from the member's extras rows)."""
        svc = self.service
        template = entry.solver
        fleet.load_member(s.seat)
        for k, field in enumerate(template.eval_F.extra_fields):
            field.preset_coeff(np.asarray(fleet._extras[k][s.seat]))
            field.mark_modified()
        targets = svc._output_fields(template, s.params["outputs"])
        out_fields = {}
        for var in targets:
            if s.params["layout"] == "c":
                out_fields[var.name] = ("c", np.asarray(var.coeff_data()))
            else:
                out_fields[var.name] = ("g", np.array(var["g"]))
        return out_fields

    def _member_checkpoint(self, fleet, entry, s):
        """Durable per-member checkpoint at a graceful stop: the
        member's state is scattered into the template and written
        through the same evaluator FileHandler path a solo served run
        uses, so `resume: true` on a solo re-submission restores it
        (validated by resume_latest)."""
        checkpoint = s.params["checkpoint"]
        if checkpoint is None:
            return
        template = entry.solver
        fleet.load_member(s.seat)
        template.sim_time = float(fleet.sim_times[s.seat])
        template.iteration = s.steps_done
        handler = template.evaluator.add_file_handler(
            checkpoint["dir"], max_writes=1, mode="append")
        try:
            for var in template.state:
                handler.add_task(var, layout="c", name=var.name)
            handler.process(iteration=s.steps_done,
                            wall_time=time.perf_counter() - s.t_dispatch,
                            sim_time=float(fleet.sim_times[s.seat]),
                            timestep=float(fleet.dts[s.seat]))
        finally:
            try:
                template.evaluator.handlers.remove(handler)
            except ValueError:
                pass

    def _finish_member(self, ctx, entry, fleet, s, stopped_by):
        """Graceful member exit (completion, deadline, drain): durable
        checkpoint when configured, telemetry record, result frame
        (cached first for idempotent replay), detach."""
        svc = self.service
        try:
            self._member_checkpoint(fleet, entry, s)
        except Exception as exc:
            logger.warning(f"batching: member checkpoint for "
                           f"{s.request_id} failed: {exc}")
        record, serving = self._member_record(ctx, fleet, s, entry)
        svc._emit(record)
        try:
            out_fields = self._member_fields(fleet, entry, s)
            payload = protocol.encode_fields(out_fields)
        except Exception as exc:
            svc._count_error()
            logger.exception(f"batching: result extraction for "
                             f"{s.request_id} failed")
            svc._send_error(s.wfile, "internal",
                            f"{type(exc).__name__}: {exc}")
            self._release(ctx, fleet, s, "internal")
            return
        result = {
            "kind": "result", "id": s.request_id,
            "iteration": s.steps_done,
            "sim_time": float(fleet.sim_times[s.seat]),
            "stopped_by": stopped_by,
            "rewinds": 0,
            "serving": serving,
        }
        if s.client_id is not None:
            svc.results.put(str(s.client_id), record, result, payload,
                            fingerprint=svc._run_fingerprint(s.header))
        # a graceful finish judges the spec healthy (the solo rule); the
        # run completed even when the client stopped listening
        svc.breaker.record_success(ctx.digest)
        t_send0 = time.perf_counter()
        self._send_member(ctx, fleet, s, record)
        self._send_member(ctx, fleet, s, result, payload=payload)
        tctx = s.item.get("trace")
        if tctx is not None:
            tracing.add_span("result_send",
                             time.perf_counter() - t_send0, parent=tctx,
                             attrs={"payload_bytes": len(payload)})
            tctx.attrs.setdefault("outcome", stopped_by)
        svc._count("requests_served")
        svc._observe_run_wall(s.t_dispatch)
        self._release(ctx, fleet, s, "deadline"
                      if stopped_by == "deadline-exceeded"
                      else ("completed" if stopped_by == "completed"
                            else "drain"))

    def _fail_member(self, ctx, entry, fleet, s, code, message):
        """Structured member failure (divergence): telemetry, error
        frame, breaker accounting, detach — the batch keeps stepping."""
        svc = self.service
        svc._count_error()
        if s.client_gone and s.probe:
            # a dead client says nothing about the SPEC: release the
            # half-open probe slot instead of judging it
            svc.breaker.abandon_probe(ctx.digest)
        else:
            svc.breaker.record_failure(ctx.digest)
        record, _serving = self._member_record(ctx, fleet, s, entry)
        svc._emit(record)
        svc._send_error(s.wfile, code, message)
        logger.warning(f"batching: member {s.request_id} failed "
                       f"({code}): {message}")
        self._release(ctx, fleet, s, "health" if code == "health"
                      else code)

    def _release(self, ctx, fleet, s, cause):
        """Detach a seat and close its connection — the single seat-
        bookkeeping point, idempotent: a client that drops INSIDE its
        own finish path (the record send fails, the abort branch fires)
        must not be counted or closed twice."""
        if s.released:
            return
        s.released = True
        if s.active:
            s.active = False
            fleet.detach_member(s.seat)
        tctx = s.item.get("trace")
        if tctx is not None:
            tracing.add_span("batch/detach", 0.0, parent=tctx,
                             attrs={"cause": cause})
            tctx.attrs.setdefault("outcome", cause)
        ctx.detached[cause] += 1
        with self._lock:
            self.detached[cause] += 1
        self._close(s.item)

    def _close(self, item):
        # every member connection closes through here, so this is also
        # where a member's trace is finished + flushed (idempotent; a
        # watchdog-requeued survivor keeps its open trace because its
        # item is requeued, never closed)
        self.service._finish_trace(item.get("trace"))
        try:
            item["conn"].close()
        except OSError:
            pass

    # ---------------------------------------------------------- watchdog

    def on_watchdog(self, ctx, stuck_sec):
        """The watchdog declared this batch hung (no boundary progress
        within WATCHDOG_SEC): abandon it, postmortem it, quarantine the
        pool entry (and with it the fleet the wedged executor may still
        be dispatching on), and REQUEUE every surviving member's request
        so the replacement executor re-runs them — member requests are
        the unit of replay, not the batch. Runs on the watchdog
        thread."""
        svc = self.service
        ctx.abandoned.set()
        svc._count("watchdog_fires")
        svc._count_error()
        pending = ctx.pending_item
        if pending is not None:
            # the batch never got past its build: the anchor's client is
            # answered like a solo watchdog fire (re-running a hung
            # build would just hang the replacement too)
            ctx.pending_item = None
            svc._send_error(
                pending["wfile"], "watchdog-timeout",
                f"no progress within {svc.watchdog_sec}s during the "
                f"batch build ({ctx.request_id}); postmortem recorded")
            self._close(pending)
        survivors, gone = [], 0
        # snapshot: the executor may be inserting a seat concurrently
        # (list() of the view is C-atomic under the GIL; iterating the
        # live dict would race a resize)
        for s in list(ctx.seats.values()):
            if not s.active:
                continue
            if s.item is pending:
                # the fire raced the seat registration: this request was
                # already answered through the pending branch above
                continue
            if s.client_gone:
                gone += 1
                self._close(s.item)
            else:
                survivors.append(s)
        record = {
            "kind": "watchdog_postmortem",
            "request_id": ctx.request_id,
            "batch": True,
            "member_requests": [s.request_id
                                for s in list(ctx.seats.values())],
            "requeued": [s.request_id for s in survivors],
            "stuck_sec": round(stuck_sec, 3),
            "watchdog_sec": svc.watchdog_sec,
            "request_age_sec": round(time.monotonic() - ctx.started_ts, 3),
            "stacks": faults.thread_stacks(),
            # held/waiting named-lock map per thread (non-empty only when
            # the runtime lock-order sanitizer is enabled)
            "held_locks": faults.held_locks(),
        }
        logger.error(
            f"batching: WATCHDOG — {ctx.request_id} made no boundary "
            f"progress for {stuck_sec:.1f}s (> {svc.watchdog_sec}s); "
            f"abandoning the batch, requeuing {len(survivors)} surviving "
            f"member(s) on the replacement executor")
        svc._emit(record)
        if ctx.digest is not None:
            svc.breaker.record_failure(ctx.digest)
            svc.pool.discard(ctx.digest)
        with self._lock:
            self.detached["watchdog"] += len(survivors) + gone
        for s in survivors:
            # the member's original item re-enters the queue intact
            # (connection open, payload kept, absolute deadline kept);
            # idempotent ids make a doubled execution safe. Any chaos
            # block is STRIPPED — each armed fault fires once per
            # request (the chaos contract), so the replay runs clean
            # instead of re-wedging every replacement executor.
            s.header.pop("chaos", None)
            svc.requeue_item(s.item)
