"""
Service-level fault tolerance primitives for the warm-pool daemon
(dedalus_tpu/service/server.py): the request-path siblings of the
step-loop machinery in tools/resilience.py.

PR 4's resilience protects a single solve loop (rewind, dt backoff,
errno-classified IO retry); this module lifts the same discipline one
layer up, to the orchestration layer that distributed solver stacks
assume absorbs node and task failures:

  * `CircuitBreaker` — per-spec failure accounting. A spec whose build
    or run fails `failures` consecutive times enters a cooling-off
    period during which requests fast-fail with a structured
    `circuit-open` error (carrying `retry_after_sec`) instead of
    monopolizing the single executor; after the cool-off ONE probe
    request is admitted (half-open), and its success closes the circuit
    while a failure re-opens it with the cool-off doubled (capped).

  * `ResultCache` — a small LRU of completed run results keyed by the
    CLIENT-provided request id, so an idempotent retry after a dropped
    `result` frame re-fetches the finished outcome instead of
    re-running the solve.

  * `Watchdog` — a monitor thread that detects a hung JAX dispatch (no
    step progress on the active run within `watchdog_sec`) and invokes
    the server's fire callback, which fails the request with a
    postmortem (thread stacks + request context) and replaces the
    wedged executor thread instead of wedging the daemon forever.

  * `RunContext` / `AbandonedRun` — the per-request state the executor
    and the watchdog share, and the exception a watchdog-abandoned run
    raises from its step hook so the stale executor unwinds without
    touching the (already answered, already closed) connection.

Everything here is plain host-side Python — no JAX, no solver imports —
so the primitives are unit-testable without a built solver, and the
chaos suite (tools/chaos.py service faults) drives every branch
deterministically in tier-1.
"""

import logging
import sys
import threading
import time
import traceback
from collections import OrderedDict

from ..tools.lint.threadcheck import named_lock

logger = logging.getLogger(__name__)

__all__ = ["AbandonedRun", "CircuitBreaker", "ResultCache", "RunContext",
           "Watchdog"]


class AbandonedRun(Exception):
    """Raised from a run's step hook after the watchdog declared the run
    hung and answered the client: the stale executor must unwind without
    replying (the watchdog already sent `watchdog-timeout` and closed
    the connection) and without consuming further queue items."""


class RunContext:
    """Shared per-request state between the executor thread (writes) and
    the watchdog thread (reads). `last_progress` is a monotonic-clock
    float updated on dispatch start and after every completed step;
    single-word float stores are atomic under the GIL, so no lock is
    needed on the hot path."""

    __slots__ = ("request_id", "digest", "conn", "wfile", "loop",
                 "deadline_ts", "last_progress", "abandoned",
                 "deadline_fired", "client_gone", "probe", "started_ts",
                 "header", "trace")

    def __init__(self, request_id, digest, conn, wfile, loop,
                 deadline_ts=None, probe=False, header=None, trace=None):
        self.request_id = request_id
        self.digest = digest
        self.conn = conn
        self.wfile = wfile
        self.loop = loop
        self.header = header
        self.trace = trace    # tools/tracing.TraceContext (None: off)
        self.deadline_ts = deadline_ts
        self.last_progress = time.monotonic()
        self.abandoned = threading.Event()
        self.deadline_fired = False
        self.client_gone = False
        self.probe = probe
        self.started_ts = time.monotonic()


# ------------------------------------------------------- circuit breaker

class CircuitBreaker:
    """
    Per-key (spec-digest) circuit breaker. States per key:

        closed     requests pass; consecutive failures counted
        open       requests fast-fail until `cooloff` elapses
        half-open  one probe request admitted; outcome decides

    `admit(key)` returns (allowed, retry_after_sec, state); when it
    admits the half-open probe, `state` is "probe" and the caller must
    eventually report `record_success`/`record_failure` (or
    `abandon_probe` when the outcome was the CLIENT's fault — a dropped
    connection says nothing about the spec) or the key stays probing.
    Keys are LRU-bounded so a storm of unique poisoned specs cannot grow
    the table without bound. All methods are thread-safe (reader threads
    admit, the executor records).
    """

    def __init__(self, failures=3, cooloff_sec=30.0, max_cooloff_sec=600.0,
                 max_keys=256):
        self.failures = max(int(failures), 1)
        self.cooloff_sec = float(cooloff_sec)
        self.max_cooloff_sec = float(max_cooloff_sec)
        self.max_keys = int(max_keys)
        self._keys = OrderedDict()   # key -> state dict
        self._lock = named_lock(
            "service/faults.py:CircuitBreaker._lock")
        self.opens = 0
        self.fastfails = 0
        self.closes = 0

    def _entry(self, key):
        entry = self._keys.get(key)
        if entry is None:
            entry = self._keys[key] = {
                "fails": 0, "state": "closed", "opened_ts": 0.0,
                "cooloff": self.cooloff_sec, "probing": False}
            while len(self._keys) > self.max_keys:
                self._keys.popitem(last=False)
        self._keys.move_to_end(key)
        return entry

    def admit(self, key):
        """Gate one request. Returns (allowed, retry_after_sec, state)
        with state in {"closed", "probe", "open"}."""
        now = time.monotonic()
        with self._lock:
            entry = self._keys.get(key)
            if entry is None or entry["state"] == "closed":
                return True, 0.0, "closed"
            self._keys.move_to_end(key)
            remaining = entry["opened_ts"] + entry["cooloff"] - now
            if entry["state"] == "open" and remaining <= 0:
                entry["state"] = "half-open"
            if entry["state"] == "half-open" and not entry["probing"]:
                entry["probing"] = True
                logger.info(f"breaker: half-open probe admitted for "
                            f"{key[:12]}")
                return True, 0.0, "probe"
            self.fastfails += 1
            return False, round(max(remaining, 0.1), 1), "open"

    def record_success(self, key):
        with self._lock:
            entry = self._keys.get(key)
            if entry is None:
                return
            if entry["state"] != "closed":
                self.closes += 1
                logger.info(f"breaker: circuit for {key[:12]} closed")
            entry.update(fails=0, state="closed", probing=False,
                         cooloff=self.cooloff_sec)

    def record_failure(self, key):
        """Count one build/run failure; open (or re-open, with the
        cool-off doubled) when the consecutive budget is spent. A
        failure recorded while ALREADY open (e.g. queued work admitted
        before the circuit tripped) counts but neither re-stamps the
        cool-off clock — clients were already told a retry_after — nor
        inflates the opens counter."""
        now = time.monotonic()
        with self._lock:
            entry = self._entry(key)
            entry["fails"] += 1
            if entry["state"] == "open":
                return
            reopened = entry["state"] == "half-open"
            if reopened or entry["fails"] >= self.failures:
                if reopened:
                    entry["cooloff"] = min(entry["cooloff"] * 2.0,
                                           self.max_cooloff_sec)
                entry.update(state="open", opened_ts=now, probing=False)
                self.opens += 1
                logger.warning(
                    f"breaker: circuit OPEN for {key[:12]} "
                    f"({entry['fails']} consecutive failures, cool-off "
                    f"{entry['cooloff']:.1f}s)")

    def abandon_probe(self, key):
        """The half-open probe ended without a verdict on the SPEC (the
        client vanished, the daemon drained): return the key to
        half-open so the next request probes again."""
        with self._lock:
            entry = self._keys.get(key)
            if entry is not None and entry["state"] == "half-open":
                entry["probing"] = False

    def state(self, key):
        with self._lock:
            entry = self._keys.get(key)
            return entry["state"] if entry else "closed"

    def stats(self):
        with self._lock:
            open_keys = [k[:12] for k, e in self._keys.items()
                         if e["state"] != "closed"]
            return {"opens": self.opens, "closes": self.closes,
                    "fastfails": self.fastfails, "open": open_keys}


# ----------------------------------------------------------- result cache

class ResultCache:
    """LRU of completed run results keyed by client-provided request id:
    (telemetry_record_or_None, result_header, payload_bytes,
    fingerprint). The fingerprint identifies WHAT ran (spec digest +
    outcome-affecting run params); the server refuses to replay an id
    whose retry carries a different fingerprint — an id can never serve
    another request's result. Sized in entries (`[service]
    RESULT_CACHE`; 0 disables) AND bytes (`max_bytes`, default 256 MiB
    of payload — protocol payloads can legitimately reach 256 MiB each,
    and an entry-count bound alone would let a fleet of retrying
    large-grid clients pin gigabytes of npz in daemon RSS). Thread-safe
    (reader threads serve replays while the executor stores
    completions)."""

    def __init__(self, size=16, max_bytes=256 * 2**20):
        self.size = int(size)
        self.max_bytes = int(max_bytes)
        self._entries = OrderedDict()
        self._bytes = 0
        self._lock = named_lock(
            "service/faults.py:ResultCache._lock")
        self.replays = 0

    def __len__(self):
        with self._lock:
            return len(self._entries)

    @property
    def payload_bytes(self):
        with self._lock:
            return self._bytes

    def put(self, request_id, record, result, payload, fingerprint=None):
        if self.size <= 0 or not request_id:
            return
        n = len(payload) if payload else 0
        if n > self.max_bytes:
            return   # one oversized result must not flush everything
        with self._lock:
            old = self._entries.pop(request_id, None)
            if old is not None:
                self._bytes -= len(old[2]) if old[2] else 0
            self._entries[request_id] = (record, result, payload,
                                         fingerprint)
            self._bytes += n
            while self._entries and (len(self._entries) > self.size
                                     or self._bytes > self.max_bytes):
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= len(dropped[2]) if dropped[2] else 0

    def get(self, request_id, fingerprint=None):
        """The cached (record, result, payload, fingerprint) for one id,
        or None. A non-None `fingerprint` must MATCH the stored one —
        an id reused with a different spec/params is a miss (the fresh
        run then overwrites the entry). Counts a replay when found."""
        if self.size <= 0 or not request_id:
            return None
        with self._lock:
            entry = self._entries.get(request_id)
            if entry is None:
                return None
            if fingerprint is not None and entry[3] is not None \
                    and entry[3] != fingerprint:
                return None
            self._entries.move_to_end(request_id)
            self.replays += 1
            return entry

    def clear(self):
        """Drop every cached result (the memory-watermark shedding path:
        cached payloads can dominate RSS for large-grid results, and
        replayability is worth less than the daemon staying alive).
        Returns the number dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            return n


# --------------------------------------------------------------- watchdog

def thread_stacks():
    """Formatted stack of every live thread — the postmortem of a hung
    dispatch (which thread is wedged, and where)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        stack = "".join(traceback.format_stack(frame, limit=12))
        out.append(f"thread {names.get(ident, ident)}:\n{stack}")
    return out


def held_locks():
    """Per-thread held/waiting named-lock map for the postmortem record:
    which service locks each thread holds and the one it is blocked on,
    when the runtime lock-order sanitizer is enabled ({} when it is off
    — the default — so the record stays cheap and honest)."""
    from ..tools.lint.threadcheck import held_locks_dump
    return held_locks_dump()


class Watchdog:
    """
    Hung-dispatch detector: polls `get_active()` (a RunContext or None)
    and calls `on_fire(ctx, stuck_sec)` ONCE per context when
    `now - ctx.last_progress` exceeds `watchdog_sec`. A legitimate pool
    miss pays its build + first-step compile before the first
    `last_progress` update, so `watchdog_sec` must exceed the worst-case
    cold start (docs/serving.md; the assembly + XLA caches keep that
    small in practice). `stop()` ends the thread at drain.
    """

    def __init__(self, get_active, on_fire, watchdog_sec, poll_sec=None):
        self.get_active = get_active
        self.on_fire = on_fire
        self.watchdog_sec = float(watchdog_sec)
        self.poll_sec = (float(poll_sec) if poll_sec is not None
                         else max(min(self.watchdog_sec / 4.0, 1.0), 0.05))
        self._stop = threading.Event()
        self._fired_for = None
        self._thread = None

    def start(self):
        if self.watchdog_sec <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._watch,
                                        name="service-watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _watch(self):
        while not self._stop.wait(self.poll_sec):
            ctx = self.get_active()
            if ctx is not self._fired_for:
                # the fired run is no longer active (idle daemon OR the
                # replacement already serves a new one): drop the
                # reference — it transitively pins the abandoned
                # (quarantined) solver's memory, which is exactly what
                # the fire freed
                self._fired_for = None
            if ctx is None or ctx is self._fired_for:
                continue
            stuck = time.monotonic() - ctx.last_progress
            if stuck < self.watchdog_sec:
                continue
            self._fired_for = ctx
            try:
                self.on_fire(ctx, stuck)
            except Exception:
                logger.exception("watchdog: fire callback failed")
