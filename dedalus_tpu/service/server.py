"""
The warm-pool solver daemon: `python -m dedalus_tpu serve`.

One accept loop (main thread) spawns a lightweight reader thread per
connection: control requests (`ping`/`stats`/`shutdown`) are answered
immediately there — never starved behind a long run — while `run`
requests enqueue for the SINGLE executor thread that owns every solver
in the LRU pool (service/pool.py). JAX dispatch stays single-threaded,
and the queue wait is measured per request as `queue_sec`. Each run
executes through the existing resilient evolve path
(tools/resilience.ResilientLoop), so a served run gets the same
snapshot-rewind/dt-backoff recovery and durable checkpointing as a
local `solver.evolve_resilient(...)` call.

Request-path fault tolerance (service/faults.py; the orchestration-
layer sibling of the PR-4 solve-loop resilience):

  * admission control — the run queue is bounded ([service]
    QUEUE_DEPTH); excess work is refused with a structured `overloaded`
    error carrying `retry_after_sec` (estimated from the recent
    per-request wall EWMA), and a process-RSS watermark ([service]
    MEM_WATERMARK_MB) triggers LRU pool eviction BEFORE an OOM instead
    of after;
  * per-request deadlines — clients submit `deadline_sec`; the executor
    checks it at queue pop (structured `deadline-exceeded` error before
    any stepping) and the step hook enforces it mid-run (graceful stop
    through the resilient loop, final durable checkpoint when the
    request configured one, result frame with
    `stopped_by: "deadline-exceeded"`);
  * a watchdog — no step progress on the active run within [service]
    WATCHDOG_SEC fails the request with a postmortem (thread stacks +
    request context, emitted to the sink as a `watchdog_postmortem`
    record), answers the client with `watchdog-timeout`, and REPLACES
    the wedged executor thread (worker generations) so one hung JAX
    dispatch cannot wedge the daemon forever;
  * a per-spec circuit breaker — specs whose build or run fails
    BREAKER_FAILURES consecutive times cool off with fast-fail
    `circuit-open` replies, half-open probe on expiry, close on probe
    success;
  * idempotent replay — completed results are cached by client-provided
    request id (RESULT_CACHE entries), so a retry after a dropped
    `result` frame re-fetches the outcome instead of re-running;
  * client-drop handling — a dead client socket detected mid-stream
    (progress/telemetry send fails) either lets the run complete or
    aborts it at the next step boundary ([service] ON_CLIENT_DROP),
    counted once, with the run's single telemetry flush intact.

Continuous batching (`serve --batch`, service/batching.py): concurrent
run requests whose specs canonicalize to the same pool key coalesce into
ONE vmapped EnsembleSolver micro-batch on the executor — late arrivals
join at block boundaries, per-member deadlines/divergence/client-drops
detach members without perturbing the rest (blast-radius zero, results
bit-identical to solo serving), and a wedged batch is abandoned with its
surviving members requeued for the replacement executor. Requests that
cannot batch (resume, sim-time stops, solo-only chaos) take the solo
path below unchanged.

Graceful drain: SIGTERM/SIGINT (or a `shutdown` request) stop the accept
loop, request a cooperative stop on the in-flight loop via the PR-4
stop-request machinery — the current step completes, a final durable
checkpoint is written when the request configured one, and the client
receives its telemetry + result frames — then queued-but-unstarted
connections get a structured `draining` error and the daemon exits 0
after flushing a `service_stats` record to the telemetry sink.

Served-latency fields stamped on every request's telemetry record
(under `serving`; tools/metrics.py documents the vocabulary):
`queue_sec`, `pool_verdict` (hit | warm-cache | cold),
`time_to_first_step_sec` (dispatch start -> first step complete,
INCLUDING any build/compile a pool miss pays — the metric the warm pool
exists to collapse), `build_sec`, `request_id`, and `deadline_sec`
when the request set one. Shed/deadline/watchdog/breaker/drop/replay
counters ride the `stats` reply and the final `service_stats` record.
"""

import argparse
import collections
import contextlib
import json
import logging
import queue
import signal
import socket
import sys
import threading
import time

import numpy as np

from . import batching, faults, protocol
from .pool import SolverPool
from ..tools import metrics as metrics_mod
from ..tools import tracing
from ..tools.config import cfg_get
from ..tools.lint.threadcheck import named_lock

logger = logging.getLogger(__name__)

__all__ = ["SolverService", "main"]

# minimum transfer rate assumed when extending an absolute socket
# deadline for a large declared payload: a legitimate slow link gets
# IDLE_TIMEOUT_SEC + bytes/RATE to move the data (steady progress on a
# big IC upload or result download must not be refused), while a
# byte-dripper is still cut off in bounded time
MIN_TRANSFER_BYTES_PER_SEC = 1 << 20

# HELP strings for the latency histograms exported on the Prometheus
# surface (prom_text / GET /metrics)
_HIST_HELP = {
    "run_seconds": "Executor wall seconds per dispatched run request.",
    "queue_seconds": "Seconds a run request waited in the admission "
                     "queue before dispatch.",
}

# run-header chaos keys a --chaos daemon accepts (tools/chaos.py
# ChaosInjector constructor surface; test machinery, never production)
_CHAOS_KEYS = frozenset({"seed", "nan_field", "nan_iteration",
                         "nan_member", "fail_checkpoint_write",
                         "sigterm_iteration", "hang_iteration",
                         "hang_sec"})


@contextlib.contextmanager
def _socket_deadline(conn, timeout, how):
    """ABSOLUTE time bound on a socket read or write phase. Per-op
    socket timeouts reset whenever any bytes (or buffer space) move, so
    a byte-dripping slow-loris — on either side — never trips them; this
    timer tears the affected half down (`how`: SHUT_RD leaves the write
    half usable for a structured error reply; SHUT_RDWR for reply
    writes), turning the stalled call into an OSError the caller's
    error path absorbs. Yields a list that is non-empty iff the
    deadline fired (the read path words its error with it)."""
    expired = []

    def _expire():
        expired.append(True)
        try:
            conn.shutdown(how)
        except OSError:
            pass

    timer = threading.Timer(timeout, _expire)
    timer.daemon = True
    timer.start()
    try:
        yield expired
    finally:
        timer.cancel()


class SolverService:

    def __init__(self, host="127.0.0.1", port=0, pool_size=None, sink=None,
                 allow_imports=False, drain_grace=600.0, queue_depth=None,
                 idle_timeout=None, watchdog_sec=None, breaker_failures=None,
                 breaker_cooloff=None, result_cache=None,
                 mem_watermark_mb=None, on_client_drop=None,
                 chaos_enabled=False, batching_enabled=None,
                 batch_max=None, batch_window=None, batch_block=None,
                 trace_file=None, metrics_port=None):
        self.host = host
        self.port = int(port)
        self.pool = SolverPool(size=pool_size, allow_imports=allow_imports)
        self.sink = str(sink) if sink else None
        self.drain_grace = float(drain_grace)
        # ---- fault-tolerance knobs (None pulls the [service] default)
        self.queue_depth = max(int(
            queue_depth if queue_depth is not None
            else cfg_get("service", "QUEUE_DEPTH", "8")), 1)
        self.idle_timeout = float(
            idle_timeout if idle_timeout is not None
            else cfg_get("service", "IDLE_TIMEOUT_SEC", "60"))
        self.watchdog_sec = float(
            watchdog_sec if watchdog_sec is not None
            else cfg_get("service", "WATCHDOG_SEC", "300"))
        self.on_client_drop = str(
            on_client_drop if on_client_drop is not None
            else cfg_get("service", "ON_CLIENT_DROP", "complete")).lower()
        if self.on_client_drop not in ("complete", "abort"):
            raise ValueError(f"ON_CLIENT_DROP must be 'complete' or "
                             f"'abort', got {self.on_client_drop!r}")
        self.mem_watermark_bytes = int(float(
            mem_watermark_mb if mem_watermark_mb is not None
            else cfg_get("service", "MEM_WATERMARK_MB", "0")) * 2**20)
        self.breaker = faults.CircuitBreaker(
            failures=int(breaker_failures if breaker_failures is not None
                         else cfg_get("service", "BREAKER_FAILURES", "3")),
            cooloff_sec=float(
                breaker_cooloff if breaker_cooloff is not None
                else cfg_get("service", "BREAKER_COOLOFF_SEC", "30")))
        self.results = faults.ResultCache(
            size=int(result_cache if result_cache is not None
                     else cfg_get("service", "RESULT_CACHE", "16")))
        self.chaos_enabled = bool(chaos_enabled)
        # ---- continuous batching (service/batching.py): opt-in — the
        # solo executor path stays the default dispatch mode
        if batching_enabled is None:
            batching_enabled = str(cfg_get(
                "service", "BATCH", "False")).strip().lower() in (
                    "1", "true", "yes", "on")
        self.batcher = batching.BatchDispatcher(
            self, batch_max=batch_max, batch_window=batch_window,
            batch_block=batch_block) if batching_enabled else None
        # ---- end-to-end request tracing (tools/tracing.py): opt-in;
        # when enabled each run request gets one trace (accept ->
        # admission -> queue -> pool acquire -> batch/run -> result
        # send), flushed as a `kind: trace` record to the trace sink
        # (--trace FILE, falling back to the telemetry sink)
        if trace_file is not None:
            tracing.enable(sink=str(trace_file) if trace_file else None)
        # ---- request accounting
        self.requests_served = 0
        self.errors = 0
        self.shed = 0
        self.deadline_exceeded = 0
        self.watchdog_fires = 0
        self.client_drops = 0
        self.mem_evictions = 0
        # per-error-code counters ({code: count}): the error MIX —
        # bad-spec vs deadline-exceeded vs circuit-open vs overloaded —
        # that the aggregate `errors` total cannot show
        self.error_codes = {}
        self._request_seq = 0     # default-id counter: EVERY run request
                                  # advances it (success or not), so ids
                                  # in the telemetry sink never collide
        # counters are bumped from reader threads, workers, the watchdog,
        # and the drain sweep concurrently; unguarded `+= 1` loses counts
        self._counters_lock = named_lock(
            "service/server.py:SolverService._counters_lock")
        # latency histograms behind the Prometheus surface (service/
        # promexport.py): fed under _counters_lock, snapshotted by
        # prom_text() so a scrape never reads a half-updated bucket map
        self.hists = {
            "run_seconds": tracing.LogHistogram(),
            "queue_seconds": tracing.LogHistogram(),
        }
        # /metrics listener port: None pulls [service] METRICS_PORT,
        # where 0 means disabled; an EXPLICIT 0 binds an ephemeral port
        # (tests read the bound port back off `metrics_port` after start)
        if metrics_port is None:
            configured = int(float(cfg_get("service", "METRICS_PORT",
                                           "0")))
            self.metrics_port = configured if configured > 0 else None
        else:
            self.metrics_port = (int(metrics_port)
                                 if int(metrics_port) >= 0 else None)
        self._metrics_server = None
        self.started_ts = None
        # the queue object is unbounded; admission is bounded by the
        # _queued_runs counter so the drain sentinel can never block on
        # a full queue behind a wedged executor
        self._queue = queue.Queue()
        self._queued_runs = 0
        self._avg_run_sec = None      # EWMA of per-request executor wall
        self._draining = None
        self._active_run = None       # faults.RunContext while executing
        self._active_lock = named_lock(
            "service/server.py:SolverService._active_lock")
        self._worker_gen = 0          # bumped when the watchdog replaces
                                      # a wedged executor thread
        self._worker_thread = None
        self._watchdog = faults.Watchdog(
            self._get_active_run, self._watchdog_fire, self.watchdog_sec)
        self._sock = None

    # ---------------------------------------------------------- lifecycle

    def request_drain(self, why):
        """Begin a graceful drain (signal handler, `shutdown` request, or
        tests): refuse new work and cooperatively stop the in-flight run
        so it checkpoints before the daemon exits."""
        if self._draining is None:
            self._draining = str(why)
            logger.warning(f"service: draining ({why}) — in-flight run "
                           "will checkpoint and stop")
        with self._active_lock:
            ctx = self._active_run
        if ctx is not None and ctx.loop is not None:
            ctx.loop.request_stop(str(why))

    def _handle_signal(self, signum, frame):
        self.request_drain(signal.Signals(signum).name)

    def _start_worker(self):
        """Start a (replacement) executor thread. The generation stamp
        lets a watchdog-abandoned worker notice it was declared dead and
        exit after its current run instead of racing the replacement for
        queue items."""
        self._worker_gen += 1
        gen = self._worker_gen
        thread = threading.Thread(target=self._worker, args=(gen,),
                                  name=f"service-worker-{gen}", daemon=True)
        self._worker_thread = thread
        thread.start()
        return thread

    def serve_forever(self, ready_stream=None):
        """Bind, announce readiness, and serve until drained. Prints ONE
        JSON line {"kind": "ready", "port": N, "pid": ...} to
        `ready_stream` (default stdout) once accepting — the handshake
        benchmark/test drivers wait on."""
        previous = {}
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[signum] = signal.signal(signum, self._handle_signal)
            except (ValueError, OSError):
                pass   # non-main thread (in-process tests): drain via
                       # request_drain/shutdown only
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._sock.settimeout(0.2)
        self.started_ts = time.time()
        self._start_worker()
        self._watchdog.start()
        self._start_metrics_server()
        import os
        banner = {"kind": "ready", "port": self.port, "pid": os.getpid(),
                  "pool_size": self.pool.size}
        stream = ready_stream if ready_stream is not None else sys.stdout
        print(json.dumps(banner), file=stream, flush=True)
        logger.info(f"service: listening on {self.host}:{self.port} "
                    f"(pool size {self.pool.size}, queue depth "
                    f"{self.queue_depth})")
        try:
            while self._draining is None:
                try:
                    conn, _addr = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._receive,
                                 args=(conn, time.perf_counter()),
                                 daemon=True).start()
        finally:
            self._sock.close()
            self._watchdog.stop()
            self._stop_metrics_server()
            self._queue.put(None)           # worker stop sentinel
            worker = self._worker_thread
            if worker is not None:
                worker.join(timeout=self.drain_grace)
                if worker.is_alive():
                    logger.error("service: worker did not drain within "
                                 f"{self.drain_grace}s; exiting anyway")
            self._refuse_queued()
            self._flush_stats()
            for signum, handler in previous.items():
                try:
                    signal.signal(signum, handler)
                except (ValueError, OSError):
                    pass
        logger.info(f"service: stopped ({self._draining})")

    def _flush_stats(self):
        """One `service_stats` record to the sink (and the log) at drain:
        pool hit/miss/eviction counters + request/fault totals, so the
        serving trajectory is machine-recorded like every other
        subsystem."""
        record = dict(self.stats(), kind="service_stats",
                      ts=round(time.time(), 1))
        self._emit(record)
        logger.info(f"service: final stats {json.dumps(record)}")

    def _emit(self, record):
        """Append one record to the telemetry sink (no-op when sinkless)."""
        if self.sink:
            metrics_mod.Metrics(sink=self.sink, enabled=True).emit(record)

    def prom_text(self):
        """The daemon's stats surface as Prometheus text exposition
        0.0.4 (service/promexport.py): counters, occupancy gauges,
        per-error-code counters, and the run/queue latency LogHistograms
        as native Prometheus histograms. Served by the `stats` frame
        with `prom: true` and by GET /metrics on the [service]
        METRICS_PORT listener."""
        from . import promexport
        with self._counters_lock:
            hists = {
                name: ({"counts": dict(h.counts), "total": h.total,
                        "sum": h.sum}, _HIST_HELP[name])
                for name, h in self.hists.items()
            }
        return promexport.render_stats(self.stats(), hists)

    def _start_metrics_server(self):
        """Bind the plaintext GET /metrics listener when configured
        (`[service] METRICS_PORT` > 0, `--metrics-port`, or an explicit
        ephemeral 0 from tests). Serves scrapes on daemon threads so a
        slow scraper can never wedge the request loop; everything else
        404s."""
        if self.metrics_port is None:
            return
        import http.server
        service = self

        class MetricsHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?")[0].rstrip("/") not in (
                        "", "/metrics"):
                    self.send_error(404)
                    return
                body = service.prom_text().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass      # scrapes every few seconds would flood the log

        server = http.server.ThreadingHTTPServer(
            (self.host, self.metrics_port), MetricsHandler)
        server.daemon_threads = True
        self.metrics_port = server.server_address[1]
        self._metrics_server = server
        threading.Thread(target=server.serve_forever,
                         name="service-metrics", daemon=True).start()
        logger.info(f"service: /metrics listening on "
                    f"{self.host}:{self.metrics_port}")

    def _stop_metrics_server(self):
        server, self._metrics_server = self._metrics_server, None
        if server is not None:
            server.shutdown()
            server.server_close()

    def stats(self):
        # counter snapshot under the lock: these are bumped from reader
        # threads, the executor, the watchdog, and the drain sweep, so a
        # lock-free read here can see a torn mix of mid-update values.
        # The pool/batcher/breaker/cache blocks below take their OWN
        # locks and are deliberately called OUTSIDE this one — the
        # service never nests lock acquisitions (threadcheck DTC003
        # keeps the acquisition-order graph edge-free).
        with self._counters_lock:
            counters = {
                "requests_served": self.requests_served,
                "errors": self.errors,
                "queued": self._queued_runs,
                "shed": self.shed,
                "deadline_exceeded": self.deadline_exceeded,
                "watchdog_fires": self.watchdog_fires,
                "client_drops": self.client_drops,
                "mem_evictions": self.mem_evictions,
                "error_codes": dict(self.error_codes),
            }
        return {
            "requests_served": counters["requests_served"],
            "errors": counters["errors"],
            "draining": self._draining,
            "uptime_sec": round(time.time() - self.started_ts, 1)
            if self.started_ts else 0.0,
            "pool": self.pool.stats(),
            # per-batch occupancy (members seated / joined / detached by
            # cause, per-block peaks) — executor-level counters alone
            # cannot show how full the fleets ran
            "serving": {
                "batching": (self.batcher.stats() if self.batcher
                             else {"enabled": False}),
            },
            "faults": {
                "queue_depth": self.queue_depth,
                "queued": counters["queued"],
                "shed": counters["shed"],
                "deadline_exceeded": counters["deadline_exceeded"],
                "watchdog_fires": counters["watchdog_fires"],
                "client_drops": counters["client_drops"],
                "mem_evictions": counters["mem_evictions"],
                "replays": self.results.replays,
                "result_cache": len(self.results),
                "breaker": self.breaker.stats(),
                "error_codes": counters["error_codes"],
            },
        }

    # ----------------------------------------------------- reader threads

    def _receive(self, conn, t_accept):
        """Per-connection reader: parse the one request frame, answer
        control kinds inline (so `shutdown` can drain an in-flight run
        and `ping`/`stats` stay responsive during one), and admit runs
        for the executor — bounded queue, circuit-breaker fast-fail, and
        result-cache replay all happen here, before any solver work.
        Closes the connection itself on every path except a queued run
        (the worker owns that close). The connection read/write timeout
        ([service] IDLE_TIMEOUT_SEC) bounds slow-loris clients on both
        the request read and the result write."""
        enqueued = False
        try:
            conn.settimeout(self.idle_timeout)
            rfile = conn.makefile("rb")
            wfile = conn.makefile("wb")
            # absolute bounds on the request read (the per-recv socket
            # timeout cannot stop a byte-dripping slow loris); SHUT_RD
            # leaves the write half usable for the error reply. The
            # header line gets the flat bound; the payload budget scales
            # with its declared size so legitimate slow uploads of large
            # ICs are not refused while still bounding total time.
            expired = []
            try:
                with _socket_deadline(conn, self.idle_timeout,
                                      socket.SHUT_RD) as expired:
                    header = protocol.recv_header(rfile)
                payload = None
                if header is not None and header.get("payload_bytes", 0):
                    budget = self.idle_timeout \
                        + header["payload_bytes"] / MIN_TRANSFER_BYTES_PER_SEC
                    with _socket_deadline(conn, budget,
                                          socket.SHUT_RD) as expired:
                        payload = protocol.recv_payload(rfile, header)
            except (protocol.ProtocolError, OSError) as exc:
                self._count_error()
                why = (f"request not completed within its transfer "
                       f"budget: {exc}" if expired else str(exc))
                self._send_error(wfile, "bad-frame", why)
                return
            if header is None:
                return
            kind = header.get("kind")
            if kind == "ping":
                protocol.send_frame(wfile, {"kind": "pong"})
            elif kind == "stats":
                if header.get("prom"):
                    # Prometheus text exposition rides the payload slot
                    # (a raw byte body, not a JSON header field) so the
                    # header stays a clean one-line JSON frame
                    protocol.send_frame(
                        wfile, {"kind": "stats", "format": "prometheus"},
                        self.prom_text().encode("utf-8"))
                else:
                    protocol.send_frame(wfile, dict(self.stats(),
                                                    kind="stats"))
            elif kind == "shutdown":
                protocol.send_frame(wfile, {"kind": "ok",
                                            "draining": True})
                self.request_drain("shutdown request")
            elif kind == "run":
                # one trace per run request, opened on the reader thread;
                # accept = the request read we just finished. tctx is
                # None with tracing off and every consumer tolerates it.
                tctx = tracing.new_trace("request")
                if tctx is not None:
                    tracing.add_span("accept",
                                     time.perf_counter() - t_accept,
                                     parent=tctx)
                with tracing.resume(tctx):
                    with tracing.span("admission"):
                        enqueued = self._admit_run(conn, wfile, header,
                                                   payload, t_accept, tctx)
                if not enqueued:
                    self._finish_trace(tctx, outcome="refused")
            else:
                self._count_error()
                self._send_error(wfile, "unknown-kind",
                                 f"unknown request kind {kind!r}")
        except Exception:
            self._count_error()
            logger.exception("service: connection reader failed")
        finally:
            if not enqueued:
                try:
                    conn.close()
                except OSError:
                    pass

    def _finish_trace(self, tctx, **attrs):
        """Close a request trace's root span and flush the whole span
        tree as one `kind: trace` record to the trace sink (falling back
        to the telemetry sink). No-op for tctx=None (tracing off)."""
        if tctx is None:
            return
        tctx.finish(**attrs)
        # an explicit trace sink (--trace FILE / tracing.enable(sink))
        # wins; otherwise trace records ride the telemetry sink
        tracing.flush_trace(tctx.trace_id,
                            sink=tracing.trace_sink() or self.sink)

    def _admit_run(self, conn, wfile, header, payload, t_accept,
                   tctx=None):
        """Admission control for one run request (reader thread). Returns
        True when the request was enqueued (the worker then owns the
        connection). Order matters: replay first (a finished result is
        returned even under overload or an open circuit), then queue
        capacity, then the breaker — so a shed request never consumes
        the half-open probe slot."""
        if self._draining is not None:
            self._count_error()
            self._send_error(wfile, "draining",
                             f"daemon is draining ({self._draining})")
            return False
        client_id = header.get("id")
        if client_id is not None and self._send_replay(conn, wfile, header,
                                                       str(client_id)):
            return False
        # bounded admission: reserve a queue slot or shed
        with self._counters_lock:
            if self._queued_runs >= self.queue_depth:
                self.shed += 1
                self.errors += 1
                shed = True
            else:
                self._queued_runs += 1
                shed = False
        if shed:
            retry_after = self._retry_after()
            self._send_error(
                wfile, "overloaded",
                f"run queue is full ({self.queue_depth} deep); retry "
                f"in ~{retry_after}s",
                retry_after_sec=retry_after)
            return False
        digest = self._spec_digest(header)
        probe = False
        if digest is not None:
            allowed, retry_after, state = self.breaker.admit(digest)
            if not allowed:
                with self._counters_lock:
                    self._queued_runs -= 1
                    self.errors += 1
                self._send_error(
                    wfile, "circuit-open",
                    f"spec {digest[:12]} is cooling off after repeated "
                    f"failures; retry in ~{retry_after}s",
                    retry_after_sec=retry_after)
                return False
            probe = state == "probe"
        deadline_mono = None
        deadline = header.get("deadline_sec")
        if isinstance(deadline, (int, float)) and deadline > 0:
            deadline_mono = time.monotonic() + float(deadline)
        self._queue.put({"conn": conn, "wfile": wfile, "header": header,
                         "payload": payload, "t_accept": t_accept,
                         "deadline_mono": deadline_mono, "probe": probe,
                         "trace": tctx})
        return True

    @staticmethod
    def _spec_digest(header):
        """Spec digest for breaker accounting, or None when the spec is
        malformed (full validation — and the structured bad-spec reply —
        happens at the executor)."""
        try:
            return protocol.spec_digest(header.get("spec"))
        except Exception:
            return None

    @classmethod
    def _run_fingerprint(cls, header):
        """Identity of a run request for idempotent replay: the spec
        digest composed with every outcome-affecting run parameter. A
        retry with the same id but a different fingerprint must NOT be
        answered from the cache (it re-runs, and its completion
        overwrites the entry) — an id can never serve another request's
        result."""
        import hashlib
        blob = json.dumps(
            [cls._spec_digest(header), header.get("dt"),
             header.get("stop_iteration"), header.get("stop_sim_time"),
             header.get("layout", "c"), header.get("outputs"),
             header.get("deadline_sec"), header.get("resume"),
             bool(header.get("checkpoint"))],
            sort_keys=True, default=str)
        return hashlib.blake2b(blob.encode(), digest_size=12).hexdigest()

    def _send_replay(self, conn, wfile, header, client_id):
        """Serve a cached completed result for an idempotent retry.
        Returns True when the id hit the cache WITH a matching run
        fingerprint (frames sent, connection done) — the solve is NOT
        re-run; an id reused with a different spec/params is a miss and
        executes fresh. The replayed payload write gets the same
        ABSOLUTE slow-reader bound as the executor's reply phase
        (per-send timeouts reset on every freed buffer byte, and replay
        is served before admission — a byte-at-a-time reader must not
        pin reader threads and payloads unboundedly)."""
        cached = self.results.get(client_id,
                                  fingerprint=self._run_fingerprint(header))
        if cached is None:
            return False
        record, result, payload, _fingerprint = cached
        budget = self.idle_timeout + (len(payload) if payload else 0) \
            / MIN_TRANSFER_BYTES_PER_SEC
        with _socket_deadline(conn, budget, socket.SHUT_RDWR):
            try:
                protocol.send_frame(wfile, {
                    "kind": "ack", "id": client_id,
                    "pool_verdict": "replayed",
                    "queue_sec": 0.0, "build_sec": 0.0})
                if record is not None:
                    try:
                        protocol.send_frame(wfile, record)
                    except (TypeError, ValueError):
                        # a sinkless daemon never JSON-validated the
                        # record at flush time; skip it on replay exactly
                        # like the direct path does — the result frame
                        # must still go
                        logger.warning("service: cached telemetry record "
                                       "not JSON-serializable; skipped")
                protocol.send_frame(wfile, dict(result, replayed=True),
                                    payload=payload)
            except OSError:
                pass   # the retrying client vanished; cache entry stays
        logger.info(f"service: replayed cached result for request "
                    f"{client_id}")
        return True

    # ------------------------------------------------------------- worker

    def _worker(self, gen=None):
        if gen is None:
            gen = self._worker_gen
        # items a running batch popped at a boundary but could not seat
        # (different spec/dt, not batchable): processed FIRST, in order,
        # before new queue pops — deferral must not become starvation.
        # Deferred items keep their admission reservation (_queued_runs)
        # until handled here, so QUEUE_DEPTH keeps counting them.
        pending = collections.deque()
        while gen == self._worker_gen:
            if pending:
                item = pending.popleft()
            else:
                item = self._queue.get()
                if item is None:
                    return
            with self._counters_lock:
                self._queued_runs -= 1
            conn, wfile = item["conn"], item["wfile"]
            abandoned = False
            batch_owned = False
            try:
                if self._draining is not None:
                    # drain began while this run sat in the queue
                    self._count_error()
                    self._send_error(
                        wfile, "draining",
                        f"daemon is draining ({self._draining})")
                    self._finish_trace(item.get("trace"),
                                       outcome="draining")
                elif self.batcher is not None \
                        and not item.get("force_solo") \
                        and self.batcher.batchable(item["header"]):
                    # continuous batching: this item anchors a micro-
                    # batch; compatible queued/arriving requests join at
                    # block boundaries. The batcher owns every member
                    # connection (including this one).
                    batch_owned = True
                    pending.extend(self.batcher.run_batch(item))
                else:
                    self._handle_run(item)
            except faults.AbandonedRun:
                # the watchdog failed this run and is replacing this
                # worker; replies/requeues already happened there
                logger.warning("service: abandoned run unwound; stale "
                               "executor exiting")
                abandoned = True
            except Exception:
                self._count_error()
                logger.exception("service: connection handler failed")
            finally:
                if not batch_owned:
                    try:
                        conn.close()
                    except OSError:
                        pass
            if abandoned:
                # exit UNCONDITIONALLY, not via the generation check: the
                # fire sets ctx.abandoned BEFORE it bumps the generation,
                # so an unwinding worker can observe itself still
                # "current" — looping back here would leave TWO live
                # executors racing the queue (and wedge the drain
                # sentinel, which only one of them can consume). Work
                # this worker still held locally goes back on the queue
                # for the replacement — reservations still held, so no
                # re-increment.
                while pending:
                    self._queue.put(pending.popleft())
                return
        # generation mismatch: this worker was declared dead mid-run and
        # a replacement owns the queue now — exit without touching it

    def requeue_item(self, item):
        """Return an already-admitted run item to the queue (batch
        watchdog replay; deferred work orphaned by an abandoned
        executor): re-reserves its admission slot so the drain sweep and
        the stats stay consistent."""
        with self._counters_lock:
            self._queued_runs += 1
        self._queue.put(item)

    def _refuse_queued(self):
        """After the worker exits, answer any run a reader enqueued in
        the drain race window with a structured refusal."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is None:
                continue
            # same accounting as the worker-side drain refusal: release
            # the reserved queue slot and count the error, or the final
            # service_stats record claims phantom queued work
            with self._counters_lock:
                self._queued_runs -= 1
                self.errors += 1
            self._send_error(item["wfile"], "draining",
                             f"daemon is draining ({self._draining})")
            self._finish_trace(item.get("trace"), outcome="draining")
            try:
                item["conn"].close()
            except OSError:
                pass

    def _count_error(self):
        with self._counters_lock:
            self.errors += 1

    def _count(self, name, n=1):
        with self._counters_lock:
            setattr(self, name, getattr(self, name) + n)

    def _send_error(self, wfile, code, message, **extra):
        # every structured refusal counts by its code, so the final
        # service_stats record shows the error MIX, not just a total
        with self._counters_lock:
            self.error_codes[code] = self.error_codes.get(code, 0) + 1
        if tracing.enabled() and tracing.current_context() is not None:
            # zero-length marker span under the request's ambient trace
            tracing.add_span("error", 0.0, attrs={"code": code})
        try:
            frame = {"kind": "error", "code": code, "message": message}
            frame.update(extra)
            protocol.send_frame(wfile, frame)
        except OSError:
            pass   # client gone; nothing to tell it

    # ----------------------------------------------------------- watchdog

    def _get_active_run(self):
        with self._active_lock:
            return self._active_run

    def _watchdog_fire(self, ctx, stuck_sec):
        """The active run made no step progress within WATCHDOG_SEC: fail
        it with a postmortem and replace the wedged executor. Runs on the
        watchdog thread — the executor is hung by premise, so writing the
        error frame from here cannot interleave with a healthy stream
        (the pathological case, a hang INSIDE a partial frame write,
        degrades to a protocol error on the client, never a wrong
        result)."""
        with self._active_lock:
            if self._active_run is not ctx:
                # the run finished between the watchdog's poll and this
                # fire: it was never hung — leave the reply alone
                return
            self._active_run = None
        if getattr(ctx, "is_batch", False):
            # a wedged BATCH: member requests are the unit of replay —
            # the dispatcher abandons the batch, quarantines the pool
            # entry (and its fleet), and requeues every surviving
            # member's request for the replacement executor. The
            # replacement starts UNCONDITIONALLY: a fire that blows up
            # mid-bookkeeping must never leave the daemon executor-less
            # (the stale worker exits on AbandonedRun either way).
            try:
                self.batcher.on_watchdog(ctx, stuck_sec)
            except Exception:
                logger.exception("service: batch watchdog fire failed")
            finally:
                self._start_worker()
            return
        # abandon FIRST: a slow-but-alive executor must stop writing to
        # this socket (its next step hook raises AbandonedRun) before we
        # put the structured error frame on it
        ctx.abandoned.set()
        self._count("watchdog_fires")
        self._count_error()
        iteration = None
        if ctx.loop is not None:
            try:
                iteration = int(ctx.loop.solver.iteration)
            except Exception:
                pass
        record = {
            "kind": "watchdog_postmortem",
            "request_id": ctx.request_id,
            "stuck_sec": round(stuck_sec, 3),
            "watchdog_sec": self.watchdog_sec,
            "request_age_sec": round(time.monotonic() - ctx.started_ts, 3),
            "iteration": iteration,
            "stacks": faults.thread_stacks(),
            # which service locks each thread holds / waits on, when the
            # runtime lock-order sanitizer is enabled ({} when off)
            "held_locks": faults.held_locks(),
        }
        logger.error(
            f"service: WATCHDOG — request {ctx.request_id} made no step "
            f"progress for {stuck_sec:.1f}s (> {self.watchdog_sec}s); "
            "failing it with a postmortem and replacing the executor")
        self._emit(record)
        if ctx.digest is not None:
            if ctx.client_gone:
                # the stall followed a known-dead client (same
                # attribution rule as the ack/drop paths: a dropped
                # connection says nothing about the SPEC) — release any
                # probe slot instead of blaming the circuit
                self.breaker.abandon_probe(ctx.digest)
            else:
                self.breaker.record_failure(ctx.digest)
            # quarantine the pool entry BEFORE the replacement executor
            # starts: the stale executor may still be inside a dispatch
            # on this solver, and a pool hit by the replacement would
            # share (and race) the very instance that is wedged — a
            # spurious fire on a slow-but-alive step would then serve
            # corrupted state as a healthy result
            self.pool.discard(ctx.digest)
        # replace the executor BEFORE the client-visible error write:
        # the write below can block up to the socket deadline, and the
        # daemon must not sit executor-less for that window. The order
        # is also an observability contract — once a client holds the
        # watchdog-timeout error, the replacement generation is visible
        # (stats/_worker_gen), so "error received then state inspected"
        # can never race the bookkeeping.
        self._start_worker()
        # the error write shares ctx.wfile's buffered-writer lock with
        # the (possibly mid-send) wedged executor: if the stall IS a
        # blocked send to a byte-dripping client, writing here would
        # deadlock the watchdog on that lock. The bounded deadline tears
        # the socket down in that case — unblocking BOTH writers — and
        # in the ordinary hung-dispatch case (wfile idle) the structured
        # error goes out normally.
        with _socket_deadline(ctx.conn, min(self.idle_timeout, 10.0),
                              socket.SHUT_RDWR):
            self._send_error(
                ctx.wfile, "watchdog-timeout",
                f"no step progress within {self.watchdog_sec}s "
                f"(request {ctx.request_id}); postmortem recorded")
        try:
            ctx.conn.close()
        except OSError:
            pass

    # ---------------------------------------------------------------- run

    @staticmethod
    def _run_params(header):
        """Validate the run request's parameters (everything outside the
        spec). Raises SpecError with a message naming the field."""
        dt = header.get("dt")
        if not isinstance(dt, (int, float)) or not np.isfinite(dt) or dt <= 0:
            raise protocol.SpecError(f"run: dt must be a positive finite "
                                     f"number, got {dt!r}")
        stop_iteration = header.get("stop_iteration")
        stop_sim_time = header.get("stop_sim_time")
        if stop_iteration is None and stop_sim_time is None:
            raise protocol.SpecError(
                "run: one of stop_iteration / stop_sim_time is required")
        if stop_iteration is not None and (
                not isinstance(stop_iteration, int) or stop_iteration < 1):
            raise protocol.SpecError(
                f"run: stop_iteration must be a positive integer, got "
                f"{stop_iteration!r}")
        if stop_sim_time is not None and (
                not isinstance(stop_sim_time, (int, float))
                or not np.isfinite(stop_sim_time) or stop_sim_time <= 0):
            raise protocol.SpecError(
                f"run: stop_sim_time must be positive and finite, got "
                f"{stop_sim_time!r}")
        layout = header.get("layout", "c")
        if layout not in ("c", "g"):
            raise protocol.SpecError(f"run: layout must be 'c' or 'g', "
                                     f"got {layout!r}")
        outputs = header.get("outputs")
        if outputs is not None and (
                not isinstance(outputs, list)
                or not all(isinstance(n, str) for n in outputs)):
            raise protocol.SpecError("run: outputs must be a list of "
                                     "field names")
        checkpoint = header.get("checkpoint")
        if checkpoint is not None:
            if not (isinstance(checkpoint, dict) and checkpoint.get("dir")):
                raise protocol.SpecError(
                    "run: checkpoint must be {'dir': path, 'iter': N?}")
            ckpt_iter = checkpoint.get("iter") or 0
            if not isinstance(ckpt_iter, int) or ckpt_iter < 0:
                raise protocol.SpecError(
                    f"run: checkpoint iter must be a non-negative "
                    f"integer, got {checkpoint.get('iter')!r}")
            checkpoint = {"dir": str(checkpoint["dir"]), "iter": ckpt_iter}
        progress_every = header.get("progress_every") or 0
        if not isinstance(progress_every, int) or progress_every < 0:
            raise protocol.SpecError(
                f"run: progress_every must be a non-negative integer, "
                f"got {header.get('progress_every')!r}")
        deadline = header.get("deadline_sec")
        if deadline is not None and (
                not isinstance(deadline, (int, float))
                or not np.isfinite(deadline) or deadline <= 0):
            raise protocol.SpecError(
                f"run: deadline_sec must be a positive finite number, "
                f"got {deadline!r}")
        return {
            "dt": float(dt),
            "stop_iteration": stop_iteration,
            "stop_sim_time": stop_sim_time,
            "layout": layout,
            "outputs": outputs,
            "checkpoint": checkpoint,
            "resume": bool(header.get("resume")),
            "progress_every": progress_every,
            "deadline_sec": float(deadline) if deadline is not None
            else None,
        }

    def _build_chaos(self, header):
        """Construct a per-run ChaosInjector from the request header —
        ONLY on a daemon started with --chaos (test machinery: the chaos
        suite drives daemon-side faults deterministically)."""
        spec = header.get("chaos")
        if spec is None:
            return None
        if not self.chaos_enabled:
            raise protocol.SpecError(
                "run: chaos injection is disabled on this daemon "
                "(start it with --chaos; test deployments only)")
        if not isinstance(spec, dict):
            raise protocol.SpecError("run: chaos must be a JSON object")
        unknown = sorted(set(spec) - _CHAOS_KEYS)
        if unknown:
            raise protocol.SpecError(
                f"run: unknown chaos key(s) {unknown} "
                f"(known: {sorted(_CHAOS_KEYS)})")
        from ..tools.chaos import ChaosInjector
        try:
            injector = ChaosInjector(**spec)
            # pre-coerce the lazily-used numeric knobs so a bad value is
            # a structured bad-spec now, not a mid-run executor blowup
            if injector.hang_sec is not None:
                injector.hang_sec = float(injector.hang_sec)
            if injector.hang_iteration is not None:
                injector.hang_iteration = int(injector.hang_iteration)
            if injector.nan_iteration is not None:
                injector.nan_iteration = int(injector.nan_iteration)
            if injector.sigterm_iteration is not None:
                injector.sigterm_iteration = int(injector.sigterm_iteration)
            return injector
        except (TypeError, ValueError) as exc:
            raise protocol.SpecError(f"run: bad chaos block: {exc}")

    @staticmethod
    def _fields_by_name(solver):
        """Addressable fields of one solver: state variables plus the
        RHS-parameter (extra) fields — both settable as initial
        conditions and returnable as outputs."""
        by_name = {}
        for var in solver.state:
            by_name[var.name] = var
        for field in solver.eval_F.extra_fields:
            by_name.setdefault(field.name, field)
        return by_name

    @classmethod
    def _install_ics(cls, solver, ics):
        """Apply the request's field payload onto the (reset) solver.
        Targets state variables and RHS-parameter (extra) fields by name;
        unknown names are a spec error BEFORE any stepping."""
        by_name = cls._fields_by_name(solver)
        for name, (layout, array) in ics.items():
            field = by_name.get(name)
            if field is None:
                raise protocol.SpecError(
                    f"run: unknown field {name!r} in initial conditions "
                    f"(known: {sorted(k for k in by_name if k)})")
            try:
                field[layout] = array
            except (ValueError, TypeError) as exc:
                raise protocol.SpecError(
                    f"run: initial condition for {name!r} rejected: {exc}")

    @classmethod
    def _output_fields(cls, solver, names):
        """Resolve the requested output field list (None: all state
        variables). Unknown names are a spec error — a typo'd output must
        fail loudly before stepping, not return an empty payload."""
        if names is None:
            return list(solver.state)
        by_name = cls._fields_by_name(solver)
        unknown = [n for n in names if n not in by_name]
        if unknown:
            raise protocol.SpecError(
                f"run: unknown output field(s) {unknown} "
                f"(known: {sorted(k for k in by_name if k)})")
        return [by_name[n] for n in names]

    def _retry_after(self):
        """Load-shed hint: roughly how long until a queue slot drains,
        from the per-request executor-wall EWMA. The reservation count
        is read under its lock (reader threads call this while the
        executor and drain sweep mutate it); _avg_run_sec is the
        executor-only EWMA — a single-word float read is GIL-atomic, so
        it stays lock-free by design (threadcheck catalog exclusion)."""
        with self._counters_lock:
            queued = self._queued_runs
        base = self._avg_run_sec if self._avg_run_sec else 1.0
        return round(min(max(base * (queued + 1), 1.0), 600.0), 1)

    def _observe_run_wall(self, t_dispatch):
        wall = time.perf_counter() - t_dispatch
        if self._avg_run_sec is None:
            self._avg_run_sec = wall
        else:
            self._avg_run_sec = 0.7 * self._avg_run_sec + 0.3 * wall
        with self._counters_lock:
            self.hists["run_seconds"].add(wall)

    def _shed_memory(self):
        """Process-RSS watermark: above [service] MEM_WATERMARK_MB, evict
        warm pool entries down to one BEFORE the next build can OOM the
        daemon (each entry pins matrices + factorizations + compiled
        programs)."""
        if not self.mem_watermark_bytes:
            return
        rss = metrics_mod.process_rss_bytes()
        if not rss or rss <= self.mem_watermark_bytes:
            return
        if len(self.pool) <= 1 and not len(self.results):
            return
        # both warm tiers are shed: pool entries pin matrices + compiled
        # programs, cached results pin whole npz payloads — either can
        # dominate RSS, and the daemon staying alive outranks both
        evicted = self.pool.trim(keep=1)
        dropped = self.results.clear()
        if evicted or dropped:
            self._count("mem_evictions", evicted)
            logger.warning(
                f"service: RSS {rss / 2**20:.0f} MiB over the "
                f"{self.mem_watermark_bytes / 2**20:.0f} MiB watermark; "
                f"evicted {evicted} warm pool entr(ies), dropped "
                f"{dropped} cached result(s)")

    def _handle_run(self, item):
        """Solo-path dispatch wrapper: stamps the request's queue-wait
        span, resumes its trace on the executor thread (so build/run/
        phase spans parent correctly), and guarantees the trace is
        finished + flushed on every exit path — including AbandonedRun
        unwinds."""
        tctx = item.get("trace")
        if tctx is None:
            return self._dispatch_run(item)
        tracing.add_span("queue", time.perf_counter() - item["t_accept"],
                         parent=tctx)
        try:
            with tracing.resume(tctx):
                return self._dispatch_run(item)
        finally:
            self._finish_trace(tctx)

    def _dispatch_run(self, item):
        from ..tools.resilience import ResilientLoop
        from ..tools.exceptions import SolverHealthError
        import jax
        header, payload = item["header"], item["payload"]
        wfile, conn = item["wfile"], item["conn"]
        t_dispatch = time.perf_counter()
        queue_sec = t_dispatch - item["t_accept"]
        # locked: after a watchdog fire a stale executor can briefly
        # overlap the replacement, and colliding default ids would break
        # the never-collide invariant the telemetry sink relies on
        with self._counters_lock:
            self._request_seq += 1
            seq = self._request_seq
            self.hists["queue_seconds"].add(queue_sec)
        client_id = header.get("id")
        request_id = str(client_id or f"r{seq}")
        tctx = item.get("trace")
        if tctx is not None:
            tctx.attrs.setdefault("request_id", request_id)
        # NOTE: the replay -> params -> breaker -> deadline sequence
        # below is mirrored by service/batching.BatchDispatcher.
        # _admit_member for batched members; a change to the ordering or
        # the bookkeeping here must be applied there too.
        # replay re-check: the original of an idempotent retry may have
        # completed while the retry sat in the queue
        if client_id is not None and self._send_replay(conn, wfile, header,
                                                       str(client_id)):
            if item.get("probe"):
                # this request was admitted as the half-open probe but
                # resolved without running: free the slot or the circuit
                # could never close
                replay_digest = self._spec_digest(header)
                if replay_digest is not None:
                    self.breaker.abandon_probe(replay_digest)
            return
        probe = item.get("probe", False)
        digest = None
        try:
            spec = protocol.normalize_spec(header.get("spec"))
            digest = protocol.spec_digest(spec)
            params = self._run_params(header)
            chaos = self._build_chaos(header)
        except protocol.SpecError as exc:
            self._count_error()
            self._send_error(wfile, "bad-spec", str(exc))
            if probe and digest is not None:
                self.breaker.abandon_probe(digest)
            return
        if not probe:
            # the circuit may have opened (or half-opened) while this
            # request sat in the queue
            allowed, retry_after, state = self.breaker.admit(digest)
            if not allowed:
                self._count_error()
                self._send_error(
                    wfile, "circuit-open",
                    f"spec {digest[:12]} is cooling off after repeated "
                    f"failures; retry in ~{retry_after}s",
                    retry_after_sec=retry_after)
                return
            probe = state == "probe"
        deadline_mono = item.get("deadline_mono")
        if deadline_mono is not None and time.monotonic() >= deadline_mono:
            self._count("deadline_exceeded")
            self._count_error()
            self._send_error(
                wfile, "deadline-exceeded",
                f"run: deadline_sec={params['deadline_sec']} elapsed "
                f"while queued ({queue_sec:.2f}s in queue)")
            if probe:
                self.breaker.abandon_probe(digest)
            return
        self._shed_memory()
        # the active-run context is registered BEFORE the build so the
        # watchdog also covers a hung build/compile (WATCHDOG_SEC must
        # exceed the worst-case cold start — docs/serving.md)
        ctx = faults.RunContext(request_id, digest, conn, wfile, None,
                                deadline_ts=deadline_mono, probe=probe,
                                header=header, trace=tctx)
        with self._active_lock:
            self._active_run = ctx
        try:
            self._execute_run(ctx, spec, params, payload, chaos,
                              t_dispatch, queue_sec, client_id,
                              ResilientLoop, SolverHealthError, jax)
        finally:
            with self._active_lock:
                if self._active_run is ctx:
                    self._active_run = None

    def _execute_run(self, ctx, spec, params, payload, chaos, t_dispatch,
                     queue_sec, client_id, ResilientLoop,
                     SolverHealthError, jax):
        wfile = ctx.wfile
        request_id, digest, probe = ctx.request_id, ctx.digest, ctx.probe
        try:
            ics = protocol.decode_fields(payload) if payload else {}
            with tracing.span("pool_acquire") as acq:
                # a cold build inside acquire() emits its own
                # `build/<phase>` child spans (metrics.BuildPhases)
                entry, verdict, build_sec = self.pool.acquire(spec)
                acq.set(verdict=verdict, build_sec=round(build_sec, 4))
            if ctx.abandoned.is_set():
                # the watchdog fired during OUR build: its quarantine ran
                # before this build finished and re-inserted the entry,
                # so drop it again — the replacement executor must never
                # share a solver this (stale) thread has touched
                self.pool.discard(digest)
                raise faults.AbandonedRun(request_id)
            solver = entry.solver
            self._install_ics(solver, ics)
            targets = self._output_fields(solver, params["outputs"])
        except faults.AbandonedRun:
            raise
        except protocol.SpecError as exc:
            self._count_error()
            self._send_error(wfile, "bad-spec", str(exc))
            if probe:
                self.breaker.abandon_probe(digest)
            return
        except Exception as exc:
            if ctx.abandoned.is_set():
                # the watchdog fired during this build and already judged
                # the request (breaker failure recorded, client answered,
                # connection closed): a second count or a reply on the
                # dead socket would double-book the one wedged request
                raise faults.AbandonedRun(request_id)
            # a builder blowing up on technically-valid params (resolution
            # the basis rejects, singular operator, ...) must reply
            # structurally, not drop the connection — and it counts
            # against the spec's circuit
            self._count_error()
            logger.exception(f"service: build for request {request_id} "
                             "failed")
            self.breaker.record_failure(digest)
            self._send_error(wfile, "build-failed",
                             f"{type(exc).__name__}: {exc}")
            return
        if ctx.abandoned.is_set():
            raise faults.AbandonedRun(request_id)
        if params["stop_iteration"] is not None:
            solver.stop_iteration = params["stop_iteration"]
        if params["stop_sim_time"] is not None:
            solver.stop_sim_time = params["stop_sim_time"]
        solver.metrics.sink = self.sink
        solver.metrics.meta["config"] = f"{protocol.spec_name(spec)}_served"
        tctx = ctx.trace
        if tctx is not None and hasattr(solver, "plan_provenance"):
            # the resolved plan rides the trace root, so an exported
            # span tree names the plan that produced its latencies
            tctx.attrs.update(plan=solver.plan_provenance(),
                              pool_verdict=verdict,
                              pool_key=str(entry.key)[:16])
        try:
            protocol.send_frame(wfile, {
                "kind": "ack", "id": request_id, "pool_verdict": verdict,
                "queue_sec": round(queue_sec, 6),
                "build_sec": round(build_sec, 4)})
        except OSError:
            # the client died before its ack: nothing to serve. Says
            # nothing about the SPEC, so a half-open probe slot must be
            # released, not judged — otherwise the circuit never closes
            self._count("client_drops")
            if probe:
                self.breaker.abandon_probe(digest)
            logger.warning(f"service: client for {request_id} vanished "
                           "before the ack; run skipped")
            return

        ttfs = [None]
        progress_every = params["progress_every"]
        progress_next = [progress_every]

        def step_hook(s):
            # ctx.loop is assigned before loop.run() and the hook only
            # fires inside it, so the reference is always live here
            if ctx.abandoned.is_set():
                raise faults.AbandonedRun(request_id)
            ctx.last_progress = time.monotonic()
            # first completed step: block so time-to-first-step covers the
            # device tail (and, on a miss, the build + compile it followed)
            if ttfs[0] is None:
                jax.block_until_ready(s.X)
                ttfs[0] = time.perf_counter() - t_dispatch
            if ctx.deadline_ts is not None and not ctx.deadline_fired \
                    and time.monotonic() >= ctx.deadline_ts:
                ctx.deadline_fired = True
                self._count("deadline_exceeded")
                logger.warning(
                    f"service: request {request_id} exceeded its "
                    f"{params['deadline_sec']}s deadline at iteration "
                    f"{int(s.iteration)}; stopping gracefully")
                ctx.loop.request_stop("deadline-exceeded")
            if progress_every and s.iteration >= progress_next[0]:
                progress_next[0] = s.iteration + progress_every
                # no per-send deadline timer here (a Timer thread per
                # progress frame would tax the hot loop): a send stalled
                # by a byte-dripping client freezes last_progress, so
                # the WATCHDOG reaps it like any other executor stall.
                # The absolute _socket_deadline timers guard only the
                # phases outside watchdog coverage (reader-thread request
                # reads, the post-run reply).
                try:
                    protocol.send_frame(wfile, {
                        "kind": "progress", "id": request_id,
                        "iteration": int(s.iteration),
                        "sim_time": float(s.sim_time)})
                except OSError:
                    self._client_dropped(ctx, ctx.loop)

        loop_kw = {}
        checkpoint = params["checkpoint"]
        if checkpoint is not None:
            loop_kw["checkpoint_dir"] = checkpoint["dir"]
            loop_kw["checkpoint_iter"] = checkpoint["iter"]
            loop_kw["resume"] = params["resume"]
        # the service owns this run's single telemetry flush (serving
        # fields stamped on it); the loop's own exit flush is suppressed
        loop = ResilientLoop(solver, dt=params["dt"], step_hook=step_hook,
                             install_signal_handlers=False,
                             flush_telemetry=False, chaos=chaos, **loop_kw)
        ctx.loop = loop
        if self._draining is not None:
            # drain began between queue pop and loop construction: stop at
            # the first boundary, still writing the final checkpoint
            loop.request_stop(self._draining)
        serving = {
            "queue_sec": round(queue_sec, 6),
            "pool_verdict": verdict,
            "time_to_first_step_sec": None,
            "build_sec": round(build_sec, 4),
            "request_id": request_id,
        }
        if params["deadline_sec"] is not None:
            serving["deadline_sec"] = params["deadline_sec"]
        if tctx is not None:
            # the key that joins this step record to its trace record
            serving["trace_id"] = tctx.trace_id
        try:
            try:
                with tracing.span("run"):
                    summary = loop.run(log_cadence=0)
            finally:
                # the solve is over (or failed): everything below is
                # reply-phase IO — telemetry flush, result encode, and a
                # possibly SLOW-READING client draining a large payload.
                # None of that is a hung dispatch, so the run must stop
                # being watchdog-eligible here, not after the reply.
                # (The graceful-stop final checkpoint runs INSIDE run()
                # and stays covered: a wedged checkpoint write really
                # does wedge the executor.)
                with self._active_lock:
                    if self._active_run is ctx:
                        self._active_run = None
        except SolverHealthError as exc:
            if ctx.abandoned.is_set():
                # the watchdog already judged, answered, and postmortemed
                # this request: a second breaker failure / error count /
                # telemetry flush would double-book the one wedged run
                raise faults.AbandonedRun(request_id)
            self._count_error()
            self.breaker.record_failure(digest)
            self._observe_run_wall(t_dispatch)
            serving["time_to_first_step_sec"] = ttfs[0]
            try:
                solver.flush_metrics(extra={"serving": serving})
            except Exception:
                pass
            self._send_error(
                wfile, "health",
                f"run halted unrecoverably: {getattr(exc, 'reason', exc)}")
            return
        except faults.AbandonedRun:
            raise
        except Exception as exc:
            if ctx.abandoned.is_set():
                raise faults.AbandonedRun(request_id)
            self._count_error()
            # counted against the circuit too: without a verdict a
            # half-open probe slot would stay consumed forever
            self.breaker.record_failure(digest)
            logger.exception(f"service: request {request_id} failed")
            self._send_error(wfile, "internal",
                             f"{type(exc).__name__}: {exc}")
            return
        if ctx.abandoned.is_set():
            # spurious watchdog fire on a run that then completed: the
            # client was already answered with watchdog-timeout and the
            # connection closed; nothing more to send
            raise faults.AbandonedRun(request_id)
        # breaker outcome: a client-drop abort says nothing about the
        # spec, so the probe slot is released instead of judged
        if ctx.client_gone and summary.get("stopped_by") == "client-drop":
            if probe:
                self.breaker.abandon_probe(digest)
        else:
            self.breaker.record_success(digest)
        self._observe_run_wall(t_dispatch)
        serving["time_to_first_step_sec"] = (round(ttfs[0], 6)
                                             if ttfs[0] is not None
                                             else None)
        record = None
        try:
            record = solver.flush_metrics(extra={"serving": serving})
        except Exception as exc:
            logger.warning(f"service: telemetry flush failed: {exc}")
        out_fields = {}
        for var in targets:
            if params["layout"] == "c":
                out_fields[var.name] = ("c", np.asarray(var.coeff_data()))
            else:
                out_fields[var.name] = ("g", np.array(var["g"]))
        result = {
            "kind": "result", "id": request_id,
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "stopped_by": summary.get("stopped_by"),
            "rewinds": summary.get("rewinds", 0),
            "serving": serving,
        }
        if summary.get("resumed_from"):
            result["resumed_from"] = summary["resumed_from"]
        result_payload = protocol.encode_fields(out_fields)
        # cache BEFORE sending: the idempotent retry exists precisely for
        # the client that vanishes between here and its result frame. A
        # client-drop ABORT is the one outcome that must NOT be cached —
        # replaying a deliberately truncated run to a retrying client
        # would dress a partial result up as the completed outcome (the
        # retry should re-execute instead)
        if client_id is not None \
                and summary.get("stopped_by") != "client-drop":
            self.results.put(str(client_id), record, result, result_payload,
                             fingerprint=self._run_fingerprint(ctx.header))
        # a client draining the result one byte at a time would hold the
        # single executor in sendall indefinitely — the write-side slow
        # loris; the absolute bound (scaled for the payload size, so a
        # slow-but-steady reader of a big result survives) turns the
        # stalled send into an OSError the client-drop path absorbs
        reply_budget = self.idle_timeout \
            + len(result_payload) / MIN_TRANSFER_BYTES_PER_SEC
        with tracing.span("result_send",
                          attrs={"payload_bytes": len(result_payload)}):
            with _socket_deadline(ctx.conn, reply_budget,
                                  socket.SHUT_RDWR):
                if record is not None:
                    try:
                        protocol.send_frame(wfile, record)
                    except (TypeError, ValueError):
                        logger.warning("service: telemetry record not "
                                       "JSON-serializable; skipped")
                    except OSError:
                        self._client_dropped(ctx, loop)
                try:
                    protocol.send_frame(wfile, result,
                                        payload=result_payload)
                except OSError:
                    self._client_dropped(ctx, loop)
                    logger.warning(f"service: client for {request_id} "
                                   "hung up before the result frame")
        self._count("requests_served")

    def _client_dropped(self, ctx, loop):
        """A send to the client failed mid-stream: the socket is dead.
        Counted ONCE per request; per [service] ON_CLIENT_DROP the run
        either completes (its result stays replayable from the cache) or
        aborts at the next step boundary through the resilient loop's
        stop-request path — the run's single telemetry flush happens on
        the normal exit path either way."""
        if ctx.client_gone:
            return
        ctx.client_gone = True
        self._count("client_drops")
        running = loop is not None and loop.stopped_by is None
        if not running:
            # detected in the reply phase: the solve already finished —
            # nothing to abort, and the completed result stays
            # replayable from the cache
            logger.warning(
                f"service: client for {ctx.request_id} disconnected "
                "during the reply; run already complete (result stays "
                "replayable)")
        elif self.on_client_drop == "abort":
            logger.warning(
                f"service: client for {ctx.request_id} disconnected "
                "mid-stream; aborting the run at the next step boundary "
                "(ON_CLIENT_DROP = abort)")
            loop.request_stop("client-drop")
        else:
            logger.warning(
                f"service: client for {ctx.request_id} disconnected "
                "mid-stream; completing the run "
                "(ON_CLIENT_DROP = complete)")


# --------------------------------------------------------------- CLI

def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu serve",
        description="Warm-pool solver daemon: LRU pool of live compiled "
                    "solvers served over a local socket (docs/serving.md).")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address (default: %(default)s)")
    parser.add_argument("--port", type=int, default=0,
                        help="TCP port; 0 picks an ephemeral port, "
                             "announced on stdout (default: %(default)s)")
    parser.add_argument("--pool-size", type=int, default=None,
                        help="warm solver entries kept (default: "
                             "[service] POOL_SIZE, else 4)")
    parser.add_argument("--sink", default=None,
                        help="JSONL telemetry sink for served records "
                             "(tools/metrics.py format)")
    parser.add_argument("--import-builders", action="store_true",
                        help="allow dotted module:function builder specs "
                             "(server-side imports; trusted clients only)")
    parser.add_argument("--drain-grace", type=float, default=600.0,
                        help="seconds to wait for the in-flight run at "
                             "drain (default: %(default)s)")
    parser.add_argument("--queue-depth", type=int, default=None,
                        help="bounded run-queue depth; excess requests get "
                             "a structured 'overloaded' refusal (default: "
                             "[service] QUEUE_DEPTH)")
    parser.add_argument("--idle-timeout", type=float, default=None,
                        help="per-connection read/write timeout in seconds "
                             "(default: [service] IDLE_TIMEOUT_SEC)")
    parser.add_argument("--watchdog-sec", type=float, default=None,
                        help="hung-dispatch watchdog: no step progress "
                             "within this many seconds fails the request "
                             "with a postmortem; must exceed the worst "
                             "cold build (default: [service] WATCHDOG_SEC)")
    parser.add_argument("--breaker-failures", type=int, default=None,
                        help="consecutive per-spec failures before the "
                             "circuit opens (default: [service] "
                             "BREAKER_FAILURES)")
    parser.add_argument("--breaker-cooloff", type=float, default=None,
                        help="circuit cool-off seconds (default: [service] "
                             "BREAKER_COOLOFF_SEC)")
    parser.add_argument("--result-cache", type=int, default=None,
                        help="completed results kept for idempotent "
                             "retries (default: [service] RESULT_CACHE)")
    parser.add_argument("--mem-watermark-mb", type=float, default=None,
                        help="process-RSS watermark triggering pool "
                             "eviction; 0 disables (default: [service] "
                             "MEM_WATERMARK_MB)")
    parser.add_argument("--on-client-drop", choices=("complete", "abort"),
                        default=None,
                        help="dead client socket mid-run: finish the solve "
                             "or abort at the next step boundary (default: "
                             "[service] ON_CLIENT_DROP)")
    parser.add_argument("--chaos", action="store_true",
                        help="accept per-run 'chaos' fault-injection "
                             "blocks (tools/chaos.py; TEST DEPLOYMENTS "
                             "ONLY)")
    parser.add_argument("--batch", action="store_true", default=None,
                        help="continuous batching: coalesce concurrent "
                             "same-spec requests into one vmapped "
                             "ensemble micro-batch (default: [service] "
                             "BATCH; docs/serving.md)")
    parser.add_argument("--batch-max", type=int, default=None,
                        help="seats per micro-batch (default: [service] "
                             "BATCH_MAX_MEMBERS)")
    parser.add_argument("--batch-window", type=float, default=None,
                        help="coalescing wait in seconds after the first "
                             "member seats (default: [service] "
                             "BATCH_WINDOW_SEC)")
    parser.add_argument("--batch-block", type=int, default=None,
                        help="fleet block size in iterations between "
                             "join/detach boundaries (default: [service] "
                             "BATCH_BLOCK_ITERS)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="plaintext GET /metrics listener serving "
                             "the stats surface in Prometheus text "
                             "exposition format; 0 binds an ephemeral "
                             "port (default: [service] METRICS_PORT, "
                             "where 0 disables; docs/observability.md)")
    parser.add_argument("--trace", nargs="?", const="", default=None,
                        metavar="FILE",
                        help="end-to-end request tracing (tools/"
                             "tracing.py): one span tree per request, "
                             "flushed as 'trace' records to FILE (bare "
                             "--trace rides the --sink); `python -m "
                             "dedalus_tpu trace` dumps/converts them")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s :: %(message)s")
    service = SolverService(
        host=args.host, port=args.port, pool_size=args.pool_size,
        sink=args.sink, allow_imports=args.import_builders,
        drain_grace=args.drain_grace, queue_depth=args.queue_depth,
        idle_timeout=args.idle_timeout, watchdog_sec=args.watchdog_sec,
        breaker_failures=args.breaker_failures,
        breaker_cooloff=args.breaker_cooloff,
        result_cache=args.result_cache,
        mem_watermark_mb=args.mem_watermark_mb,
        on_client_drop=args.on_client_drop, chaos_enabled=args.chaos,
        batching_enabled=args.batch, batch_max=args.batch_max,
        batch_window=args.batch_window, batch_block=args.batch_block,
        trace_file=args.trace, metrics_port=args.metrics_port)
    service.serve_forever()
    return 0
