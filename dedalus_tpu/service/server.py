"""
The warm-pool solver daemon: `python -m dedalus_tpu serve`.

One accept loop (main thread) spawns a lightweight reader thread per
connection: control requests (`ping`/`stats`/`shutdown`) are answered
immediately there — never starved behind a long run — while `run`
requests enqueue for the SINGLE executor thread that owns every solver
in the LRU pool (service/pool.py). JAX dispatch stays single-threaded,
and the queue wait is measured per request as `queue_sec`. Each run
executes through the existing resilient evolve path
(tools/resilience.ResilientLoop), so a served run gets the same
snapshot-rewind/dt-backoff recovery and durable checkpointing as a
local `solver.evolve_resilient(...)` call.

Graceful drain: SIGTERM/SIGINT (or a `shutdown` request) stop the accept
loop, request a cooperative stop on the in-flight loop via the PR-4
stop-request machinery — the current step completes, a final durable
checkpoint is written when the request configured one, and the client
receives its telemetry + result frames — then queued-but-unstarted
connections get a structured `draining` error and the daemon exits 0
after flushing a `service_stats` record to the telemetry sink.

Served-latency fields stamped on every request's telemetry record
(under `serving`; tools/metrics.py documents the vocabulary):
`queue_sec`, `pool_verdict` (hit | warm-cache | cold),
`time_to_first_step_sec` (dispatch start -> first step complete,
INCLUDING any build/compile a pool miss pays — the metric the warm pool
exists to collapse), `build_sec`, and `request_id`.
"""

import argparse
import json
import logging
import queue
import signal
import socket
import sys
import threading
import time

import numpy as np

from . import protocol
from .pool import SolverPool
from ..tools import metrics as metrics_mod

logger = logging.getLogger(__name__)

__all__ = ["SolverService", "main"]


class SolverService:

    def __init__(self, host="127.0.0.1", port=0, pool_size=None, sink=None,
                 allow_imports=False, drain_grace=600.0):
        self.host = host
        self.port = int(port)
        self.pool = SolverPool(size=pool_size, allow_imports=allow_imports)
        self.sink = str(sink) if sink else None
        self.drain_grace = float(drain_grace)
        self.requests_served = 0
        self.errors = 0
        self._request_seq = 0     # default-id counter: EVERY run request
                                  # advances it (success or not), so ids
                                  # in the telemetry sink never collide
        # errors is bumped from reader threads, the worker, and the
        # drain sweep concurrently; unguarded `+= 1` loses increments
        self._errors_lock = threading.Lock()
        self.started_ts = None
        self._queue = queue.Queue()
        self._draining = None
        self._active_loop = None
        self._active_lock = threading.Lock()
        self._sock = None

    # ---------------------------------------------------------- lifecycle

    def request_drain(self, why):
        """Begin a graceful drain (signal handler, `shutdown` request, or
        tests): refuse new work and cooperatively stop the in-flight run
        so it checkpoints before the daemon exits."""
        if self._draining is None:
            self._draining = str(why)
            logger.warning(f"service: draining ({why}) — in-flight run "
                           "will checkpoint and stop")
        with self._active_lock:
            loop = self._active_loop
        if loop is not None:
            loop.request_stop(str(why))

    def _handle_signal(self, signum, frame):
        self.request_drain(signal.Signals(signum).name)

    def serve_forever(self, ready_stream=None):
        """Bind, announce readiness, and serve until drained. Prints ONE
        JSON line {"kind": "ready", "port": N, "pid": ...} to
        `ready_stream` (default stdout) once accepting — the handshake
        benchmark/test drivers wait on."""
        previous = {}
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[signum] = signal.signal(signum, self._handle_signal)
            except (ValueError, OSError):
                pass   # non-main thread (in-process tests): drain via
                       # request_drain/shutdown only
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._sock.settimeout(0.2)
        self.started_ts = time.time()
        worker = threading.Thread(target=self._worker, name="service-worker",
                                  daemon=True)
        worker.start()
        import os
        banner = {"kind": "ready", "port": self.port, "pid": os.getpid(),
                  "pool_size": self.pool.size}
        stream = ready_stream if ready_stream is not None else sys.stdout
        print(json.dumps(banner), file=stream, flush=True)
        logger.info(f"service: listening on {self.host}:{self.port} "
                    f"(pool size {self.pool.size})")
        try:
            while self._draining is None:
                try:
                    conn, _addr = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._receive,
                                 args=(conn, time.perf_counter()),
                                 daemon=True).start()
        finally:
            self._sock.close()
            self._queue.put(None)           # worker stop sentinel
            worker.join(timeout=self.drain_grace)
            if worker.is_alive():
                logger.error("service: worker did not drain within "
                             f"{self.drain_grace}s; exiting anyway")
            self._refuse_queued()
            self._flush_stats()
            for signum, handler in previous.items():
                try:
                    signal.signal(signum, handler)
                except (ValueError, OSError):
                    pass
        logger.info(f"service: stopped ({self._draining})")

    def _flush_stats(self):
        """One `service_stats` record to the sink (and the log) at drain:
        pool hit/miss/eviction counters + request totals, so the serving
        trajectory is machine-recorded like every other subsystem."""
        record = dict(self.stats(), kind="service_stats",
                      ts=round(time.time(), 1))
        if self.sink:
            sink = metrics_mod.Metrics(sink=self.sink, enabled=True)
            sink.emit(record)
        logger.info(f"service: final stats {json.dumps(record)}")

    def stats(self):
        return {
            "requests_served": self.requests_served,
            "errors": self.errors,
            "draining": self._draining,
            "uptime_sec": round(time.time() - self.started_ts, 1)
            if self.started_ts else 0.0,
            "pool": self.pool.stats(),
        }

    # ----------------------------------------------------- reader threads

    def _receive(self, conn, t_accept):
        """Per-connection reader: parse the one request frame, answer
        control kinds inline (so `shutdown` can drain an in-flight run
        and `ping`/`stats` stay responsive during one), and enqueue runs
        for the single executor. Closes the connection itself on every
        path except a queued run (the worker owns that close)."""
        enqueued = False
        try:
            conn.settimeout(60.0)
            rfile = conn.makefile("rb")
            wfile = conn.makefile("wb")
            try:
                header, payload = protocol.recv_frame(rfile)
            except (protocol.ProtocolError, OSError) as exc:
                self._count_error()
                self._send_error(wfile, "bad-frame", str(exc))
                return
            if header is None:
                return
            kind = header.get("kind")
            if kind == "ping":
                protocol.send_frame(wfile, {"kind": "pong"})
            elif kind == "stats":
                protocol.send_frame(wfile, dict(self.stats(),
                                                kind="stats"))
            elif kind == "shutdown":
                protocol.send_frame(wfile, {"kind": "ok",
                                            "draining": True})
                self.request_drain("shutdown request")
            elif kind == "run":
                if self._draining is not None:
                    self._count_error()
                    self._send_error(
                        wfile, "draining",
                        f"daemon is draining ({self._draining})")
                    return
                self._queue.put((conn, wfile, header, payload, t_accept))
                enqueued = True
            else:
                self._count_error()
                self._send_error(wfile, "unknown-kind",
                                 f"unknown request kind {kind!r}")
        except Exception:
            self._count_error()
            logger.exception("service: connection reader failed")
        finally:
            if not enqueued:
                try:
                    conn.close()
                except OSError:
                    pass

    # ------------------------------------------------------------- worker

    def _worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            conn, wfile, header, payload, t_accept = item
            try:
                if self._draining is not None:
                    # drain began while this run sat in the queue
                    self._count_error()
                    self._send_error(
                        wfile, "draining",
                        f"daemon is draining ({self._draining})")
                else:
                    self._handle_run(header, payload, wfile, t_accept)
            except Exception:
                self._count_error()
                logger.exception("service: connection handler failed")
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _refuse_queued(self):
        """After the worker exits, answer any run a reader enqueued in
        the drain race window with a structured refusal."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is None:
                continue
            conn, wfile = item[0], item[1]
            self._send_error(wfile, "draining",
                             f"daemon is draining ({self._draining})")
            try:
                conn.close()
            except OSError:
                pass

    def _count_error(self):
        with self._errors_lock:
            self.errors += 1

    @staticmethod
    def _send_error(wfile, code, message):
        try:
            protocol.send_frame(wfile, {"kind": "error", "code": code,
                                        "message": message})
        except OSError:
            pass   # client gone; nothing to tell it

    # ---------------------------------------------------------------- run

    @staticmethod
    def _run_params(header):
        """Validate the run request's parameters (everything outside the
        spec). Raises SpecError with a message naming the field."""
        dt = header.get("dt")
        if not isinstance(dt, (int, float)) or not np.isfinite(dt) or dt <= 0:
            raise protocol.SpecError(f"run: dt must be a positive finite "
                                     f"number, got {dt!r}")
        stop_iteration = header.get("stop_iteration")
        stop_sim_time = header.get("stop_sim_time")
        if stop_iteration is None and stop_sim_time is None:
            raise protocol.SpecError(
                "run: one of stop_iteration / stop_sim_time is required")
        if stop_iteration is not None and (
                not isinstance(stop_iteration, int) or stop_iteration < 1):
            raise protocol.SpecError(
                f"run: stop_iteration must be a positive integer, got "
                f"{stop_iteration!r}")
        if stop_sim_time is not None and (
                not isinstance(stop_sim_time, (int, float))
                or not np.isfinite(stop_sim_time) or stop_sim_time <= 0):
            raise protocol.SpecError(
                f"run: stop_sim_time must be positive and finite, got "
                f"{stop_sim_time!r}")
        layout = header.get("layout", "c")
        if layout not in ("c", "g"):
            raise protocol.SpecError(f"run: layout must be 'c' or 'g', "
                                     f"got {layout!r}")
        outputs = header.get("outputs")
        if outputs is not None and (
                not isinstance(outputs, list)
                or not all(isinstance(n, str) for n in outputs)):
            raise protocol.SpecError("run: outputs must be a list of "
                                     "field names")
        checkpoint = header.get("checkpoint")
        if checkpoint is not None:
            if not (isinstance(checkpoint, dict) and checkpoint.get("dir")):
                raise protocol.SpecError(
                    "run: checkpoint must be {'dir': path, 'iter': N?}")
            ckpt_iter = checkpoint.get("iter") or 0
            if not isinstance(ckpt_iter, int) or ckpt_iter < 0:
                raise protocol.SpecError(
                    f"run: checkpoint iter must be a non-negative "
                    f"integer, got {checkpoint.get('iter')!r}")
            checkpoint = {"dir": str(checkpoint["dir"]), "iter": ckpt_iter}
        progress_every = header.get("progress_every") or 0
        if not isinstance(progress_every, int) or progress_every < 0:
            raise protocol.SpecError(
                f"run: progress_every must be a non-negative integer, "
                f"got {header.get('progress_every')!r}")
        return {
            "dt": float(dt),
            "stop_iteration": stop_iteration,
            "stop_sim_time": stop_sim_time,
            "layout": layout,
            "outputs": outputs,
            "checkpoint": checkpoint,
            "resume": bool(header.get("resume")),
            "progress_every": progress_every,
        }

    @staticmethod
    def _fields_by_name(solver):
        """Addressable fields of one solver: state variables plus the
        RHS-parameter (extra) fields — both settable as initial
        conditions and returnable as outputs."""
        by_name = {}
        for var in solver.state:
            by_name[var.name] = var
        for field in solver.eval_F.extra_fields:
            by_name.setdefault(field.name, field)
        return by_name

    @classmethod
    def _install_ics(cls, solver, ics):
        """Apply the request's field payload onto the (reset) solver.
        Targets state variables and RHS-parameter (extra) fields by name;
        unknown names are a spec error BEFORE any stepping."""
        by_name = cls._fields_by_name(solver)
        for name, (layout, array) in ics.items():
            field = by_name.get(name)
            if field is None:
                raise protocol.SpecError(
                    f"run: unknown field {name!r} in initial conditions "
                    f"(known: {sorted(k for k in by_name if k)})")
            try:
                field[layout] = array
            except (ValueError, TypeError) as exc:
                raise protocol.SpecError(
                    f"run: initial condition for {name!r} rejected: {exc}")

    @classmethod
    def _output_fields(cls, solver, names):
        """Resolve the requested output field list (None: all state
        variables). Unknown names are a spec error — a typo'd output must
        fail loudly before stepping, not return an empty payload."""
        if names is None:
            return list(solver.state)
        by_name = cls._fields_by_name(solver)
        unknown = [n for n in names if n not in by_name]
        if unknown:
            raise protocol.SpecError(
                f"run: unknown output field(s) {unknown} "
                f"(known: {sorted(k for k in by_name if k)})")
        return [by_name[n] for n in names]

    def _handle_run(self, header, payload, wfile, t_accept):
        from ..tools.resilience import ResilientLoop
        from ..tools.exceptions import SolverHealthError
        import jax
        t_dispatch = time.perf_counter()
        queue_sec = t_dispatch - t_accept
        self._request_seq += 1
        request_id = str(header.get("id") or f"r{self._request_seq}")
        try:
            spec = protocol.normalize_spec(header.get("spec"))
            params = self._run_params(header)
            ics = protocol.decode_fields(payload) if payload else {}
            entry, verdict, build_sec = self.pool.acquire(spec)
            solver = entry.solver
            self._install_ics(solver, ics)
            targets = self._output_fields(solver, params["outputs"])
        except protocol.SpecError as exc:
            self._count_error()
            self._send_error(wfile, "bad-spec", str(exc))
            return
        except Exception as exc:
            # a builder blowing up on technically-valid params (resolution
            # the basis rejects, singular operator, ...) must reply
            # structurally, not drop the connection
            self._count_error()
            logger.exception(f"service: build for request {request_id} "
                             "failed")
            self._send_error(wfile, "build-failed",
                             f"{type(exc).__name__}: {exc}")
            return
        if params["stop_iteration"] is not None:
            solver.stop_iteration = params["stop_iteration"]
        if params["stop_sim_time"] is not None:
            solver.stop_sim_time = params["stop_sim_time"]
        solver.metrics.sink = self.sink
        solver.metrics.meta["config"] = f"{protocol.spec_name(spec)}_served"
        protocol.send_frame(wfile, {
            "kind": "ack", "id": request_id, "pool_verdict": verdict,
            "queue_sec": round(queue_sec, 6),
            "build_sec": round(build_sec, 4)})

        ttfs = [None]
        progress_every = params["progress_every"]
        progress_next = [progress_every]

        def step_hook(s):
            # first completed step: block so time-to-first-step covers the
            # device tail (and, on a miss, the build + compile it followed)
            if ttfs[0] is None:
                jax.block_until_ready(s.X)
                ttfs[0] = time.perf_counter() - t_dispatch
            if progress_every and s.iteration >= progress_next[0]:
                progress_next[0] = s.iteration + progress_every
                try:
                    protocol.send_frame(wfile, {
                        "kind": "progress", "id": request_id,
                        "iteration": int(s.iteration),
                        "sim_time": float(s.sim_time)})
                except OSError:
                    pass   # client hung up; finish the run regardless

        loop_kw = {}
        checkpoint = params["checkpoint"]
        if checkpoint is not None:
            loop_kw["checkpoint_dir"] = checkpoint["dir"]
            loop_kw["checkpoint_iter"] = checkpoint["iter"]
            loop_kw["resume"] = params["resume"]
        # the service owns this run's single telemetry flush (serving
        # fields stamped on it); the loop's own exit flush is suppressed
        loop = ResilientLoop(solver, dt=params["dt"], step_hook=step_hook,
                             install_signal_handlers=False,
                             flush_telemetry=False, **loop_kw)
        with self._active_lock:
            self._active_loop = loop
        if self._draining is not None:
            # drain began between queue pop and loop construction: stop at
            # the first boundary, still writing the final checkpoint
            loop.request_stop(self._draining)
        try:
            summary = loop.run(log_cadence=0)
        except SolverHealthError as exc:
            self._count_error()
            serving = {"queue_sec": round(queue_sec, 6),
                       "pool_verdict": verdict,
                       "time_to_first_step_sec": ttfs[0],
                       "build_sec": round(build_sec, 4),
                       "request_id": request_id}
            try:
                solver.flush_metrics(extra={"serving": serving})
            except Exception:
                pass
            self._send_error(
                wfile, "health",
                f"run halted unrecoverably: {getattr(exc, 'reason', exc)}")
            return
        except Exception as exc:
            self._count_error()
            logger.exception(f"service: request {request_id} failed")
            self._send_error(wfile, "internal",
                             f"{type(exc).__name__}: {exc}")
            return
        finally:
            with self._active_lock:
                self._active_loop = None
        serving = {
            "queue_sec": round(queue_sec, 6),
            "pool_verdict": verdict,
            "time_to_first_step_sec": round(ttfs[0], 6)
            if ttfs[0] is not None else None,
            "build_sec": round(build_sec, 4),
            "request_id": request_id,
        }
        record = None
        try:
            record = solver.flush_metrics(extra={"serving": serving})
        except Exception as exc:
            logger.warning(f"service: telemetry flush failed: {exc}")
        if record is not None:
            try:
                protocol.send_frame(wfile, record)
            except (TypeError, ValueError):
                logger.warning("service: telemetry record not "
                               "JSON-serializable; skipped")
            except OSError:
                pass
        out_fields = {}
        for var in targets:
            if params["layout"] == "c":
                out_fields[var.name] = ("c", np.asarray(var.coeff_data()))
            else:
                out_fields[var.name] = ("g", np.array(var["g"]))
        result = {
            "kind": "result", "id": request_id,
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "stopped_by": summary.get("stopped_by"),
            "rewinds": summary.get("rewinds", 0),
            "serving": serving,
        }
        if summary.get("resumed_from"):
            result["resumed_from"] = summary["resumed_from"]
        try:
            protocol.send_frame(wfile, result,
                                payload=protocol.encode_fields(out_fields))
        except OSError:
            logger.warning(f"service: client for {request_id} hung up "
                           "before the result frame")
        self.requests_served += 1


# --------------------------------------------------------------- CLI

def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu serve",
        description="Warm-pool solver daemon: LRU pool of live compiled "
                    "solvers served over a local socket (docs/serving.md).")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address (default: %(default)s)")
    parser.add_argument("--port", type=int, default=0,
                        help="TCP port; 0 picks an ephemeral port, "
                             "announced on stdout (default: %(default)s)")
    parser.add_argument("--pool-size", type=int, default=None,
                        help="warm solver entries kept (default: "
                             "[service] POOL_SIZE, else 4)")
    parser.add_argument("--sink", default=None,
                        help="JSONL telemetry sink for served records "
                             "(tools/metrics.py format)")
    parser.add_argument("--import-builders", action="store_true",
                        help="allow dotted module:function builder specs "
                             "(server-side imports; trusted clients only)")
    parser.add_argument("--drain-grace", type=float, default=600.0,
                        help="seconds to wait for the in-flight run at "
                             "drain (default: %(default)s)")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s :: %(message)s")
    service = SolverService(
        host=args.host, port=args.port, pool_size=args.pool_size,
        sink=args.sink, allow_imports=args.import_builders,
        drain_grace=args.drain_grace)
    service.serve_forever()
    return 0
