"""
Replica fleet supervision for the spec-hash router (service/router.py).

A `ReplicaSupervisor` owns N `SolverService` replicas — SPAWNED as
`python -m dedalus_tpu serve --port 0` subprocesses whose ready banner
names the ephemeral port, or ADOPTED from `--attach host:port` pairs the
operator already runs — and keeps one answer current for the router:
which replicas can take traffic right now.

Health model (docs/serving.md "Replica fleet"):

  * crash  — a spawned replica's process exited. Detected on the next
    prober cycle via `Popen.poll()`; restarted with exponential backoff
    (base doubled per consecutive failure, capped, reset after the
    replica proves healthy again).
  * wedge  — the process is alive but the daemon stopped answering the
    `stats` frame (`wedge_misses` consecutive probe timeouts). A wedged
    SPAWNED replica is SIGKILLed and restarted through the same backoff
    path; an attached one is only marked down (we do not own it) and
    rejoins when its probes recover.
  * drain  — the probe's stats reply carries `draining`; the replica is
    reported non-routable so the router stops sending NEW work, while
    its in-flight runs finish under the daemon's own drain grace. A
    spawned replica that drains to exit comes back through the crash
    path — a rolling restart, not an outage.
  * watchdog postmortem — `faults.watchdog_fires` moving between probes
    is surfaced per replica and counted fleet-wide. The daemon heals
    itself (worker replacement + requeue), so the supervisor only
    records the signal; it restarts nothing that still answers stats.

Lock discipline: `_lock` guards the replica table and the fleet
counters, and every `with self._lock:` block is TIGHT — probing,
spawning, killing, and banner reads all happen outside it on snapshots,
so the fleet never holds its lock across network or process IO and the
static lock graph stays edge-free (tools/lint/threadcheck.py).
"""

import json
import logging
import os
import select
import signal
import socket
import subprocess
import sys
import threading
import time

from . import protocol
from ..tools.lint.threadcheck import named_lock

logger = logging.getLogger(__name__)

__all__ = ["Replica", "ReplicaSupervisor"]


class Replica:
    """One replica's record. Plain data: every mutation happens inside a
    tight `supervisor._lock` section (enforced by review + the DTC tier
    on the supervisor's table field, not per-attribute)."""

    __slots__ = ("name", "host", "port", "proc", "attached", "state",
                 "draining", "restarts", "misses", "watchdog_fires",
                 "last_stats", "generation", "backoff_sec",
                 "next_restart_ts", "log_path", "started_ts")

    def __init__(self, name, host, port, proc=None, attached=False,
                 log_path=None):
        self.name = name
        self.host = host
        self.port = int(port)
        self.proc = proc
        self.attached = bool(attached)
        self.state = "up"            # up | down | restarting
        self.draining = False
        self.restarts = 0
        self.misses = 0
        self.watchdog_fires = 0
        self.last_stats = None
        self.generation = 0
        self.backoff_sec = 0.0
        self.next_restart_ts = 0.0
        self.log_path = log_path
        self.started_ts = time.monotonic()

    def pid(self):
        return self.proc.pid if self.proc is not None else None

    def snapshot(self):
        return {"name": self.name, "host": self.host, "port": self.port,
                "state": self.state, "draining": self.draining,
                "attached": self.attached, "restarts": self.restarts,
                "misses": self.misses, "generation": self.generation,
                "watchdog_fires": self.watchdog_fires,
                "pid": self.pid(),
                "backoff_sec": round(self.backoff_sec, 3)}


class ReplicaSupervisor:
    """Spawn/adopt `SolverService` replicas, health-check them via the
    stats frame, and restart spawned casualties with exponential
    backoff. The router reads `routable()` per request and `snapshot()`
    for stats; both are cheap lock-bounded copies."""

    def __init__(self, replicas=0, attach=(), host="127.0.0.1",
                 replica_args=(), workdir=None, probe_sec=1.0,
                 probe_timeout=3.0, wedge_misses=4, backoff_base=0.5,
                 backoff_max=30.0, spawn_timeout=300.0, on_spawn=None):
        self.host = host
        self.n_spawn = int(replicas)
        self.attach = [self._parse_endpoint(a) for a in attach]
        self.replica_args = list(replica_args)
        self.workdir = workdir
        self.probe_sec = float(probe_sec)
        self.probe_timeout = float(probe_timeout)
        self.wedge_misses = max(int(wedge_misses), 1)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.spawn_timeout = float(spawn_timeout)
        self.on_spawn = on_spawn     # hook(proc, log_path): test registry
        self._replicas = {}          # name -> Replica
        self._lock = named_lock(
            "service/fleet.py:ReplicaSupervisor._lock")
        self.restarts_total = 0
        self.crashes_detected = 0
        self.wedges_detected = 0
        self.watchdog_fires_total = 0
        self._stop = threading.Event()
        self._prober = None

    @staticmethod
    def _parse_endpoint(entry):
        if isinstance(entry, (tuple, list)):
            return str(entry[0]), int(entry[1])
        host, _, port = str(entry).rpartition(":")
        return (host or "127.0.0.1"), int(port)

    # ------------------------------------------------------------ lifecycle

    def start(self):
        """Spawn the owned replicas (concurrently — the banner reads
        happen after every process has been launched), adopt the
        attached endpoints, and start the prober thread."""
        launched = []
        for i in range(self.n_spawn):
            name = f"r{i}"
            proc, log_path = self._launch(name)
            launched.append((name, proc, log_path))
        adopted = []
        for name, proc, log_path in launched:
            port = self._read_banner(name, proc)
            adopted.append(Replica(name, self.host, port, proc=proc,
                                   log_path=log_path))
        for j, (host, port) in enumerate(self.attach):
            adopted.append(Replica(f"a{j}", host, port, attached=True))
        with self._lock:
            for replica in adopted:
                self._replicas[replica.name] = replica
        if not adopted:
            raise ValueError("fleet: no replicas to supervise (use "
                             "replicas=N or attach=...)")
        self._prober = threading.Thread(target=self._probe_loop,
                                        name="fleet-prober", daemon=True)
        self._prober.start()
        return [r.name for r in adopted]

    def _launch(self, name):
        """Popen one replica daemon (stdout = the ready banner pipe,
        stderr = its log file). No lock held — this is process IO."""
        cmd = [sys.executable, "-m", "dedalus_tpu", "serve",
               "--port", "0"] + list(self.replica_args)
        log_path = None
        stderr = subprocess.DEVNULL
        if self.workdir:
            os.makedirs(self.workdir, exist_ok=True)
            if "--sink" not in self.replica_args:
                cmd += ["--sink", os.path.join(self.workdir,
                                               f"{name}.jsonl")]
            log_path = os.path.join(self.workdir, f"{name}.stderr")
            stderr = open(log_path, "ab")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=stderr, env=env)
        if stderr is not subprocess.DEVNULL:
            stderr.close()
        if self.on_spawn is not None:
            try:
                self.on_spawn(proc, log_path)
            except Exception:
                logger.exception("fleet: on_spawn hook failed")
        logger.info(f"fleet: launched replica {name} pid {proc.pid}")
        return proc, log_path

    def _read_banner(self, name, proc):
        """Block (bounded) for the replica's one-line ready banner and
        return its port. A replica that dies or stays silent past
        `spawn_timeout` is killed and reported."""
        deadline = time.monotonic() + self.spawn_timeout
        buf = b""
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet: replica {name} exited rc={proc.returncode} "
                    f"before its ready banner")
            ready, _, _ = select.select([proc.stdout], [], [], 0.25)
            if not ready:
                continue
            chunk = proc.stdout.readline()
            if not chunk:
                continue
            buf = chunk
            try:
                banner = json.loads(buf.decode())
            except ValueError:
                continue
            if banner.get("kind") == "ready":
                return int(banner["port"])
        proc.kill()
        raise RuntimeError(f"fleet: replica {name} produced no ready "
                           f"banner within {self.spawn_timeout}s")

    def stop(self, shutdown_replicas=True, grace_sec=60.0):
        """Stop the prober; drain-and-exit every SPAWNED replica (the
        shutdown frame is the SIGTERM path), escalating to SIGKILL past
        the grace. Attached replicas are left alone — we do not own
        them."""
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=self.probe_timeout
                              + self.probe_sec + 5.0)
        with self._lock:
            owned = [(r.name, r.host, r.port, r.proc)
                     for r in self._replicas.values()
                     if r.proc is not None]
        if not shutdown_replicas:
            return
        for name, host, port, proc in owned:
            if proc.poll() is not None:
                continue
            try:
                self._request(host, port, {"kind": "shutdown"},
                              timeout=5.0)
            except Exception:
                proc.terminate()
        deadline = time.monotonic() + float(grace_sec)
        for name, _host, _port, proc in owned:
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                logger.warning(f"fleet: replica {name} ignored drain; "
                               "SIGKILL")
                proc.kill()
                proc.wait(timeout=10)

    # ------------------------------------------------------------- probing

    def _request(self, host, port, request, timeout=None):
        """One frame round-trip to a replica (no lock held)."""
        timeout = self.probe_timeout if timeout is None else timeout
        with socket.create_connection((host, port),
                                      timeout=timeout) as conn:
            conn.settimeout(timeout)
            wfile = conn.makefile("wb")
            rfile = conn.makefile("rb")
            protocol.send_frame(wfile, request)
            header, payload = protocol.recv_frame(rfile)
            return header, payload

    def _probe_loop(self):
        while not self._stop.wait(self.probe_sec):
            try:
                self._probe_once()
            except Exception:
                logger.exception("fleet: prober cycle failed")

    def _probe_once(self):
        with self._lock:
            work = [(r.name, r.host, r.port, r.proc, r.generation,
                     r.state, r.next_restart_ts)
                    for r in self._replicas.values()]
        now = time.monotonic()
        verdicts = []
        respawns = []
        for name, host, port, proc, gen, state, next_ts in work:
            if proc is not None and proc.poll() is not None:
                if state == "down":
                    if now >= next_ts:
                        respawns.append((name, gen))
                    continue
                verdicts.append((name, gen, "crashed", None))
                continue
            if state == "down" and proc is None:
                # attached and unreachable: keep probing for recovery
                pass
            try:
                header, _ = self._request(host, port, {"kind": "stats"})
                if header is None or header.get("kind") != "stats":
                    raise protocol.ProtocolError("no stats reply")
                verdicts.append((name, gen, "ok", header))
            except Exception:
                verdicts.append((name, gen, "miss", None))
        kills = self._apply_verdicts(verdicts)
        for name, proc in kills:
            logger.warning(f"fleet: replica {name} wedged; SIGKILL pid "
                           f"{proc.pid}")
            try:
                proc.kill()
            except OSError:
                pass
        for name, gen in respawns:
            self._respawn(name, gen)

    def _apply_verdicts(self, verdicts):
        """Fold one probe cycle's results into the table (tight lock;
        returns the wedged processes to kill OUTSIDE it)."""
        kills = []
        now = time.monotonic()
        with self._lock:
            for name, gen, verdict, stats in verdicts:
                replica = self._replicas.get(name)
                if replica is None or replica.generation != gen:
                    continue          # restarted under us; stale verdict
                if verdict == "ok":
                    fires = int(((stats.get("faults") or {})
                                 .get("watchdog_fires") or 0))
                    if fires > replica.watchdog_fires:
                        self.watchdog_fires_total += (
                            fires - replica.watchdog_fires)
                        logger.warning(
                            f"fleet: replica {name} reported a watchdog "
                            f"postmortem (fires={fires}); daemon healed "
                            "itself, not restarting")
                    replica.watchdog_fires = fires
                    replica.misses = 0
                    replica.state = "up"
                    replica.draining = bool(stats.get("draining"))
                    replica.last_stats = stats
                    replica.backoff_sec = 0.0
                elif verdict == "crashed":
                    self.crashes_detected += 1
                    replica.state = "down"
                    replica.draining = False
                    replica.backoff_sec = (
                        min(max(replica.backoff_sec * 2.0,
                                self.backoff_base), self.backoff_max))
                    replica.next_restart_ts = now + replica.backoff_sec
                    logger.warning(
                        f"fleet: replica {name} crashed "
                        f"(rc={replica.proc.returncode}); restart in "
                        f"{replica.backoff_sec:.2f}s")
                elif verdict == "miss":
                    replica.misses += 1
                    if replica.misses < self.wedge_misses:
                        continue
                    self.wedges_detected += 1
                    replica.draining = False
                    if replica.proc is not None \
                            and replica.state != "down":
                        kills.append((name, replica.proc))
                        # the kill lands outside this lock; the NEXT
                        # cycle sees the exit and runs the crash path
                    replica.state = "down"
        return kills

    def _respawn(self, name, generation):
        """Relaunch one crashed spawned replica (process IO outside the
        lock; the table swap is tight). A failed relaunch re-arms the
        backoff clock."""
        with self._lock:
            replica = self._replicas.get(name)
            if replica is None or replica.generation != generation \
                    or replica.state == "restarting":
                return
            replica.state = "restarting"
        try:
            proc, log_path = self._launch(name)
            port = self._read_banner(name, proc)
        except Exception:
            logger.exception(f"fleet: relaunch of {name} failed")
            now = time.monotonic()
            with self._lock:
                replica = self._replicas.get(name)
                if replica is not None:
                    replica.state = "down"
                    replica.backoff_sec = min(
                        max(replica.backoff_sec * 2.0, self.backoff_base),
                        self.backoff_max)
                    replica.next_restart_ts = now + replica.backoff_sec
            return
        with self._lock:
            replica = self._replicas.get(name)
            if replica is None:
                proc.kill()
                return
            replica.proc = proc
            replica.port = port
            replica.log_path = log_path
            replica.state = "up"
            replica.draining = False
            replica.misses = 0
            replica.watchdog_fires = 0
            replica.last_stats = None
            replica.generation += 1
            replica.restarts += 1
            replica.started_ts = time.monotonic()
            self.restarts_total += 1
        logger.warning(f"fleet: replica {name} restarted (pid "
                       f"{proc.pid}, port {port})")

    # ------------------------------------------------------------- queries

    def snapshot(self):
        """Per-replica state list (copies; safe to hold)."""
        with self._lock:
            return [r.snapshot() for r in self._replicas.values()]

    def routable(self):
        """Names of replicas the router may send NEW work to."""
        with self._lock:
            return [r.name for r in self._replicas.values()
                    if r.state == "up" and not r.draining]

    def endpoint(self, name):
        """(host, port) of one replica, or None."""
        with self._lock:
            replica = self._replicas.get(name)
            if replica is None:
                return None
            return replica.host, replica.port

    def pid_of(self, name):
        with self._lock:
            replica = self._replicas.get(name)
            return replica.pid() if replica is not None else None

    def set_endpoint(self, name, host=None, port=None):
        """Repoint one replica's endpoint (ops/chaos machinery: DNS
        repointing, or tools/chaos.partition simulating an unreachable
        replica). Returns the previous (host, port)."""
        with self._lock:
            replica = self._replicas.get(name)
            if replica is None:
                raise KeyError(f"fleet: no replica named {name!r}")
            previous = (replica.host, replica.port)
            if host is not None:
                replica.host = str(host)
            if port is not None:
                replica.port = int(port)
            return previous

    def stats(self):
        """The `fleet` stats block (docs/serving.md#replica-fleet)."""
        snap = self.snapshot()
        with self._lock:
            counters = {"restarts": self.restarts_total,
                        "crashes": self.crashes_detected,
                        "wedges": self.wedges_detected,
                        "watchdog_fires": self.watchdog_fires_total}
        states = {}
        for r in snap:
            key = "draining" if r["draining"] else r["state"]
            states[key] = states.get(key, 0) + 1
        return dict(counters, replicas={r["name"]: r for r in snap},
                    states=states,
                    spawned=sum(1 for r in snap if not r["attached"]),
                    attached=sum(1 for r in snap if r["attached"]))
