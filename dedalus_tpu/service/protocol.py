"""
Wire protocol of the warm-pool solver service (`python -m dedalus_tpu
serve` / `submit`): problem-spec schema, message framing, and the
npz field-payload codecs shared by server.py and client.py.

Framing
-------
Every message is ONE frame on the stream:

    <JSON header line, UTF-8, "\\n"-terminated>
    <payload: exactly header["payload_bytes"] raw bytes, when present>

Headers are flat JSON objects with a `kind` discriminator. Telemetry
records stream back to the client as plain frames whose header IS the
record — the `tools/metrics.py` JSONL sink format is the wire format, so
a client can append streamed frames straight into a results-style file
and `python -m dedalus_tpu report` reads them unchanged.

Client -> server kinds:  run, ping, stats, shutdown
Server -> client kinds:  ready (stdout banner, not a frame), ack,
                         progress, step_metrics (telemetry), result,
                         error, pong, stats

Run headers may carry `deadline_sec` (a per-request deadline the daemon
enforces at queue pop and mid-run) and an idempotent `id` (a retry of a
COMPLETED id replays the cached result — ack pool_verdict "replayed",
result flagged `replayed: true` — instead of re-running). Structured
`error` codes: bad-frame, bad-spec, build-failed, unknown-kind,
draining, overloaded (+retry_after_sec), circuit-open
(+retry_after_sec), deadline-exceeded, watchdog-timeout, health,
internal — the daemon survives every one of them (docs/serving.md maps
each to its telemetry and operator action).

Field payloads are `np.savez` archives: one member per field, named
`<layout>__<fieldname>` with layout `g` (grid) or `c` (coefficient).
Coefficient layout round-trips bit-exactly (no transform in the path),
which is what makes served results bit-identical to in-process solves.

Problem specs
-------------
A spec is a JSON object naming a registered problem builder plus its
parameters:

    {"problem": "diffusion",       "params": {"size": 64}}
    {"problem": "rayleigh_benard", "params": {"Nx": 256, "Nz": 64}}
    {"builder": "mypkg.mymod:make_solver", "params": {...}}

`problem` resolves in the built-in registry below; `builder` imports a
dotted `module:function` path ON THE SERVER and is therefore gated
behind `serve --import-builders` (a local trust boundary: anyone who can
reach the socket can already run code as the daemon's user, but the gate
keeps accidental remote exposure from becoming an import primitive).
Builders take the spec params as keyword arguments and return a built
`InitialValueSolver`. Initial conditions arrive separately in the run
request's field payload, so one pooled (compiled) solver serves many
requests — the pool zeroes all state and RHS-parameter fields before
each run and the request's payload overwrites the fields it names.
"""

import io
import json

import numpy as np

__all__ = ["PROBLEMS", "ProtocolError", "SpecError", "ServiceError",
           "decode_fields", "encode_fields", "normalize_spec",
           "recv_frame", "recv_header", "recv_payload",
           "register_problem", "resolve_builder", "send_frame",
           "spec_digest", "spec_name"]

# Defensive bounds: a stray client writing garbage at the socket must
# produce a structured error, not an OOM in the daemon. The payload
# bound is per frame and far above realistic field payloads (an RB
# 256x64 f64 state is ~0.5 MB/field) while small enough that even a
# handful of concurrent garbage connections cannot buffer their way to
# gigabytes before spec validation runs.
MAX_HEADER_BYTES = 1 << 20        # one JSON control line
MAX_PAYLOAD_BYTES = 1 << 28       # npz field payload (256 MiB)


class ProtocolError(Exception):
    """Malformed frame or stream-level violation."""


class SpecError(ValueError):
    """Invalid problem spec or run parameters (maps to a structured
    `error` reply with code 'bad-spec'; the daemon stays up)."""


class ServiceError(RuntimeError):
    """Client-side surface of a structured `error` reply. `frame` keeps
    the whole reply; `retry_after_sec` surfaces the daemon's load-shed /
    circuit cool-off hint when the reply carried one."""

    def __init__(self, code, message, frame=None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.frame = dict(frame) if frame else {}

    @property
    def retry_after_sec(self):
        return self.frame.get("retry_after_sec")


# ---------------------------------------------------------------- framing

def send_frame(wfile, header, payload=None):
    """Write one frame (header dict + optional payload bytes) and flush."""
    header = dict(header)
    if payload is not None:
        header["payload_bytes"] = len(payload)
    wfile.write(json.dumps(header).encode() + b"\n")
    if payload is not None:
        wfile.write(payload)
    wfile.flush()


def recv_header(rfile):
    """Read and validate ONE frame header line (including its
    payload_bytes declaration). Returns the header dict, or None on
    clean EOF. Raises ProtocolError on garbage. Split from recv_frame so
    a server can bound the header read and the payload read separately
    (a 256 MiB payload legitimately takes longer than a control line)."""
    line = rfile.readline(MAX_HEADER_BYTES + 1)
    if not line:
        return None
    if len(line) > MAX_HEADER_BYTES:
        raise ProtocolError("header line exceeds the size bound")
    try:
        header = json.loads(line.decode())
    except (UnicodeDecodeError, ValueError) as exc:
        raise ProtocolError(f"unparsable header: {exc}")
    if not isinstance(header, dict):
        raise ProtocolError("header is not a JSON object")
    n = header.get("payload_bytes", 0)
    if not isinstance(n, int) or n < 0 or n > MAX_PAYLOAD_BYTES:
        raise ProtocolError(f"bad payload_bytes: {n!r}")
    return header


def recv_payload(rfile, header):
    """Read the payload a validated header declared (None when it
    declared none). Raises ProtocolError on truncation."""
    n = header.get("payload_bytes", 0)
    if not n:
        return None
    payload = rfile.read(n)
    if len(payload) != n:
        raise ProtocolError(
            f"truncated payload: expected {n} bytes, got {len(payload)}")
    return payload


def recv_frame(rfile):
    """Read one frame. Returns (header, payload_or_None); None header on
    clean EOF. Raises ProtocolError on garbage or truncation."""
    header = recv_header(rfile)
    if header is None:
        return None, None
    return header, recv_payload(rfile, header)


# ------------------------------------------------------- field payloads

def encode_fields(fields):
    """npz-encode {name: (layout, array)} field data. Layout is 'g'
    (grid) or 'c' (coefficient); coefficient arrays round-trip
    bit-exactly."""
    members = {}
    for name, (layout, array) in fields.items():
        if layout not in ("g", "c"):
            raise SpecError(f"field {name!r}: unknown layout {layout!r}")
        members[f"{layout}__{name}"] = np.asarray(array)
    buf = io.BytesIO()
    np.savez(buf, **members)
    return buf.getvalue()


def decode_fields(payload):
    """Decode an npz field payload to {name: (layout, array)}."""
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as npz:
            out = {}
            for key in npz.files:
                layout, sep, name = key.partition("__")
                if sep != "__" or layout not in ("g", "c") or not name:
                    raise SpecError(
                        f"field payload member {key!r}: expected "
                        "'<g|c>__<fieldname>'")
                out[name] = (layout, npz[key])
            return out
    except SpecError:
        raise
    except Exception as exc:
        raise SpecError(f"unreadable field payload: {exc}")


# ------------------------------------------------------ problem registry

def _build_diffusion(size=64, dtype="float64", scheme="SBDF2",
                     warmup_iterations=2):
    """1-D forced heat IVP `dt(u) - lap(u) = a*u` with a parameter field
    `a` (an RHS extra operand), mirroring benchmarks/ensemble.py — the
    dispatch-bound serving regime."""
    from .. import public as d3
    size = int(size)
    if size < 4:
        raise SpecError(f"diffusion: size {size} too small")
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.dtype(dtype))
    xb = d3.RealFourier(xc, size=size, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "a": a, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = a*u")
    scheme_cls = _scheme(scheme)
    return problem.build_solver(scheme_cls, enforce_real_cadence=0,
                                warmup_iterations=int(warmup_iterations))


def _build_rayleigh_benard(Nx=256, Nz=64, dtype="float64",
                           matsolver=None):
    """The 2-D Rayleigh-Benard flagship (extras/bench_problems.py) — the
    compute-bound serving regime. ICs come from the request payload (the
    builder's random fill is zeroed by the pool reset). `matsolver`
    ("banded" on the headline configuration) rides into the assembly and
    pool keys, so requests differing in it never share an entry."""
    from ..extras.bench_problems import build_rb_solver
    if matsolver is not None and str(matsolver).lower() not in (
            "auto", "banded", "dense"):
        raise SpecError(f"rayleigh_benard: matsolver {matsolver!r} not in "
                        "auto|banded|dense")
    solver, b = build_rb_solver(int(Nx), int(Nz), np.dtype(dtype),
                                matsolver=matsolver)
    return solver


def _scheme(name):
    from ..core import timesteppers
    try:
        return timesteppers.schemes[str(name)]
    except KeyError:
        raise SpecError(f"unknown timestepper scheme {name!r} "
                        f"(known: {sorted(timesteppers.schemes)})")


PROBLEMS = {
    "diffusion": _build_diffusion,
    "rayleigh_benard": _build_rayleigh_benard,
}


def register_problem(name, builder):
    """Register a named problem builder (server-side extension point:
    import your module before `serve_forever`, or ship it behind
    `--import-builders` dotted specs)."""
    PROBLEMS[str(name)] = builder


def normalize_spec(spec, check_registry=True):
    """Validate and canonicalize one spec dict. Returns
    {"problem"|"builder": str, "params": dict} with params JSON-clean.
    `check_registry=False` skips the registered-problem membership test —
    the CLIENT normalizes structurally only (the daemon's registry, which
    may hold extra `register_problem` entries, is authoritative)."""
    if not isinstance(spec, dict):
        raise SpecError(f"spec must be a JSON object, got "
                        f"{type(spec).__name__}")
    kind = [k for k in ("problem", "builder") if spec.get(k)]
    if len(kind) != 1:
        raise SpecError("spec needs exactly one of 'problem' (registered "
                        "name) or 'builder' (module:function)")
    params = spec.get("params", {})
    if not isinstance(params, dict):
        raise SpecError("spec 'params' must be a JSON object")
    try:
        params = json.loads(json.dumps(params, sort_keys=True))
    except (TypeError, ValueError) as exc:
        raise SpecError(f"spec params are not JSON-serializable: {exc}")
    out = {kind[0]: str(spec[kind[0]]), "params": params}
    if check_registry and kind[0] == "problem" \
            and out["problem"] not in PROBLEMS:
        raise SpecError(f"unknown problem {out['problem']!r} "
                        f"(registered: {sorted(PROBLEMS)})")
    return out


def spec_name(spec):
    """Short human name of a spec (telemetry `config` stem)."""
    if "problem" in spec:
        return spec["problem"]
    return spec.get("builder", "?").rpartition(":")[2] or "builder"


def spec_digest(spec):
    """Content digest of a normalized spec — the pool's fast-path alias
    key (the authoritative identity is the assembly-cache pool key
    computed from the BUILT solver; textually different specs that build
    the same problem converge there)."""
    import hashlib
    blob = json.dumps(normalize_spec(spec), sort_keys=True).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def resolve_builder(spec, allow_imports=False):
    """Resolve a normalized spec to a zero-argument builder callable."""
    spec = normalize_spec(spec)
    params = spec["params"]
    if "problem" in spec:
        builder = PROBLEMS[spec["problem"]]
    else:
        if not allow_imports:
            raise SpecError(
                "dotted 'builder' specs are disabled on this daemon "
                "(start it with --import-builders to allow server-side "
                "imports from trusted local clients)")
        module_name, sep, func_name = spec["builder"].partition(":")
        if not (module_name and sep and func_name):
            raise SpecError(f"builder {spec['builder']!r} is not of the "
                            "form 'module:function'")
        import importlib
        try:
            module = importlib.import_module(module_name)
            builder = getattr(module, func_name)
        except (ImportError, AttributeError) as exc:
            raise SpecError(f"cannot import builder "
                            f"{spec['builder']!r}: {exc}")

    def build():
        try:
            solver = builder(**params)
        except SpecError:
            raise
        except TypeError as exc:
            # bad parameter names/arity surface as spec errors, not 500s
            raise SpecError(f"builder rejected params {params}: {exc}")
        if solver is None or not hasattr(solver, "step"):
            raise SpecError(
                f"builder for {spec_name(spec)!r} did not return an IVP "
                f"solver (got {type(solver).__name__})")
        return solver

    return build
