"""
Spec-hash router: one front-end daemon fanning the wire protocol out
across N `SolverService` replicas.

`python -m dedalus_tpu route --replicas N` (or `--attach host:port,...`)
speaks the exact client protocol (service/protocol.py) on one port and
forwards each `run` to the replica chosen by consistent-hashing the
canonical `spec_digest` (the warm-pool key, protocol.py:296) onto a
vnode ring — so same-spec traffic keeps landing on the replica whose
warm pool and live continuous batch already hold that program, and
adding or losing a replica only remaps the keys it owned.

Robustness model (docs/serving.md#replica-fleet):

  * failover — the router fronts the daemons' idempotent replay
    machinery. Every forwarded run carries a request id (minted here
    when the client sent none, BEFORE the first dispatch), so when a
    replica dies mid-stream (EOF/reset before the terminal frame), or
    its own watchdog abandons the run, the SAME id is re-dispatched to
    the next distinct replica on the ring with any `chaos` block
    STRIPPED (faults fire once); the client sees one ack and one
    bit-identical result. The PR-5 shared assembly cache means the
    failover target warms from its dead sibling's builds.
  * degradation — a `draining`/`overloaded`/`circuit-open` refusal is
    not a fault: the router tries the next ring replica without
    penalizing the refuser, and only when EVERY routable replica
    refused does the client get one structured error carrying the
    MINIMUM `retry_after_sec` hint observed (the soonest any replica
    expects capacity). Replica faults feed per-replica circuit
    breakers (service/faults.py) so a flapping replica is excluded
    from the ring for a cool-off, and failover hops are spaced by
    jittered exponential backoff so retry storms never synchronize.
  * fleet health — replica liveness (crash/wedge/drain detection,
    restart with backoff) is `fleet.ReplicaSupervisor`'s job; the
    router only reads its `routable()` view per request.

Lock discipline: `_lock` guards the router counters and latency
histogram only; every `with self._lock:` block is tight (no IO, no
calls into fleet/breaker objects) so the static lock graph over the
service tier stays edge-free (tools/lint/threadcheck.py).
"""

import argparse
import hashlib
import json
import logging
import os
import socket
import sys
import threading
import time
import uuid
from bisect import bisect_right

from . import protocol
from .faults import CircuitBreaker
from .fleet import ReplicaSupervisor
from ..tools import tracing
from ..tools.lint.threadcheck import named_lock
from ..tools.resilience import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["RouterService", "ring_points", "ring_order", "route_digest",
           "build_parser", "main"]

# Refusals: the replica is healthy but won't take THIS request now.
# Failover continues without a breaker penalty; hints are aggregated.
_REFUSAL_CODES = frozenset({"draining", "overloaded", "circuit-open"})
# Replica faults: the replica broke while holding the run. Failover
# continues AND the replica's breaker records a failure.
_FAULT_CODES = frozenset({"watchdog-timeout", "internal"})


# ------------------------------------------------------------- hash ring

def route_digest(header):
    """The routing key for one run header: the canonical `spec_digest`
    when the spec normalizes (registry membership is the replica's
    business — the router must not import builders), else a digest of
    the raw spec text so malformed requests still route deterministically
    to SOME replica, whose structured `bad-spec` answer is relayed."""
    spec = header.get("spec")
    try:
        blob = json.dumps(protocol.normalize_spec(spec,
                                                  check_registry=False),
                          sort_keys=True).encode()
    except Exception:
        blob = json.dumps(spec, sort_keys=True, default=str).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def ring_points(names, vnodes=64):
    """The consistent-hash ring: `vnodes` points per replica, positioned
    by blake2b so membership changes only remap the leaving/joining
    replica's arcs. Returns sorted [(point, name), ...]."""
    points = []
    for name in names:
        for i in range(vnodes):
            token = hashlib.blake2b(f"{name}#{i}".encode(),
                                    digest_size=8).digest()
            points.append((int.from_bytes(token, "big"), name))
    points.sort()
    return points


def ring_order(points, digest):
    """Failover order for one routing key: the distinct replicas met
    walking the ring clockwise from the key's position. First entry is
    the primary (spec affinity); the rest are the replay targets."""
    if not points:
        return []
    key = int.from_bytes(hashlib.blake2b(str(digest).encode(),
                                         digest_size=8).digest(), "big")
    start = bisect_right(points, (key, "￿"))
    order = []
    seen = set()
    for offset in range(len(points)):
        name = points[(start + offset) % len(points)][1]
        if name not in seen:
            seen.add(name)
            order.append(name)
    return order


# ---------------------------------------------------------------- router

class RouterService:
    """The router daemon: accept loop + one reader thread per client
    connection, forwarding frames between the client and the chosen
    replica. Single-purpose by design — it never touches solver state,
    so a router restart loses nothing but open sockets."""

    def __init__(self, host="127.0.0.1", port=0, replicas=0, attach=(),
                 replica_args=(), workdir=None, vnodes=64,
                 probe_sec=1.0, probe_timeout=3.0, wedge_misses=4,
                 backoff_base=0.5, connect_timeout=5.0,
                 forward_timeout=600.0, breaker_failures=3,
                 breaker_cooloff=30.0, sink=None, fleet=None):
        self.host = host
        self.port = int(port)
        self.vnodes = max(int(vnodes), 1)
        self.connect_timeout = float(connect_timeout)
        self.forward_timeout = float(forward_timeout)
        self.sink = sink
        self.fleet = fleet if fleet is not None else ReplicaSupervisor(
            replicas=replicas, attach=attach, replica_args=replica_args,
            workdir=workdir, probe_sec=probe_sec,
            probe_timeout=probe_timeout, wedge_misses=wedge_misses,
            backoff_base=backoff_base)
        self.breaker = CircuitBreaker(failures=breaker_failures,
                                      cooloff_sec=breaker_cooloff)
        # failover hops are spaced by this schedule (jittered so
        # simultaneous failovers from many clients never synchronize)
        self.forward_retry = RetryPolicy(max_attempts=8, base_delay=0.1,
                                         max_delay=2.0, jitter=0.25)
        self._lock = named_lock("service/router.py:RouterService._lock")
        self.started = time.monotonic()
        self.forwarded = 0           # runs relayed to completion
        self.failovers = 0           # re-dispatches after a replica fault
        self.shed = 0                # runs refused fleet-wide
        self.refusals = 0            # per-replica refusals absorbed
        self.replica_faults = 0      # faults observed (EOF, watchdog, ...)
        self.client_drops = 0        # clients gone mid-relay
        self.acks_suppressed = 0     # duplicate acks hidden on failover
        self.error_codes = {}        # code -> count relayed/emitted
        self.hists = {"forward_seconds": tracing.LogHistogram()}
        self._listener = None
        self._draining = None
        self._shutdown = threading.Event()

    # ----------------------------------------------------------- serving

    def serve_forever(self, ready_stream=None):
        """Start the fleet, bind, print the ready banner, and serve
        until a `shutdown` frame arrives."""
        members = self.fleet.start()
        try:
            self._listener = socket.create_server((self.host, self.port))
            self._listener.settimeout(0.5)
            self.port = self._listener.getsockname()[1]
            if ready_stream is None:
                ready_stream = sys.stdout
            banner = {"kind": "ready", "role": "router",
                      "port": self.port, "pid": os.getpid(),
                      "replicas": members}
            print(json.dumps(banner), file=ready_stream, flush=True)
            logger.info(f"router: serving on {self.host}:{self.port} "
                        f"fronting {len(members)} replica(s)")
            while not self._shutdown.is_set():
                try:
                    conn, addr = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._serve_connection,
                                 args=(conn,), daemon=True).start()
        finally:
            if self._listener is not None:
                try:
                    self._listener.close()
                except OSError:
                    pass
            self.fleet.stop(shutdown_replicas=True)
            self._flush_stats()
            logger.info(f"router: stopped ({self._draining})")

    def request_drain(self, why="shutdown frame"):
        self._draining = why
        self._shutdown.set()

    def _flush_stats(self):
        """One `router_stats` record to the sink (and the log) at drain —
        after `fleet.stop`, so the record carries the FINAL restart /
        crash / wedge tallies of the fleet it supervised."""
        record = dict(self.stats(), kind="router_stats",
                      ts=round(time.time(), 1))
        if self.sink:
            from ..tools import metrics as metrics_mod
            metrics_mod.Metrics(sink=self.sink, enabled=True).emit(record)
        logger.info(f"router: final stats {json.dumps(record)}")

    def _serve_connection(self, conn):
        """One client connection: the router accepts the same one-shot
        frame kinds the daemon does and answers `run` by relaying."""
        try:
            conn.settimeout(self.forward_timeout)
            rfile = conn.makefile("rb")
            wfile = conn.makefile("wb")
            header = protocol.recv_header(rfile)
            if header is None:
                return
            payload = protocol.recv_payload(rfile, header)
            kind = header.get("kind")
            if kind == "ping":
                protocol.send_frame(wfile, {"kind": "pong",
                                            "role": "router"})
            elif kind == "stats":
                if header.get("prom"):
                    protocol.send_frame(
                        wfile, {"kind": "stats", "format": "prometheus"},
                        self.prom_text().encode("utf-8"))
                else:
                    protocol.send_frame(wfile, self.stats())
            elif kind == "shutdown":
                protocol.send_frame(wfile, {"kind": "ok",
                                            "role": "router"})
                self.request_drain()
            elif kind == "run":
                self._handle_run(wfile, header, payload)
            else:
                self._send_error(wfile, "unknown-kind",
                                 f"router does not handle {kind!r}")
        except (protocol.ProtocolError, OSError, ValueError) as exc:
            logger.debug(f"router: connection dropped: {exc}")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _send_error(self, wfile, code, message, **extra):
        with self._lock:
            self.error_codes[code] = self.error_codes.get(code, 0) + 1
        try:
            frame = {"kind": "error", "code": code, "message": message}
            frame.update(extra)
            protocol.send_frame(wfile, frame)
        except OSError:
            pass

    # -------------------------------------------------------- run routing

    def route_of(self, spec):
        """The primary replica a spec routes to right now (ops/debug
        surface, and what tests use to aim chaos at the right replica)."""
        order = self._order_for({"spec": spec})
        return order[0] if order else None

    def _order_for(self, header):
        members = self.fleet.routable()
        return ring_order(ring_points(sorted(members), self.vnodes),
                          route_digest(header))

    def _handle_run(self, wfile, header, payload):
        """Forward one run with failover. The request id is pinned
        BEFORE the first dispatch so every re-dispatch replays the same
        idempotent identity; chaos is stripped after attempt 1 so
        injected faults fire exactly once."""
        if self._draining:
            self._send_error(wfile, "draining",
                             f"router draining: {self._draining}",
                             retry_after_sec=5.0)
            return
        if not header.get("id"):
            header["id"] = uuid.uuid4().hex[:16]
        order = self._order_for(header)
        if not order:
            with self._lock:
                self.shed += 1
            self._send_error(
                wfile, "fleet-unavailable",
                "no routable replica (fleet down or fully draining)",
                retry_after_sec=self.fleet.probe_sec * 2
                + self.fleet.probe_timeout)
            return
        t0 = time.monotonic()
        hints = []
        attempt = 0
        relay = _RelayState()
        for name in order:
            allowed, retry_after, breaker_state = self.breaker.admit(name)
            if not allowed:
                hints.append(retry_after or 1.0)
                continue
            attempt += 1
            if attempt > 1:
                time.sleep(self.forward_retry.delay(attempt - 1))
            verdict, detail = self._relay_once(name, wfile, header,
                                               payload, attempt, relay)
            if verdict == "served":
                self.breaker.record_success(name)
                wall = time.monotonic() - t0
                with self._lock:
                    self.forwarded += 1
                    if attempt > 1:
                        self.failovers += 1
                    self.hists["forward_seconds"].add(wall)
                return
            if verdict == "client-error":
                # deterministic structured answer: the replica judged
                # the REQUEST, not itself — already relayed verbatim
                self.breaker.record_success(name)
                code = (detail or {}).get("code", "error")
                with self._lock:
                    self.error_codes[code] = (
                        self.error_codes.get(code, 0) + 1)
                return
            if verdict == "client-gone":
                with self._lock:
                    self.client_drops += 1
                return
            if verdict == "refused":
                if breaker_state == "probe":
                    self.breaker.abandon_probe(name)
                hints.append((detail or {}).get("retry_after_sec") or 1.0)
                with self._lock:
                    self.refusals += 1
                continue
            # verdict == "fault": penalize and fail over
            self.breaker.record_failure(name)
            with self._lock:
                self.replica_faults += 1
            logger.warning(f"router: replica {name} fault on request "
                           f"{header['id']} (attempt {attempt}): "
                           f"{detail}")
        if hints:
            with self._lock:
                self.shed += 1
            self._send_error(
                wfile, "overloaded",
                f"all {len(order)} routable replica(s) refused",
                retry_after_sec=round(min(hints), 3))
        else:
            with self._lock:
                self.shed += 1
            self._send_error(
                wfile, "fleet-unavailable",
                f"all {len(order)} routable replica(s) faulted",
                retry_after_sec=self.fleet.backoff_base * 2
                + self.fleet.probe_timeout)

    def _relay_once(self, name, wfile, header, payload, attempt, relay):
        """One forwarding attempt. Returns (verdict, detail) where
        verdict is `served` / `client-error` / `client-gone` /
        `refused` / `fault`."""
        endpoint = self.fleet.endpoint(name)
        if endpoint is None:
            return "fault", "replica vanished from the fleet"
        fwd = dict(header)
        if attempt > 1:
            fwd.pop("chaos", None)       # injected faults fire once
            fwd["failover"] = attempt - 1
        read_timeout = self.forward_timeout
        deadline = fwd.get("deadline_sec")
        if deadline:
            # a stalled replica must not pin the relay past the point
            # the run could still meet its deadline
            read_timeout = min(read_timeout, float(deadline) + 2.0)
        try:
            rconn = socket.create_connection(
                endpoint, timeout=self.connect_timeout)
        except OSError as exc:
            return "fault", f"connect {endpoint}: {exc}"
        try:
            rconn.settimeout(read_timeout)
            rr = rconn.makefile("rb")
            rw = rconn.makefile("wb")
            try:
                protocol.send_frame(rw, fwd, payload)
            except OSError as exc:
                return "fault", f"send: {exc}"
            while True:
                try:
                    frame, fpayload = protocol.recv_frame(rr)
                except (protocol.ProtocolError, OSError) as exc:
                    return "fault", f"stream: {exc}"
                if frame is None:
                    return "fault", "EOF before terminal frame"
                kind = frame.get("kind")
                if kind == "error":
                    code = frame.get("code")
                    if code in _REFUSAL_CODES:
                        return "refused", frame
                    if code in _FAULT_CODES:
                        return "fault", frame
                    if not self._to_client(wfile, frame, fpayload):
                        return "client-gone", None
                    return "client-error", frame
                if kind == "ack":
                    if relay.acked:
                        with self._lock:
                            self.acks_suppressed += 1
                        continue
                    relay.acked = True
                    frame["replica"] = name
                    if not self._to_client(wfile, frame, fpayload):
                        return "client-gone", None
                    continue
                if kind == "result":
                    frame["replica"] = name
                    if attempt > 1:
                        frame["failover"] = attempt - 1
                    if not self._to_client(wfile, frame, fpayload):
                        return "client-gone", None
                    return "served", frame
                # progress / telemetry / anything future: relay verbatim
                if not self._to_client(wfile, frame, fpayload):
                    return "client-gone", None
        finally:
            try:
                rconn.close()
            except OSError:
                pass

    @staticmethod
    def _to_client(wfile, frame, fpayload):
        try:
            protocol.send_frame(wfile, frame, fpayload)
            return True
        except OSError:
            return False

    # ------------------------------------------------------------- stats

    def stats(self):
        """The router/fleet stats frame (`kind: stats, role: router`)."""
        with self._lock:
            router = {"forwarded": self.forwarded,
                      "failovers": self.failovers,
                      "shed": self.shed,
                      "refusals": self.refusals,
                      "replica_faults": self.replica_faults,
                      "client_drops": self.client_drops,
                      "acks_suppressed": self.acks_suppressed,
                      "error_codes": dict(self.error_codes)}
            fwd_hist = self.hists["forward_seconds"]
            forward = {"p50_ms": round(
                fwd_hist.percentile(50) * 1e3, 3),
                "p95_ms": round(fwd_hist.percentile(95) * 1e3, 3),
                "count": fwd_hist.total}
        fleet_stats = self.fleet.stats()
        routable = self.fleet.routable()
        open_keys = self.breaker.stats().get("open") or []
        ring = [n for n in routable
                if not any(n == k or k.startswith(n) for k in open_keys)]
        return {"kind": "stats", "role": "router", "port": self.port,
                "uptime_sec": round(time.monotonic() - self.started, 3),
                "draining": self._draining,
                "router": dict(router, forward=forward,
                               ring_members=sorted(ring),
                               breaker=self.breaker.stats()),
                "fleet": fleet_stats}

    def prom_text(self):
        from . import promexport
        with self._lock:
            hist = self.hists["forward_seconds"]
            hists = {"router_forward_seconds":
                     ({"counts": dict(hist.counts), "total": hist.total,
                       "sum": hist.sum},
                      "Wall seconds per routed run, failover included.")}
        return promexport.render_router_stats(self.stats(), hists)


class _RelayState:
    """Per-request relay memory shared across failover attempts: the
    client must see exactly one ack no matter how many replicas touched
    the run."""

    __slots__ = ("acked",)

    def __init__(self):
        self.acked = False


# ------------------------------------------------------------------- CLI

def build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu route",
        description="Spec-hash router fronting a SolverService replica "
                    "fleet: consistent-hash routing on spec_digest, "
                    "health-checked failover, idempotent cross-replica "
                    "replay.")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="router port (0 = ephemeral, banner names it)")
    parser.add_argument("--replicas", type=int, default=0,
                        help="spawn N local replicas (serve --port 0)")
    parser.add_argument("--attach", default="",
                        help="adopt running replicas: host:port,host:port")
    parser.add_argument("--replica-arg", action="append", default=[],
                        dest="replica_args", metavar="ARG",
                        help="extra `serve` argv token for SPAWNED "
                             "replicas (repeat; option-like tokens need "
                             "the = form: --replica-arg=--pool-size "
                             "--replica-arg=4)")
    parser.add_argument("--workdir", default=None,
                        help="directory for replica sinks + stderr logs")
    parser.add_argument("--vnodes", type=int, default=64,
                        help="ring points per replica")
    parser.add_argument("--probe-sec", type=float, default=1.0,
                        help="health-probe cadence")
    parser.add_argument("--probe-timeout", type=float, default=3.0,
                        help="stats-frame probe timeout")
    parser.add_argument("--wedge-misses", type=int, default=4,
                        help="consecutive probe misses before a replica "
                             "is declared wedged (SIGKILL + restart)")
    parser.add_argument("--backoff-base", type=float, default=0.5,
                        help="restart backoff base (doubles per failure)")
    parser.add_argument("--connect-timeout", type=float, default=5.0)
    parser.add_argument("--forward-timeout", type=float, default=600.0,
                        help="per-forward read timeout")
    parser.add_argument("--breaker-failures", type=int, default=3,
                        help="consecutive faults opening a replica's "
                             "circuit")
    parser.add_argument("--breaker-cooloff", type=float, default=30.0)
    parser.add_argument("--sink", default=None,
                        help="telemetry sink for router stats records")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.replicas <= 0 and not args.attach:
        build_parser().error("need --replicas N and/or --attach "
                             "host:port,...")
    attach = [a for a in args.attach.split(",") if a.strip()]
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    router = RouterService(
        host=args.host, port=args.port, replicas=args.replicas,
        attach=attach, replica_args=args.replica_args,
        workdir=args.workdir, vnodes=args.vnodes,
        probe_sec=args.probe_sec, probe_timeout=args.probe_timeout,
        wedge_misses=args.wedge_misses, backoff_base=args.backoff_base,
        connect_timeout=args.connect_timeout,
        forward_timeout=args.forward_timeout,
        breaker_failures=args.breaker_failures,
        breaker_cooloff=args.breaker_cooloff, sink=args.sink)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        router.request_drain("SIGINT")
    return 0


if __name__ == "__main__":
    sys.exit(main())
