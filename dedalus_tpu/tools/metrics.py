"""
Step-loop metrics: named counters, phase timers, device-memory watermarks,
and a JSONL telemetry sink.

Async-dispatch awareness: JAX dispatch is asynchronous, so a host timer
around a dispatched computation measures enqueue latency, not device work,
unless the result is blocked on — and blocking every iteration serializes
the dispatch pipeline. Phase timers therefore bracket `block_until_ready`
only on sampled iterations (every `SAMPLE_CADENCE`-th step, config section
[profiling]); off-cadence iterations pay one counter bump and no device
sync. Sampled phase times are re-measurements of the already-compiled step
pieces on the current state (the solver supplies the thunks), so sampling
never perturbs the solution.

Naming scheme: phase timer names are the `jax.named_scope` labels on the
corresponding traced code, prefixed `dedalus/` — `dedalus/transform/...`,
`dedalus/matsolve/...`, `dedalus/transpose/...`, `dedalus/evaluator/...`,
`dedalus/step...`, `dedalus/health/...` (the numerical-health probe,
tools/health.py), `dedalus/adjoint/...` (the differentiable-solve
forward/loss scopes and grad dispatch annotations, core/adjoint.py) — so
per-phase wall aggregates in the JSONL record and op rows in a
`jax.profiler` trace share one vocabulary. Records flushed by a
DifferentiableIVP carry an `adjoint` sub-dict (grad_steps_per_sec,
checkpoint segments, grad/forward cost ratio, peak device memory) that
`report` renders as its own block.

Flush emits ONE record per call, shaped like `benchmarks/results.jsonl`
rows (flat JSON object, `ts` + `config`/`backend`/`dtype` keys) with the
phase breakdown attached; `python -m dedalus_tpu report <file.jsonl>`
summarizes the records.

Resilience vocabulary: the `resilience/...` counter scope carries the
recovery trajectory (rewinds, retries, dt_backoffs, snapshots,
io_retries, checkpoints_written/validated, resumes) plus the durability
and integrity columns added with the sharded tier —
`resilience/checkpoint_stall_sec` (cumulative wall the step loop was
held by durable checkpoint writes: the whole write for synchronous
formats, just the submit/overrun-barrier wait for async sharded ones),
`resilience/sdc_checks` / `resilience/sdc_detected` (silent-corruption
sentinel re-executions and caught mismatches). The flushed `resilience`
block mirrors them and adds a `checkpoint` sub-dict
(format/async/written/stall_sec/max_inflight/errors from the
dcheckpoint writer). Fleet records add `ensemble/reshards` and a
`reshards` field in the `ensemble` block — one per device-loss
re-sharding event (core/ensemble.py).

Served-latency vocabulary: records flushed by the warm-pool service
(dedalus_tpu/service/) carry a `serving` sub-dict —
`queue_sec` (accept -> dispatch wait), `pool_verdict`
("hit" | "warm-cache" | "cold": warm pool reuse / fresh build off the
persistent assembly cache / fully cold build), `time_to_first_step_sec`
(dispatch -> first step complete, including any build+compile a miss
pays), `build_sec`, `request_id`, and `deadline_sec` when the request
set one. Service-level fault-tolerance counters (shed, deadline
exceeded, watchdog fires, circuit-breaker opens/fast-fails, client
drops, idempotent replays, memory-watermark evictions) ride the `stats`
reply and the drain-time `service_stats` record under `faults`; a hung
dispatch additionally leaves a `watchdog_postmortem` record (request
id, stuck seconds, thread stacks). This sink format doubles as the
service's wire format, so streamed frames and the daemon's JSONL file
are the same records.

Trajectory vocabulary: every row appended through the bench driver or
the lint cost tier is stamped with an `env` host/environment fingerprint
(tools/envinfo.py: backend, device kind/count, jax/jaxlib/python
versions, hashed hostname, load average) so cross-host history is
attributable. `kind: ledger` rows (tools/lint/progcheck.py cost tier,
`lint --programs --ledger`) carry per-census-program compile-time
resource costs — flops, transcendentals, bytes accessed,
argument/output/temp/peak memory, HLO instruction count, scan depths —
plus the resolved-plan provenance block. `kind: probe` rows record TPU
backend-probe verdicts (bench.py) for TTL replay ([bench]
PROBE_CACHE_SEC). `python -m dedalus_tpu perfwatch` reads the whole
file as a perf trajectory and flags noise-band regressions per series
(docs/observability.md).
"""

import atexit
import json
import os
import signal
import threading
import time
import weakref

import numpy as np
import jax

from . import tracing
from .config import config
from .lint.threadcheck import named_lock

__all__ = ["PHASES", "SUM_PHASES", "BUILD_PHASES", "CadenceGate", "Counter",
           "PhaseTimer",
           "MemoryWatermark", "Metrics", "BuildPhases", "trace_scope",
           "annotate", "scoped", "resolve", "format_phase_table",
           "register_exit_flush", "flush_pending", "process_rss_bytes"]

# The hot-path phase vocabulary (shared with trace annotations).
# SUM_PHASES is the step DECOMPOSITION: rows that partition one step and
# should sum to ~the loop wall. The `fused` row (present when the fused
# step path is active, core/fusedstep.py) is an ALTERNATIVE whole-step
# attribution — the one-dispatch fused program re-measured end-to-end —
# that OVERLAPS the decomposition rows, so it is excluded from phase
# sums: `fused` below the decomposition sum is the fusion win (separate
# dispatches pay per-phase boundaries the fused program elides).
SUM_PHASES = ("transform", "matsolve", "transpose", "evaluator")
# `transpose_exposed` / `transpose_overlapped` split the distributed
# transpose wall of an OVERLAPPED chunked walk (parallel/transposes.py,
# [distributed] TRANSPOSE_CHUNKS): exposed = communication the step
# still waits on after chunking; overlapped = communication hidden
# under the interleaved chunk transforms. Like `fused`, they OVERLAP
# the `transpose` decomposition row (exposed + overlapped ~= the
# monolithic transpose wall), so they are excluded from phase sums —
# benchmarks/scaling.py measures and records them per device count.
PHASES = SUM_PHASES + ("fused", "transpose_exposed", "transpose_overlapped")

# The cold-start (build) phase vocabulary: host-side symbolic assembly,
# banded structural analysis, device transfer + factorization, and the
# first-dispatch trace/compile. Labels double as `dedalus/build/...`
# trace annotations so profiler rows and telemetry share one vocabulary.
BUILD_PHASES = ("host_assembly", "structure", "factor", "compile")


def trace_scope(phase, detail=None):
    """Named scope for traced code: labels the XLA ops compiled under it so
    profiler traces group by the same phase names the timers report."""
    name = f"dedalus/{phase}" + (f"/{detail}" if detail else "")
    return jax.named_scope(name)


def annotate(label, **kwargs):
    """Host-level profiler annotation (TraceMe row around a dispatch);
    near-free when no trace is being captured."""
    return jax.profiler.TraceAnnotation(label, **kwargs)


def scoped(fn, label):
    """Wrap a callable in a jax.named_scope so profiler traces label the
    ops it compiles with the shared phase vocabulary (the single helper
    behind the transform-plan and matsolver wrapping)."""
    def wrapper(*args, **kw):
        with jax.named_scope(label):
            return fn(*args, **kw)
    wrapper.__name__ = getattr(fn, "__name__", "scoped")
    return wrapper


class CadenceGate:
    """
    Consuming iteration-cadence gate: `due(iterations)` fires once per
    cadence crossing and advances the next due point past the observed
    count (a block of steps crossing several multiples fires once). The
    single gating primitive behind both the [profiling] phase sampler and
    the [health] probe, so the two subsystems cannot drift in semantics.
    """

    __slots__ = ("cadence", "_next_due")

    def __init__(self, cadence):
        self.cadence = int(cadence)
        self._next_due = max(self.cadence, 1)

    def reset(self, iterations=0):
        """Re-anchor: the next fire is one full cadence past `iterations`."""
        self._next_due = iterations + max(self.cadence, 1)

    def due(self, iterations):
        if self.cadence <= 0:
            return False
        if iterations >= self._next_due:
            self._next_due = iterations + self.cadence
            return True
        return False


class BuildPhases:
    """
    Wall-clock accounting of the solver BUILD (cold-start) phases, the
    setup-side sibling of the step-loop PhaseTimer: `scope(name)` brackets
    one phase (accumulating across re-entries, e.g. Newton rebuilds) and
    annotates the region `dedalus/build/<name>` for profiler traces.
    `record()` flattens to the `<name>_sec` keys telemetry records and
    bench rows carry (`host_assembly_sec`, `structure_sec`, `factor_sec`,
    `compile_sec`), plus the assembly-cache verdict.
    """

    def __init__(self):
        self.seconds = {}
        self.cache = "off"   # off | miss | hit

    class _Scope:
        def __init__(self, phases, name):
            self.phases = phases
            self.name = name

        def __enter__(self):
            self.ann = annotate(f"dedalus/build/{self.name}")
            self.ann.__enter__()
            # child span under the ambient trace (the server's
            # pool_acquire span when a cold build runs inside a request)
            self.span = tracing.span(f"build/{self.name}")
            self.span.__enter__()
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            dt = time.perf_counter() - self.t0
            sec = self.phases.seconds
            sec[self.name] = sec.get(self.name, 0.0) + dt
            self.span.__exit__(*exc)
            return self.ann.__exit__(*exc)

    def scope(self, name):
        return self._Scope(self, name)

    def add(self, name, seconds):
        self.seconds[name] = self.seconds.get(name, 0.0) + float(seconds)

    def record(self):
        out = {f"{name}_sec": round(self.seconds.get(name, 0.0), 4)
               for name in BUILD_PHASES}
        out["assembly_cache"] = self.cache
        return out


class Counter:
    """Named monotonic counter."""

    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        self.value += n
        return self.value


class PhaseTimer:
    """Accumulates sampled per-step seconds for each phase, plus a
    log-bucketed histogram per phase (tools/tracing.LogHistogram) so
    flushed records and the `report` CLI carry tail percentiles
    (p50/p95/p99), not just means — the tails are what a serving tier
    lives or dies by. The histogram feed is always on (one log + one
    dict bump per sample) regardless of whether tracing is enabled."""

    def __init__(self, phases=PHASES):
        self.totals = {p: 0.0 for p in phases}
        self.counts = {p: 0 for p in phases}
        self.hists = {}

    def add(self, phase, seconds):
        self.totals[phase] = self.totals.get(phase, 0.0) + float(seconds)
        self.counts[phase] = self.counts.get(phase, 0) + 1
        h = self.hists.get(phase)
        if h is None:
            h = self.hists[phase] = tracing.LogHistogram()
        h.add(seconds)

    def mean(self, phase):
        n = self.counts.get(phase, 0)
        return self.totals.get(phase, 0.0) / n if n else 0.0

    def percentiles(self, phase):
        """{p50, p95, p99} seconds for one phase, or None when the phase
        has no samples."""
        h = self.hists.get(phase)
        if h is None or not h.total:
            return None
        return {"p50": h.percentile(50), "p95": h.percentile(95),
                "p99": h.percentile(99)}

    @property
    def samples(self):
        return max(self.counts.values(), default=0)


def process_rss_bytes():
    """Resident-set size of THIS process in bytes (0 when unreadable).
    The device-side MemoryWatermark tracks accelerator allocations; this
    is its host-side sibling — the number the serving daemon's
    memory-watermark shedding ([service] MEM_WATERMARK_MB) compares
    against, since on CPU backends the pooled solvers' matrices and
    compiled programs all live in process RSS."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError, AttributeError):
        try:
            import resource
            import sys
            # ru_maxrss is KiB on Linux but BYTES on macOS (peak, not
            # current — still a usable over-estimate where /proc is
            # unavailable)
            scale = 1 if sys.platform == "darwin" else 1024
            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * scale
        except Exception:
            return 0


class MemoryWatermark:
    """Tracks peak device-memory use across samples. Prefers the backend's
    allocator stats (`device.memory_stats()`, available on TPU/GPU); falls
    back to summing live device arrays where the backend exposes no stats
    (CPU)."""

    def __init__(self):
        self.peak_bytes = 0
        self.source = None

    def sample(self):
        current = None
        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats:
                current = stats.get("peak_bytes_in_use",
                                    stats.get("bytes_in_use"))
                if current is not None:
                    self.source = "memory_stats"
        except Exception:
            current = None
        if current is None:
            try:
                current = sum(int(a.nbytes) for a in jax.live_arrays())
                self.source = "live_arrays"
            except Exception:
                return self.peak_bytes
        self.peak_bytes = max(self.peak_bytes, int(current))
        return self.peak_bytes


class Metrics:
    """
    Registry of counters, one phase timer, and a memory watermark, with
    cadence-gated sampling and a JSONL sink.

    Loop accounting: `observe_steps(n)` counts iterations and stamps the
    loop clock (the first call — or `reset_loop()`, which the solver calls
    at warmup end so compile time stays out of the window — anchors t0).
    `flush()` turns the sampled per-step phase means into loop-total
    estimates and appends one JSONL record to `sink` when set.
    """

    def __init__(self, sample_cadence=200, sink=None, enabled=True,
                 sampling=True, meta=None):
        self.enabled = bool(enabled)
        self.sampling = bool(sampling) and self.enabled
        # constructed intent, restored by reset_run(): the phase-sampling
        # firewall (_try_sample_phases) may flip `sampling` off mid-run
        self._sampling_default = self.sampling
        self.sample_cadence = int(sample_cadence)
        self.sink = str(sink) if sink else None
        self.meta = dict(meta or {})
        self.counters = {}
        self.timer = PhaseTimer()
        self.memory = MemoryWatermark()
        self.iterations = 0
        # unflushed-activity latch: set by step/counter observations,
        # cleared by flush() — the exit-flush hooks use it to decide
        # whether an interrupted run still owes a telemetry record
        self.dirty = False
        self._loop_t0 = None
        self._gate = CadenceGate(self.sample_cadence)
        self._warmed = set()

    def reset_run(self, meta=None):
        """Zero the per-run accounting (counters, phase samples, memory
        watermark, loop window, dirty latch) while keeping identity:
        sink, cadence, enabled flags, meta, and retrace-sentinel
        subscriptions all survive. The warm-pool service
        (dedalus_tpu/service/pool.py) calls this between requests so one
        Metrics instance per pooled solver serves many runs without one
        request's counters bleeding into the next record."""
        self.counters = {}
        self.timer = PhaseTimer()
        self.memory = MemoryWatermark()
        self.iterations = 0
        self.dirty = False
        self._loop_t0 = None
        self._gate.reset(0)
        self._warmed = set()
        # a probe failure's firewall disable (sampling=False) is per-run
        # state, not identity — the next request samples again
        self.sampling = self._sampling_default
        if meta:
            self.meta.update(meta)

    # ------------------------------------------------------------- counters

    def counter(self, name):
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def inc(self, name, n=1):
        if not self.enabled:
            return 0
        self.dirty = True
        return self.counter(name).inc(n)

    # ----------------------------------------------------------------- loop

    def observe_steps(self, n=1):
        """Count n completed steps (non-blocking; no device sync)."""
        if not self.enabled:
            return
        if self._loop_t0 is None:
            self._loop_t0 = time.perf_counter()
        self.iterations += int(n)
        self.dirty = True

    def reset_loop(self):
        """Re-anchor the loop window (called at warmup end so compile and
        ramp time stay out of the per-step accounting)."""
        self.iterations = 0
        self._loop_t0 = time.perf_counter()
        self._gate.reset(0)

    def loop_wall(self):
        if self._loop_t0 is None:
            return 0.0
        return time.perf_counter() - self._loop_t0

    # ------------------------------------------------------------- sampling

    def due(self):
        """Whether a phase sample is due at the current iteration count;
        consuming (the next due point advances by one cadence)."""
        if not self.sampling:
            return False
        return self._gate.due(self.iterations)

    def time_thunk(self, name, thunk):
        """Wall-time one thunk, bracketing `block_until_ready`. The first
        call per name runs untimed (jit compilation / cache warm)."""
        if name not in self._warmed:
            jax.block_until_ready(thunk())
            self._warmed.add(name)
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        return time.perf_counter() - t0

    def add_phase_sample(self, seconds_by_phase):
        """Record one sampled per-step attribution {phase: seconds}. With
        tracing enabled each measurement also lands as a `phase/<name>`
        span under the ambient trace (the request's `run` span when the
        sample fires inside a served step loop)."""
        for phase, sec in seconds_by_phase.items():
            self.timer.add(phase, sec)
            if tracing.enabled():
                tracing.add_span(f"phase/{phase}", sec)
        self.inc("phase_samples")
        self.memory.sample()

    # ---------------------------------------------------------------- flush

    def emit(self, record):
        """Append one arbitrary record to the configured JSONL sink — the
        shared telemetry channel used by flush() step records and the
        health monitor's post-mortem records. Returns the record (with a
        `ts` stamped when missing), or None when disabled or sinkless."""
        if not (self.enabled and self.sink):
            return None
        record = dict(record)
        record.setdefault("ts", round(time.time(), 1))

        def write():
            parent = os.path.dirname(os.path.abspath(self.sink))
            os.makedirs(parent, exist_ok=True)
            with open(self.sink, "a") as f:
                f.write(json.dumps(record) + "\n")

        # transient host/IO faults (flaky disk/NFS) are retried with
        # backoff under the [resilience] IO_RETRIES/IO_BASE_DELAY budget
        # (tools/resilience.io_retry_policy classification); a
        # persistently failing sink degrades to a warning — telemetry
        # must never kill the simulation
        try:
            from .resilience import io_retry_policy
            io_retry_policy().call(
                write, label=f"metrics sink {self.sink}")
        except OSError as exc:
            import logging
            logging.getLogger(__name__).warning(
                f"metrics sink {self.sink}: {exc}")
        return record

    def flush(self, extra=None):
        """Build one telemetry record (and append it to the JSONL sink when
        configured). Callers should block on outstanding device work first
        (the solver's `flush_metrics` does) so the loop wall time covers
        the device tail of the final dispatch."""
        if not self.enabled:
            return None
        self.memory.sample()
        wall = self.loop_wall()
        iters = self.iterations
        phase_mean = {p: self.timer.mean(p) for p in PHASES}
        phase_total = {p: phase_mean[p] * iters for p in PHASES}
        phase_pct = {}
        for p in PHASES:
            pct = self.timer.percentiles(p)
            if pct:
                phase_pct[p] = {k: round(v, 6) for k, v in pct.items()}
        # the fused whole-step row overlaps the decomposition rows (see
        # the PHASES note): only the decomposition enters the sum
        phase_sum = sum(phase_total[p] for p in SUM_PHASES)
        record = {
            "kind": "step_metrics",
            "ts": round(time.time(), 1),
            "iterations": iters,
            "loop_wall_sec": round(wall, 6),
            "steps_per_sec": round(iters / wall, 4) if wall > 0 else 0.0,
            "sample_cadence": self.sample_cadence,
            "phase_samples": self.timer.samples,
            "phase_mean_sec": {p: round(v, 6) for p, v in phase_mean.items()},
            "phase_pct_sec": phase_pct,
            "phase_total_sec": {p: round(v, 6) for p, v in phase_total.items()},
            "phase_sum_frac": round(phase_sum / wall, 4) if wall > 0 else 0.0,
            "device_mem_peak_bytes": self.memory.peak_bytes,
            "mem_source": self.memory.source,
            "counters": {name: c.value for name, c in self.counters.items()},
        }
        record.update(self.meta)
        if extra:
            record.update(extra)
        self.emit(record)
        self.dirty = False
        return record


# --------------------------------------------------- abnormal-exit flush
#
# A run killed by an exception or a termination signal should still leave
# a complete results.jsonl record. Solvers register themselves here; the
# atexit hook (and, for SIGTERM/SIGINT — SIGTERM's default action skips
# atexit entirely, and a Ctrl-C KeyboardInterrupt swallowed by broad
# except clauses can exit without ever re-raising — chaining signal
# hooks) flushes any registered solver whose metrics have unflushed
# activity and a configured sink. Each signal is only hooked while its
# DEFAULT disposition is in place (SIG_DFL for SIGTERM, the
# KeyboardInterrupt-raising default_int_handler for SIGINT), so a user-
# or ResilientLoop- or service-installed handler is never stomped; after
# flushing, the previous disposition is restored and the signal
# re-delivered, preserving the original exit semantics.

_exit_solvers = []          # weakrefs to registered solvers
_signal_previous = {}       # {signum: previous handler} once installed
_exit_lock = named_lock("tools/metrics.py:_exit_lock")


def flush_pending(source="atexit"):
    """Flush every registered solver with unflushed activity and a JSONL
    sink. Best-effort: one failing flush never blocks the others."""
    for ref in list(_exit_solvers):
        solver = ref()
        if solver is None:
            continue
        m = getattr(solver, "metrics", None)
        if m is None or not (m.enabled and m.sink and m.dirty):
            continue
        try:
            solver.flush_metrics(extra={"flush_source": source})
        except Exception:
            pass


def _signal_flush(signum, frame):
    """Chaining SIGTERM/SIGINT hook: restore the previous disposition,
    flush, and re-deliver so the process still terminates with the
    original signal semantics (exit code / KeyboardInterrupt, parent
    observation). The restore comes FIRST on purpose: the flush blocks
    on in-flight device work (flush_metrics syncs the state, and an XLA
    compile can hold it for tens of seconds), so a SECOND Ctrl-C during
    the flush must get default semantics — an immediate
    KeyboardInterrupt escape that abandons the telemetry — instead of
    re-entering this handler and blocking again."""
    previous = _signal_previous.get(signum, signal.SIG_DFL)
    try:
        signal.signal(signum, previous)
        restored = True
    except (ValueError, OSError):
        restored = False
    flush_pending(source=f"signal:{signum}")
    if restored:
        os.kill(os.getpid(), signum)


# per-signal "still the default?" test: SIGINT's default disposition in
# CPython is the KeyboardInterrupt-raising default_int_handler, not
# SIG_DFL, so an == SIG_DFL check would never hook Ctrl-C
_HOOKABLE_DEFAULTS = {
    signal.SIGTERM: (signal.SIG_DFL,),
    signal.SIGINT: (signal.SIG_DFL, signal.default_int_handler),
}


def register_exit_flush(solver):
    """Register a solver for the abnormal-exit telemetry flush (atexit +
    SIGTERM + SIGINT). Idempotent per solver; each signal hook is
    installed once, and only where that signal's default disposition is
    still in place (a user- or ResilientLoop- or service-installed
    handler is never stomped)."""
    with _exit_lock:
        if not any(ref() is solver for ref in _exit_solvers):
            _exit_solvers.append(weakref.ref(solver))
        _exit_solvers[:] = [ref for ref in _exit_solvers
                            if ref() is not None]
        for signum, defaults in _HOOKABLE_DEFAULTS.items():
            if signum in _signal_previous:
                continue
            try:
                current = signal.getsignal(signum)
                if current in defaults:
                    _signal_previous[signum] = current
                    signal.signal(signum, _signal_flush)
            except (ValueError, OSError):
                pass   # non-main thread / unsupported platform


atexit.register(flush_pending)


def resolve(spec=None, sink=None, cadence=None, meta=None):
    """
    Resolve a solver's `metrics` argument against the [profiling] config:
    a Metrics instance passes through (meta keys are merged in); True/None
    build from config (None respects METRICS_DEFAULT, True forces on);
    False disables.
    """
    if isinstance(spec, Metrics):
        for key, val in (meta or {}).items():
            spec.meta.setdefault(key, val)
        return spec
    section = config["profiling"]
    if spec is None:
        enabled = section.getboolean("METRICS_DEFAULT", fallback=True)
    else:
        enabled = bool(spec)
    if cadence is None:
        cadence = int(section.get("SAMPLE_CADENCE", "200") or 200)
    if sink is None:
        sink = section.get("METRICS_FILE", "").strip() or None
    return Metrics(sample_cadence=cadence, sink=sink, enabled=enabled,
                   meta=meta)


def format_phase_table(record, indent="  "):
    """Render a flushed record's phase breakdown as aligned text lines
    (used by `log_stats` and the `report` CLI)."""
    if not record:
        return []
    wall = record.get("loop_wall_sec") or 0.0
    iters = record.get("iterations") or 0
    total = record.get("phase_total_sec") or {}
    mean = record.get("phase_mean_sec") or {}
    pct = record.get("phase_pct_sec") or {}
    lines = [f"Per-phase wall time ({record.get('phase_samples', 0)} samples,"
             f" cadence {record.get('sample_cadence', '?')}):"]
    for phase in SUM_PHASES:
        t = total.get(phase, 0.0)
        frac = 100.0 * t / wall if wall > 0 else 0.0
        line = (f"{indent}{phase:<10} {mean.get(phase, 0.0):#.4g} s/step"
                f"  {t:#.4g} s total  {frac:5.1f}%")
        p = pct.get(phase)
        if p:
            # tail columns from the log-bucketed sample histogram —
            # absent on records flushed before the percentile tier
            line += (f"  p50/p95/p99 {p.get('p50', 0.0):#.3g}"
                     f"/{p.get('p95', 0.0):#.3g}"
                     f"/{p.get('p99', 0.0):#.3g} s")
        lines.append(line)
    psum = sum(total.get(p, 0.0) for p in SUM_PHASES)
    frac = 100.0 * psum / wall if wall > 0 else 0.0
    lines.append(f"{indent}{'sum':<10} {psum:#.4g} s of {wall:#.4g} s loop"
                 f" wall ({frac:.1f}%), {iters} iterations")
    if total.get("fused"):
        # whole-step fused-program re-measurement (overlaps the rows
        # above; core/fusedstep.py) — below the sum when fusion wins
        lines.append(
            f"{indent}{'fused':<10} {mean.get('fused', 0.0):#.4g} s/step"
            f"  (whole fused step program; overlaps the split rows, "
            f"excluded from sum)")
    if total.get("transpose_exposed") or total.get("transpose_overlapped"):
        # overlapped-chunked-walk split of the transpose wall
        # (parallel/transposes.py): exposed = still waited on,
        # overlapped = hidden under the interleaved chunk transforms
        exp = total.get("transpose_exposed", 0.0)
        ovl = total.get("transpose_overlapped", 0.0)
        tot = exp + ovl
        pct = 100.0 * ovl / tot if tot > 0 else 0.0
        lines.append(
            f"{indent}{'transpose':<10} exposed {exp:#.4g} s / overlapped "
            f"{ovl:#.4g} s ({pct:.0f}% hidden; overlaps the transpose "
            f"row, excluded from sum)")
    mem = record.get("device_mem_peak_bytes")
    if mem:
        lines.append(f"{indent}device memory peak: {mem / 1e9:.3f} GB"
                     f" ({record.get('mem_source')})")
    return lines
