"""
Framework exception types (reference: dedalus/tools/exceptions.py).
"""


class DedalusError(Exception):
    """Base class for framework errors."""


class NonlinearOperatorError(DedalusError):
    """Raised when a linear path receives a nonlinear operator."""


class UndefinedParityError(DedalusError):
    """Raised for operations with undefined parity."""


class SymbolicParsingError(DedalusError):
    """Raised when an equation string cannot be parsed."""


class UnsupportedEquationError(DedalusError):
    """Raised when an equation is structurally unsupported."""


class SkipDispatchException(Exception):
    """Control-flow exception to bypass multiclass dispatch with an output."""

    def __init__(self, output):
        self.output = output
        super().__init__()
