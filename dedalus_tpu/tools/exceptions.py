"""
Framework exception types (reference: dedalus/tools/exceptions.py).
"""


class DedalusError(Exception):
    """Base class for framework errors."""


class NonlinearOperatorError(DedalusError):
    """Raised when a linear path receives a nonlinear operator."""


class UndefinedParityError(DedalusError):
    """Raised for operations with undefined parity."""


class SymbolicParsingError(DedalusError):
    """Raised when an equation string cannot be parsed."""


class UnsupportedEquationError(DedalusError):
    """Raised when an equation is structurally unsupported."""


class SolverHealthError(DedalusError, ValueError):
    """
    Structured numerical-health failure of a timestepping run (non-finite
    state, growth-bound violation, or a non-finite timestep): carries the
    failure context so post-mortems need no rerun. Subclasses ValueError so
    callers that guarded the historical bare `raise ValueError("Invalid
    timestep.")` keep working.

    Attributes: reason (str), iteration (int), sim_time (float), record
    (the triggering health-probe record, when one exists), postmortem_dir
    (path of the flight-recorder dump, when one was written).
    """

    def __init__(self, reason, iteration=None, sim_time=None, record=None,
                 postmortem_dir=None):
        self.reason = reason
        self.iteration = iteration
        self.sim_time = sim_time
        self.record = record
        self.postmortem_dir = postmortem_dir
        super().__init__(reason)


class SilentCorruptionError(SolverHealthError):
    """
    Silent data corruption detected by the SDC sentinel
    (tools/resilience.py, [resilience] SDC_CADENCE): a redundant
    re-execution of the last step from the anchor snapshot did not
    reproduce the live state bit-for-bit. Unlike a NaN/growth failure
    the corrupted state is still *plausible* — nothing downstream would
    have noticed — which is exactly why detection has its own error
    type: recovery must rewind without a dt backoff (the numerics are
    fine; the bits are not).

    Extra attributes: mismatched (element count that differed),
    anchor_iteration (the trusted snapshot the re-execution ran from).
    """

    def __init__(self, reason, mismatched=None, anchor_iteration=None,
                 **kwargs):
        self.mismatched = mismatched
        self.anchor_iteration = anchor_iteration
        super().__init__(reason, **kwargs)


class CheckpointError(DedalusError, OSError):
    """
    Structured checkpoint load/validation failure: names the file and the
    write index that failed (and the underlying cause) instead of leaking
    a raw h5py traceback. Subclasses OSError so callers that guarded the
    historical h5py `OSError` keep working.

    Attributes: path (str), index (write index attempted, or None for a
    file-level failure).
    """

    def __init__(self, message, path=None, index=None):
        self.path = str(path) if path is not None else None
        self.index = index
        super().__init__(message)


class SkipDispatchException(Exception):
    """Control-flow exception to bypass multiclass dispatch with an output."""

    def __init__(self, output):
        self.output = output
        super().__init__()
