"""
Multiclass constructor dispatch (reference: dedalus/tools/dispatch.py:10-62).

`MultiClass` lets a parent class (e.g. Gradient) dispatch construction to the
matching subclass (CartesianGradient vs SphericalGradient) via each subclass's
`_check_args` classmethod. A subclass's `_preprocess_args` may rewrite the
call; raising `SkipDispatchException(output)` short-circuits with `output`.
"""

from .exceptions import SkipDispatchException


class MultiClass(type):

    def __call__(cls, *args, **kw):
        # Direct instantiation of a leaf class.
        if not cls.__dict__.get("_dispatching", True):
            return super().__call__(*args, **kw)
        try:
            args, kw = cls._preprocess_args(*args, **kw)
        except SkipDispatchException as skip:
            return skip.output
        except AttributeError:
            pass
        # Find matching subclass (depth-first over subclass tree).
        for subclass in cls._walk_subclasses():
            if subclass.__dict__.get("_abstract", False):
                continue
            check = subclass.__dict__.get("_check_args")
            if check is not None and check.__func__(subclass, *args, **kw):
                return type.__call__(subclass, *args, **kw)
        # No subclass matched: instantiate cls itself if concrete.
        if cls.__dict__.get("_check_args") is None and not cls.__dict__.get("_abstract", False):
            return type.__call__(cls, *args, **kw)
        raise NotImplementedError(
            f"No subclass of {cls.__name__} supports the given arguments: {args} {kw}")

    def _walk_subclasses(cls):
        for sub in cls.__subclasses__():
            yield from sub._walk_subclasses()
            yield sub
