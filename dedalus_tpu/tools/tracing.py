"""
End-to-end request tracing: bounded ring-buffered spans, log-bucketed
latency histograms, and Chrome trace-event export.

One TRACE per served request (or per run, when enabled outside the
daemon): a tree of `Span` records — (trace_id, span_id, parent_id, name,
wall interval, attrs) — linking the full lifecycle the repo's serving
tier composes per request:

    request                         root (server/_receive)
      accept                        header+payload read off the socket
      admission                     queue-slot + breaker verdict
      queue                         accept -> worker dispatch wait
      pool_acquire                  warm-pool verdict (hit | warm-cache
        build/host_assembly           | cold); cold builds carry the
        build/structure               BuildPhases child spans
        build/factor
        build/compile
      batch/seat  batch/join        continuous-batching membership
      batch/block                   one fixed-size block of fused steps
      batch/boundary                the per-block probe sync
      run                           the solo ResilientLoop execution
      phase/<name>                  sampled step-phase re-measurements
      checkpoint/write              durable checkpoint stall intervals
      checkpoint/submit             async sharded submit + overrun wait
      result_send                   record + result frames on the wire
      error                         terminal error frame (code attr)

Spans are recorded HOST-SIDE ONLY — never inside jit-traced code — so
tracing changes no compiled program: with tracing disabled the step HLO
is bit-identical (machine-checked by the progcheck `traced_step` census
program + DTP107), and with tracing enabled the cost is a few host
timestamps per request boundary. The `span()` fast path when disabled
is a shared no-op context manager: zero allocation, zero branches
inside traced code, nothing registered anywhere.

Cross-thread propagation: the server's reader thread opens the trace,
the worker thread resumes it (`resume(ctx)` pushes the context onto the
resuming thread's stack), and the batcher stamps per-member child spans
against each member's context explicitly — so one request's spans share
one trace_id across threads. When a span opens while tracing is enabled
it also enters a `jax.profiler.TraceAnnotation("dedalus/<name>",
trace_id=...)`, so XLA profiler rows align with serving spans and carry
the request's trace id.

Export: `chrome_trace(spans)` produces Chrome trace-event JSON ("X"
complete events, microsecond ts/dur) loadable in Perfetto or
`chrome://tracing`; `flush_trace(trace_id)` pops one finished trace
from the ring and appends a single `{"kind": "trace", ...}` record to
the configured JSONL sink (the same stream the metrics records ride),
which `python -m dedalus_tpu trace` dumps, converts, or summarizes.

Config ([tracing]): TRACE_DEFAULT (off), RING_SPANS (ring capacity),
TRACE_FILE (default JSONL sink when enabled without an explicit one).
"""

import json
import math
import os
import threading
import time
import uuid

from .config import config
from .lint.threadcheck import named_lock

__all__ = ["Span", "LogHistogram", "TraceRecorder", "TraceContext",
           "enabled", "enable", "disable", "trace_sink", "recorder",
           "new_trace",
           "span", "resume", "add_span", "current_context",
           "chrome_trace_events", "chrome_trace", "trace_record",
           "flush_trace", "load_trace_records", "summarize_trace",
           "format_trace_tree"]


# --------------------------------------------------------------- histogram

# Bucket boundaries grow geometrically by 2**(1/4) per bucket (~19%/bucket,
# <10% worst-case midpoint error on percentile extraction), floored at 1 ns
# so degenerate zero/negative samples land in bucket 0.
_LOG_BASE = 2.0 ** 0.25
_LOG_FLOOR = 1e-9
_INV_LOG_BASE = 1.0 / math.log(_LOG_BASE)


class LogHistogram:
    """Log-bucketed latency histogram: O(1) `add`, tail percentiles by
    cumulative bucket walk with geometric-midpoint interpolation. The
    always-on accumulator behind the PhaseTimer's p50/p95/p99 columns —
    cheap enough (one log + one dict bump) to feed on every sampled
    phase measurement regardless of whether tracing is enabled."""

    __slots__ = ("counts", "total", "sum", "min", "max")

    def __init__(self):
        self.counts = {}
        self.total = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def _bucket(self, seconds):
        if seconds <= _LOG_FLOOR:
            return 0
        return 1 + int(math.log(seconds / _LOG_FLOOR) * _INV_LOG_BASE)

    def add(self, seconds):
        seconds = float(seconds)
        b = self._bucket(seconds)
        self.counts[b] = self.counts.get(b, 0) + 1
        self.total += 1
        self.sum += seconds
        self.min = seconds if self.min is None else min(self.min, seconds)
        self.max = seconds if self.max is None else max(self.max, seconds)

    def percentile(self, q):
        """q in [0, 100]. Geometric bucket midpoint, clamped to the
        observed min/max so small-sample percentiles never exceed the
        data range."""
        if not self.total:
            return 0.0
        rank = q / 100.0 * self.total
        seen = 0
        for b in sorted(self.counts):
            seen += self.counts[b]
            if seen >= rank:
                if b == 0:
                    value = _LOG_FLOOR
                else:
                    # geometric midpoint of [floor*base^(b-1), floor*base^b]
                    value = _LOG_FLOOR * _LOG_BASE ** (b - 0.5)
                return min(max(value, self.min), self.max)
        return self.max

    def summary(self):
        return {"count": self.total,
                "p50": self.percentile(50),
                "p95": self.percentile(95),
                "p99": self.percentile(99)}


# -------------------------------------------------------------------- spans

class Span:
    """One closed wall-clock interval in a trace tree. `t0` is an epoch
    timestamp (time.time domain) so spans from different processes and
    threads order on a shared axis; `dur` is measured with perf_counter
    deltas where possible."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "dur",
                 "attrs", "tid")

    def __init__(self, trace_id, span_id, parent_id, name, t0, dur,
                 attrs=None, tid=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.dur = dur
        self.attrs = attrs or {}
        self.tid = tid if tid is not None else threading.get_ident()

    def to_dict(self):
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "t0": round(self.t0, 6), "dur_sec": round(self.dur, 6),
             "tid": self.tid}
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class TraceRecorder:
    """Process-wide bounded span ring. Thread-safe; spans beyond the ring
    capacity evict oldest-first, so a leaked trace can never grow host
    memory unboundedly. `pop_trace` removes and returns one finished
    trace's spans (flush-once semantics for the JSONL sink)."""

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = int(config.get("tracing", "RING_SPANS",
                                      fallback="4096") or 4096)
        self.capacity = max(int(capacity), 16)
        self._spans = []
        self._lock = named_lock("tools/tracing.py:TraceRecorder._lock")
        self._next_id = 0

    def next_span_id(self):
        with self._lock:
            self._next_id += 1
            return self._next_id

    def record(self, s):
        with self._lock:
            self._spans.append(s)
            if len(self._spans) > self.capacity:
                del self._spans[:len(self._spans) - self.capacity]

    def spans(self, trace_id=None):
        with self._lock:
            if trace_id is None:
                return list(self._spans)
            return [s for s in self._spans if s.trace_id == trace_id]

    def pop_trace(self, trace_id):
        with self._lock:
            mine = [s for s in self._spans if s.trace_id == trace_id]
            if mine:
                self._spans = [s for s in self._spans
                               if s.trace_id != trace_id]
            return mine

    def clear(self):
        with self._lock:
            self._spans = []


_recorder = None
_recorder_lock = named_lock("tools/tracing.py:_recorder_lock")


def recorder():
    """The process-wide span recorder (lazily constructed)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = TraceRecorder()
    return _recorder


# ----------------------------------------------------------- enable/disable

_enabled = config.getboolean("tracing", "TRACE_DEFAULT", fallback=False)
_sink = (config.get("tracing", "TRACE_FILE", fallback="").strip() or None)


def enabled():
    return _enabled


def enable(sink=None):
    """Turn tracing on process-wide. `sink` (a JSONL path) sets where
    `flush_trace` appends trace records; None keeps the configured
    [tracing] TRACE_FILE (or leaves traces in the ring only)."""
    global _enabled, _sink
    _enabled = True
    if sink is not None:
        _sink = str(sink)
    return recorder()


def disable():
    global _enabled
    _enabled = False


def trace_sink():
    """The configured trace-record JSONL path (None when unset)."""
    return _sink


# ----------------------------------------------------- thread-local context

_tls = threading.local()


def _stack():
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_context():
    """(trace_id, span_id) of the innermost open span on THIS thread, or
    None when no trace is active here."""
    stack = _stack()
    return stack[-1] if stack else None


class TraceContext:
    """One trace's identity: a fresh trace_id plus a pre-allocated root
    span id, so child spans recorded before the root CLOSES (it closes
    last, when the request finishes) still parent correctly. Pass the
    context across threads and stamp children with `resume(ctx)` or
    `parent=ctx`; call `finish(**attrs)` exactly once to record the
    root span."""

    __slots__ = ("trace_id", "root_id", "name", "t0", "_t0_perf", "attrs",
                 "_done")

    def __init__(self, name, attrs=None):
        self.trace_id = uuid.uuid4().hex[:16]
        self.root_id = recorder().next_span_id()
        self.name = name
        self.t0 = time.time()
        self._t0_perf = time.perf_counter()
        self.attrs = dict(attrs or {})
        self._done = False

    def finish(self, **attrs):
        """Record the root span (idempotent). Returns it (or None when
        tracing got disabled mid-request)."""
        if self._done:
            return None
        self._done = True
        if not _enabled:
            return None
        self.attrs.update(attrs)
        s = Span(self.trace_id, self.root_id, None, self.name, self.t0,
                 time.perf_counter() - self._t0_perf, attrs=self.attrs)
        recorder().record(s)
        return s


def new_trace(name, attrs=None):
    """Open a new trace (returns a TraceContext, or None when tracing is
    off — callers thread the None through untouched; every consumer here
    tolerates it)."""
    if not _enabled:
        return None
    return TraceContext(name, attrs)


def _parent_ids(parent):
    """Resolve an explicit parent (TraceContext | Span | (trace, span)
    tuple | None) or fall back to the thread-local stack."""
    if parent is not None:
        if isinstance(parent, TraceContext):
            return parent.trace_id, parent.root_id
        if isinstance(parent, Span):
            return parent.trace_id, parent.span_id
        return parent  # (trace_id, span_id)
    return current_context() or (None, None)


class _NoopSpan:
    """Shared do-nothing context manager: the `span()` fast path when
    tracing is disabled (no allocation per call)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("name", "attrs", "_parent", "trace_id", "span_id",
                 "_t0", "_t0_perf", "_ann", "_pushed")

    def __init__(self, name, attrs, parent):
        self.name = name
        self.attrs = dict(attrs or {})
        self._parent = parent
        self.trace_id = None
        self.span_id = None
        self._ann = None
        self._pushed = False

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        trace_id, parent_id = _parent_ids(self._parent)
        if trace_id is None:
            # no ambient trace: each orphan span becomes its own
            # single-span trace so nothing recorded is ever unlinked
            trace_id = uuid.uuid4().hex[:16]
            parent_id = None
        self.trace_id = trace_id
        self._parent = parent_id
        self.span_id = recorder().next_span_id()
        _stack().append((trace_id, self.span_id))
        self._pushed = True
        try:
            import jax
            self._ann = jax.profiler.TraceAnnotation(
                f"dedalus/{self.name}", trace_id=trace_id)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        self._t0 = time.time()
        self._t0_perf = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0_perf
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        if self._pushed:
            stack = _stack()
            if stack and stack[-1] == (self.trace_id, self.span_id):
                stack.pop()
            elif stack:
                try:
                    stack.remove((self.trace_id, self.span_id))
                except ValueError:
                    pass
        if _enabled:
            recorder().record(Span(self.trace_id, self.span_id,
                                   self._parent, self.name, self._t0, dur,
                                   attrs=self.attrs))
        return False


def span(name, attrs=None, parent=None):
    """Context manager recording one span around the `with` body. Parent
    resolution: explicit `parent` (TraceContext / Span / (trace, span)
    pair) > this thread's innermost open span > a fresh one-span trace.
    When tracing is off, returns a shared no-op (zero per-call cost)."""
    if not _enabled:
        return _NOOP
    return _LiveSpan(name, attrs, parent)


class _Resume:
    __slots__ = ("_ids", "_pushed")

    def __init__(self, ids):
        self._ids = ids
        self._pushed = False

    def __enter__(self):
        if self._ids is not None:
            _stack().append(self._ids)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            stack = _stack()
            if stack and stack[-1] == self._ids:
                stack.pop()
            elif stack:
                try:
                    stack.remove(self._ids)
                except ValueError:
                    pass
        return False


def resume(ctx):
    """Adopt a trace context on THIS thread: spans opened inside the
    `with` body parent under `ctx` (a TraceContext, Span, or (trace_id,
    span_id) pair; None is a no-op, so the off path threads through)."""
    if ctx is None or not _enabled:
        return _Resume(None)
    return _Resume(_parent_ids(ctx))


def add_span(name, dur, parent=None, end=None, attrs=None):
    """Record one already-measured interval after the fact (the accept
    and queue waits are measured before their trace exists on the
    current thread). `end` is the interval's epoch end time (defaults
    to now); t0 is reconstructed as end - dur."""
    if not _enabled:
        return None
    trace_id, parent_id = _parent_ids(parent)
    if trace_id is None:
        trace_id, parent_id = uuid.uuid4().hex[:16], None
    end = time.time() if end is None else end
    s = Span(trace_id, recorder().next_span_id(), parent_id, name,
             end - float(dur), float(dur), attrs=dict(attrs or {}))
    recorder().record(s)
    return s


# ------------------------------------------------------------------- export

def chrome_trace_events(spans):
    """Chrome trace-event list: one "X" (complete) event per span,
    microsecond timestamps, span identity and attrs in `args`."""
    pid = os.getpid()
    events = []
    for s in spans:
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        events.append({"name": s.name, "ph": "X", "cat": "dedalus",
                       "ts": round(s.t0 * 1e6, 3),
                       "dur": round(s.dur * 1e6, 3),
                       "pid": pid, "tid": s.tid, "args": args})
    return events


def chrome_trace(spans):
    """Full Chrome trace-event JSON object (loads in Perfetto /
    chrome://tracing)."""
    return {"traceEvents": chrome_trace_events(spans),
            "displayTimeUnit": "ms"}


def chrome_trace_from_records(records):
    """Chrome trace-event JSON built back from flushed trace records
    (dict-shaped spans, `python -m dedalus_tpu trace --chrome`)."""
    pid = os.getpid()
    events = []
    for rec in records:
        for s in _span_dicts(rec):
            args = {"trace_id": s.get("trace_id"),
                    "span_id": s.get("span_id")}
            if s.get("parent_id") is not None:
                args["parent_id"] = s["parent_id"]
            args.update(s.get("attrs") or {})
            events.append({"name": s.get("name", "?"), "ph": "X",
                           "cat": "dedalus",
                           "ts": round(s.get("t0", 0.0) * 1e6, 3),
                           "dur": round(s.get("dur_sec", 0.0) * 1e6, 3),
                           "pid": pid, "tid": s.get("tid", 0),
                           "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_record(trace_id, spans, **extra):
    """One structured JSONL record holding a whole trace (the shape the
    metrics sink carries and `python -m dedalus_tpu trace` reads)."""
    record = {"kind": "trace", "trace_id": trace_id,
              "ts": round(time.time(), 1),
              "spans": [s.to_dict() for s in spans]}
    record.update(extra)
    return record


def flush_trace(trace_id, sink=None, **extra):
    """Pop one finished trace from the ring and append its record to the
    JSONL sink (explicit arg > [tracing] TRACE_FILE). Never raises —
    telemetry must never kill a request. Returns the record (or None
    when the trace has no spans)."""
    if trace_id is None:
        return None
    try:
        spans = recorder().pop_trace(trace_id)
        if not spans:
            return None
        record = trace_record(trace_id, spans, **extra)
        path = sink or _sink
        if path:
            parent = os.path.dirname(os.path.abspath(path))
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")
        return record
    except Exception:
        return None


def load_trace_records(path):
    """All `kind == "trace"` records from a JSONL file (unparseable lines
    skipped, like `report`)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "trace":
                records.append(rec)
    return records


def _span_dicts(record):
    return sorted(record.get("spans", []), key=lambda s: s.get("t0", 0.0))


def summarize_trace(record):
    """One-line-per-trace summary dict: root name/duration, span count,
    and the per-name duration totals (sorted by wall)."""
    spans = _span_dicts(record)
    by_name = {}
    root = None
    for s in spans:
        by_name[s["name"]] = by_name.get(s["name"], 0.0) + s.get("dur_sec", 0.0)
        if s.get("parent_id") is None:
            root = s
    return {"trace_id": record.get("trace_id"),
            "spans": len(spans),
            "root": (root or {}).get("name"),
            "root_sec": (root or {}).get("dur_sec", 0.0),
            "root_attrs": (root or {}).get("attrs", {}),
            "by_name": dict(sorted(by_name.items(),
                                   key=lambda kv: -kv[1]))}


def format_trace_tree(record, indent="  "):
    """Render one trace record as an indented span tree (the `trace`
    CLI's default view). Orphans (parent evicted from the ring) print
    at top level."""
    spans = _span_dicts(record)
    ids = {s["span_id"] for s in spans}
    children = {}
    roots = []
    for s in spans:
        parent = s.get("parent_id")
        if parent is None or parent not in ids:
            roots.append(s)
        else:
            children.setdefault(parent, []).append(s)
    lines = [f"trace {record.get('trace_id')}  "
             f"({len(spans)} spans, ts {record.get('ts')})"]

    def walk(s, depth):
        attrs = s.get("attrs") or {}
        detail = ""
        if attrs:
            keys = sorted(attrs)[:4]
            detail = "  " + " ".join(f"{k}={attrs[k]}" for k in keys)
        lines.append(f"{indent * depth}{s['name']:<20} "
                     f"{s.get('dur_sec', 0.0) * 1e3:9.3f} ms{detail}")
        for child in children.get(s["span_id"], []):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 1)
    return lines
