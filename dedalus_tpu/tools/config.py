"""
Configuration cascade (reference: dedalus/tools/config.py:10-17).

Reads package defaults, then user (~/.dedalus_tpu/dedalus_tpu.cfg), then
local (./dedalus_tpu.cfg). Exposes a ConfigParser `config`.
"""

import os
import pathlib
from configparser import ConfigParser

config = ConfigParser()
config.optionxform = str  # preserve key case

_here = pathlib.Path(__file__).parent.parent
config.read(str(_here / "dedalus_tpu.cfg"))
config.read(os.path.expanduser("~/.dedalus_tpu/dedalus_tpu.cfg"))
config.read("dedalus_tpu.cfg")
