"""
Configuration cascade (reference: dedalus/tools/config.py:10-17).

Reads package defaults, then user (~/.dedalus_tpu/dedalus_tpu.cfg), then
local (./dedalus_tpu.cfg). Exposes a ConfigParser `config`.
"""

import os
import pathlib
from configparser import ConfigParser

config = ConfigParser()
config.optionxform = str  # preserve key case

_here = pathlib.Path(__file__).parent.parent
config.read(str(_here / "dedalus_tpu.cfg"))
config.read(os.path.expanduser("~/.dedalus_tpu/dedalus_tpu.cfg"))
config.read("dedalus_tpu.cfg")


def cfg_get(section, key, fallback):
    """Config value with fallback, tolerant of a missing section and of
    empty-string values (both yield `fallback`). The one implementation
    of the section/get/or-fallback dance shared by the tools modules."""
    sec = config[section] if config.has_section(section) else {}
    try:
        return sec.get(key, fallback) or fallback
    except AttributeError:
        return fallback
