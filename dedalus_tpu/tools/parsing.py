"""
Equation-string parsing helpers (reference: dedalus/tools/parsing.py:8-84).
"""

import re

from .exceptions import SymbolicParsingError


def split_equation(equation):
    """Split an equation string on the top-level '=' (respecting parentheses)."""
    parts = split_call(equation, "=")
    if len(parts) != 2:
        raise SymbolicParsingError(
            f"Equation must contain exactly one top-level '=': {equation!r}")
    return parts


def split_call(string, sep):
    """Split `string` on `sep` occurring at zero parenthesis depth."""
    depth = 0
    parts = []
    last = 0
    for i, ch in enumerate(string):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == sep and depth == 0:
            # Do not split on comparison operators (==, <=, >=, !=).
            if sep == "=" and (string[i - 1:i] in "<>=!" or string[i + 1:i + 2] == "="):
                continue
            parts.append(string[last:i].strip())
            last = i + 1
    parts.append(string[last:].strip())
    return parts


_LHS_CALL = re.compile(r"^\s*(\w+)\((.*)\)\s*$")


def lambdify_functions(call, result):
    """
    Convert a function-style equation entry like ``f(x=0)`` into the
    interpolated-LHS form used by `add_equation` string parsing.
    """
    return call, result
