"""
Caching decorators (reference: dedalus/tools/cache.py).

`CachedAttribute` — compute-once property.
`CachedMethod`/`CachedFunction` — memoization on hashable arguments.
`CachedClass` — metaclass interning instances by constructor arguments, so
bases/domains are singletons per argument tuple (reference:
dedalus/tools/cache.py:111-163).
"""

import types
from collections import OrderedDict
from functools import partial

import numpy as np


class CachedAttribute:
    """Descriptor for building attributes during first access."""

    def __init__(self, method):
        self.method = method
        self.__name__ = method.__name__
        self.__doc__ = method.__doc__

    def __get__(self, instance, owner):
        if instance is None:
            return self
        value = self.method(instance)
        # Replace descriptor lookup with the computed value.
        instance.__dict__[self.__name__] = value
        return value


class CachedFunction:
    """Memoize a function on hashable (serialized) arguments."""

    def __init__(self, function, max_size=None):
        self.function = function
        self.cache = OrderedDict()
        self.max_size = max_size
        self.__name__ = function.__name__
        self.__doc__ = function.__doc__

    def __call__(self, *args, **kw):
        key = serialize_call(args, kw)
        try:
            return self.cache[key]
        except KeyError:
            result = self.cache[key] = self.function(*args, **kw)
            if self.max_size and len(self.cache) > self.max_size:
                self.cache.popitem(last=False)
            return result


def cached_function(function=None, max_size=None):
    if function is None:
        return partial(cached_function, max_size=max_size)
    return CachedFunction(function, max_size=max_size)


class CachedMethod:
    """Memoize a method per-instance on hashable arguments."""

    def __init__(self, method):
        self.method = method
        self.__name__ = method.__name__
        self.__doc__ = method.__doc__

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = CachedFunction(types.MethodType(self.method, instance))
        instance.__dict__[self.__name__] = bound
        return bound


class CachedClass(type):
    """Metaclass interning instances by (serialized) constructor arguments."""

    def __init__(cls, *args, **kw):
        super().__init__(*args, **kw)
        cls._instance_cache = {}

    def __call__(cls, *args, **kw):
        key = serialize_call(args, kw)
        try:
            return cls._instance_cache[key]
        except KeyError:
            instance = cls._instance_cache[key] = super().__call__(*args, **kw)
            return instance
        except TypeError:
            # Unhashable argument: skip interning.
            return super().__call__(*args, **kw)


def serialize_call(args, kw):
    """Produce a hashable key from call arguments."""
    return (tuple(map(serialize, args)),
            tuple((k, serialize(v)) for k, v in sorted(kw.items())))


def serialize(arg):
    if isinstance(arg, np.ndarray):
        return (arg.shape, arg.dtype.str, arg.tobytes())
    if isinstance(arg, (list, tuple)):
        return tuple(map(serialize, arg))
    if isinstance(arg, dict):
        return tuple((k, serialize(v)) for k, v in sorted(arg.items()))
    # objects with state beyond their __eq__/__hash__ that must key caches
    # (e.g. coordinate systems: equal-by-name, but the distributor-assigned
    # AXES distinguish a disk at axes (0,1) from one inside a cylinder at
    # (1,2) — interning must not alias them)
    token = getattr(arg, "_cache_token", None)
    if token is not None:
        return token
    return arg
