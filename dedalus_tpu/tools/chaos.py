"""
Deterministic fault injection (chaos harness) for the resilient loop.

Production fault tolerance that has never seen a fault is a hypothesis,
not a feature. This module injects the faults tools/resilience.py claims
to absorb — deterministically, from a seed/config, so every recovery
branch is an ordinary reproducible test (tests/test_resilience.py, the
`chaos` pytest marker):

  * NaN poisoning of a named field at iteration N (divergence without
    waiting for physics to diverge),
  * a transient `OSError` on the Nth checkpoint write (flaky disk/NFS),
  * simulated SIGTERM delivery at iteration N (pool preemption),
  * checkpoint-file truncation/corruption (a crash mid-write).

Each armed fault fires ONCE (rewind replays the triggering iteration; a
re-firing fault would deadlock the recovery it is testing) and is logged
loudly when it fires. `ChaosInjector` is test machinery: it is never
constructed by the production path, only handed to `ResilientLoop(...,
chaos=...)` or used standalone on files.
"""

import errno
import logging
import os
import signal

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["ChaosInjector", "corrupt_checkpoint"]


def _field_slice(solver, name):
    """(offset, size) of one named state variable inside the gathered
    (G, S) pencil state."""
    from ..core.subsystems import state_key
    offset = 0
    for v in solver.variables:
        size = solver.layout.slot_size(v.domain, v.tensorsig)
        if state_key(v) == name or v.name == name:
            return offset, size
        offset += size
    raise KeyError(f"no state variable named {name!r}")


def corrupt_checkpoint(path, mode="truncate", seed=0):
    """
    Damage a checkpoint file in place the way a crash or bad disk would:
      truncate — cut the file to half length (kill mid-write: the HDF5
                 superblock/objects become unreadable),
      zero     — overwrite the middle third with zeros (silent media
                 corruption; the file may still open but datasets break),
      garbage  — overwrite the middle third with seeded random bytes.
    """
    path = os.fspath(path)
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    elif mode in ("zero", "garbage"):
        start, stop = size // 3, 2 * size // 3
        blob = (bytes(stop - start) if mode == "zero"
                else np.random.default_rng(seed).bytes(stop - start))
        with open(path, "r+b") as f:
            f.seek(start)
            f.write(blob)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    logger.warning(f"chaos: corrupted checkpoint {path} (mode={mode})")


class ChaosInjector:
    """
    Seed/config-driven fault injector driven by ResilientLoop hooks
    (`before_step`/`after_step`) or attached manually. Faults:

      nan_field + nan_iteration   — poison the named field's pencil
          slice with NaN after completing iteration N (the next health
          probe sees a non-finite state). With `nan_member` set and an
          EnsembleSolver as the target, only that member's slice of the
          (N, G, S) fleet state is poisoned — the per-member drop/rewind
          machinery (core/ensemble.py) must absorb it without stopping
          the batch.
      fail_checkpoint_write       — raise a transient OSError (EIO) on
          the Nth durable checkpoint write (1-based), succeeding on
          retry.
      sigterm_iteration           — deliver a real SIGTERM to this
          process after completing iteration N.

    `fired` records what fired and when, for test assertions.
    """

    def __init__(self, seed=0, nan_field=None, nan_iteration=None,
                 fail_checkpoint_write=None, sigterm_iteration=None,
                 nan_member=None):
        self.seed = int(seed)
        self.nan_field = nan_field
        self.nan_iteration = nan_iteration
        self.nan_member = nan_member
        self.fail_checkpoint_write = fail_checkpoint_write
        self.sigterm_iteration = sigterm_iteration
        self.fired = []
        self._checkpoint_writes = 0
        self._armed = set()
        if nan_field is not None and nan_iteration is not None:
            self._armed.add("nan")
        if sigterm_iteration is not None:
            self._armed.add("sigterm")
        if fail_checkpoint_write is not None:
            self._armed.add("io")

    def attach(self, loop):
        """Wire the IO fault into the loop's checkpoint path: the Nth
        write attempt raises a transient OSError BEFORE touching the
        file (retry then finds clean ground)."""
        if "io" not in self._armed:
            return
        handler_write = loop.write_checkpoint

        def chaotic_write():
            self._checkpoint_writes += 1
            if ("io" in self._armed
                    and self._checkpoint_writes == self.fail_checkpoint_write):
                self._armed.discard("io")
                self._fire("io", attempt=self._checkpoint_writes)
                raise OSError(errno.EIO, "chaos: injected transient IO fault")
            return handler_write()

        loop.write_checkpoint = chaotic_write

    def _fire(self, kind, **info):
        info["kind"] = kind
        self.fired.append(info)
        logger.warning(f"chaos: fired {info}")

    # ------------------------------------------------------- loop hooks

    def before_step(self, solver):
        """No pre-step faults currently; hook kept so injectors can be
        subclassed without touching the loop."""

    def after_step(self, solver):
        it = int(solver.iteration)
        if "nan" in self._armed and it >= self.nan_iteration:
            self._armed.discard("nan")
            self.poison_field(solver, self.nan_field)
            self._fire("nan", iteration=it, field=self.nan_field,
                       member=self.nan_member)
        if "sigterm" in self._armed and it >= self.sigterm_iteration:
            self._armed.discard("sigterm")
            self._fire("sigterm", iteration=it)
            os.kill(os.getpid(), signal.SIGTERM)

    # ----------------------------------------------------- fault bodies

    def poison_field(self, solver, name):
        """Overwrite the named field's slice of the gathered state with
        NaN — a pure device-side update (no host sync), exactly what a
        diverging nonlinearity produces. A 3-D (members, G, S) fleet
        state (core/ensemble.EnsembleSolver) poisons only `nan_member`'s
        slice."""
        import jax.numpy as jnp
        offset, size = _field_slice(solver, name)
        X = solver.X
        if X.ndim == 3:
            m = int(self.nan_member or 0)
            # JAX scatter silently drops out-of-bounds indices — a typo'd
            # member would record a fired fault that never happened
            if not 0 <= m < X.shape[0]:
                raise ValueError(
                    f"nan_member={m} out of range for a {X.shape[0]}-member "
                    f"fleet")
            solver.X = X.at[m, :, offset:offset + size].set(jnp.nan)
            return
        solver.X = X.at[:, offset:offset + size].set(jnp.nan)
        # the fields' lazy pulls still reference the clean X; re-install
        # against the poisoned state so harness code sees what the
        # solver sees
        solver.defer_scatter(solver.X)
        solver.snapshot_versions()
