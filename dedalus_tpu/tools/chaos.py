"""
Deterministic fault injection (chaos harness) for the resilient loop AND
the serving daemon.

Production fault tolerance that has never seen a fault is a hypothesis,
not a feature. This module injects the faults tools/resilience.py and
dedalus_tpu/service/ claim to absorb — deterministically, from a
seed/config, so every recovery branch is an ordinary reproducible test
(tests/test_resilience.py + tests/test_service_faults.py, the `chaos`
pytest marker):

Solve-loop faults (`ChaosInjector`, driven by ResilientLoop hooks):

  * NaN poisoning of a named field at iteration N (divergence without
    waiting for physics to diverge),
  * a transient `OSError` on the Nth checkpoint write (flaky disk/NFS),
  * simulated SIGTERM delivery at iteration N (pool preemption),
  * an artificially HUNG step at iteration N (`hang_iteration` +
    `hang_sec`: the post-step hook sleeps, starving step progress — the
    deterministic stand-in for a wedged JAX dispatch that drives the
    serving watchdog),
  * checkpoint-file truncation/corruption (a crash mid-write),
  * ONE flipped mantissa bit in a state shard at iteration N
    (`flip_bit_iteration`: seed-chosen element and bit — the value stays
    finite and plausible, so only the SDC sentinel's redundant
    re-execution can catch it),
  * a lost/poisoned device shard at iteration N (`lose_device` +
    `lose_iteration`, EnsembleSolver targets: the device's member block
    is overwritten with NaN and the fleet receives the loss
    notification that triggers re-sharding onto the survivors),
  * a torn sharded-checkpoint write (`torn_shard_write` +
    `torn_after_shards`: the writer dies after K shard files, BEFORE the
    manifest commits — plus `corrupt_shard` for post-commit silent shard
    corruption, and `slow_shard_sec` to stretch writes so async overrun
    and kill-mid-write windows are deterministic).

Service faults (plain socket clients misbehaving at the daemon — each
helper returns once the fault has been delivered, so a test can assert
the daemon's reaction deterministically):

  * `slow_loris` — hold a connection open, dribbling a never-completed
    header (the [service] IDLE_TIMEOUT_SEC defense),
  * `half_frame` — send a header promising a payload, then disconnect
    (a truncated frame: crash mid-write at the client),
  * `vanish_client` — submit a real run, then close the socket without
    reading anything (client gone before/while the daemon streams),
  * `sigkill_client` — spawn a real `submit` subprocess and SIGKILL it
    once its run is in flight (the OS-level version of vanishing),
  * `queue_storm` — a burst of concurrent run requests sized to
    overflow the bounded admission queue (drives load shedding),
  * `late_join_storm` — staggered concurrent run requests against a
    `--batch` daemon: the first anchors a micro-batch, later ones must
    JOIN it at block boundaries. Each request carries its own header,
    so per-member deadline skew (different `deadline_sec` per member)
    and member-targeted faults (a `chaos` block with
    `nan_field`/`nan_iteration` poisons that REQUEST's own member;
    `hang_iteration`/`hang_sec` stalls the batch boundary for the
    watchdog drill) ride the same helper. Deterministic: returns every
    member's terminal outcome, in submission order.

Batch-targeted member faults (service/batching.py applies them for
run-header chaos blocks on a `--chaos --batch` daemon):

  * `poison_fleet_member` — NaN ONE seat's slice of a serving fleet's
    (N, G, S) state (the served `nan_member`): the per-member health
    probe at the next boundary must detach exactly that member,
  * `vanish_client` / `sigkill_client` aimed at a batched run — the
    daemon detaches (abort) or completes-for-replay (complete) that
    member only, mid-batch.

Replica-fleet faults (aimed at ONE named replica of a `dedalus_tpu
route` deployment through its ReplicaSupervisor; tests/test_router.py —
every fault fires once and must be invisible to clients):

  * `kill_replica` — SIGKILL the replica process (abrupt crash; the
    router fails the cut run over, the supervisor restarts the body),
  * `wedge_replica` — SIGSTOP forever (alive but protocol-dead; probes
    miss until the supervisor SIGKILLs and restarts it),
  * `slow_replica_sec` — SIGSTOP then SIGCONT after N seconds (a stall
    below the wedge threshold: failover without a restart),
  * `partition` — repoint the supervisor's endpoint at a dead port
    (healthy process, unreachable network; returns a heal() callable).

Each armed ChaosInjector fault fires ONCE (rewind replays the
triggering iteration; a re-firing fault would deadlock the recovery it
is testing) and is logged loudly when it fires. Everything here is test
machinery: never constructed by the production path, only handed to
`ResilientLoop(..., chaos=...)` / a `--chaos` daemon, or aimed at a
daemon socket by tests.
"""

import errno
import json
import logging
import os
import signal
import socket
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["ChaosInjector", "corrupt_checkpoint", "corrupt_shard",
           "half_frame", "kill_replica", "late_join_storm", "partition",
           "poison_fleet_member", "queue_storm", "sigkill_client",
           "slow_loris", "slow_replica_sec", "vanish_client",
           "wedge_replica"]


def _field_slice(solver, name):
    """(offset, size) of one named state variable inside the gathered
    (G, S) pencil state."""
    from ..core.subsystems import state_key
    offset = 0
    for v in solver.variables:
        size = solver.layout.slot_size(v.domain, v.tensorsig)
        if state_key(v) == name or v.name == name:
            return offset, size
        offset += size
    raise KeyError(f"no state variable named {name!r}")


def corrupt_checkpoint(path, mode="truncate", seed=0):
    """
    Damage a checkpoint file in place the way a crash or bad disk would:
      truncate — cut the file to half length (kill mid-write: the HDF5
                 superblock/objects become unreadable),
      zero     — overwrite the middle third with zeros (silent media
                 corruption; the file may still open but datasets break),
      garbage  — overwrite the middle third with seeded random bytes.
    """
    path = os.fspath(path)
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    elif mode in ("zero", "garbage"):
        start, stop = size // 3, 2 * size // 3
        blob = (bytes(stop - start) if mode == "zero"
                else np.random.default_rng(seed).bytes(stop - start))
        with open(path, "r+b") as f:
            f.seek(start)
            f.write(blob)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    logger.warning(f"chaos: corrupted checkpoint {path} (mode={mode})")


def corrupt_shard(ckpt_dir, shard=0, mode="garbage", seed=0):
    """
    Damage one shard file of a COMMITTED sharded checkpoint
    (tools/dcheckpoint.py) the way silent media corruption would — after
    the manifest's checksums were recorded, so restore must catch it:
      garbage  — overwrite the middle third of the payload with seeded
                 random bytes (np header left intact: the file loads,
                 the blake2b mismatches — true silent corruption),
      truncate — cut the file in half (np.load fails: torn file),
      delete   — remove the shard file entirely (lost block).
    Returns the damaged file's path.
    """
    ckpt_dir = os.fspath(ckpt_dir)
    files = sorted(f for f in os.listdir(ckpt_dir) if f.endswith(".npy"))
    if not files:
        raise FileNotFoundError(f"no shard files under {ckpt_dir}")
    path = os.path.join(ckpt_dir, files[int(shard) % len(files)])
    size = os.path.getsize(path)
    if mode == "garbage":
        start, stop = max(size // 3, 128), max(2 * size // 3, 192)
        blob = np.random.default_rng(seed).bytes(stop - start)
        with open(path, "r+b") as f:
            f.seek(start)
            f.write(blob)
    elif mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    elif mode == "delete":
        os.remove(path)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    logger.warning(f"chaos: corrupted shard {path} (mode={mode})")
    return path


def _flip_mantissa_bit(value, bit):
    """Flip one mantissa bit of a scalar float/complex value (complex:
    the real part). Exponent and sign untouched, so the result stays
    finite and the same order of magnitude — silent by construction."""
    a = np.atleast_1d(np.asarray(value)).copy()
    if np.iscomplexobj(a):
        flipped = _flip_mantissa_bit(a.real.copy(), bit)
        out = np.empty(1, dtype=a.dtype)
        out[0] = complex(flipped, float(a.imag[0]))
        return out[0]
    mantissa = {4: 23, 8: 52}[a.dtype.itemsize]
    uint = a.view({4: np.uint32, 8: np.uint64}[a.dtype.itemsize])
    uint[0] ^= np.asarray(1, dtype=uint.dtype) << (int(bit) % mantissa)
    return a[0]


class ChaosInjector:
    """
    Seed/config-driven fault injector driven by ResilientLoop hooks
    (`before_step`/`after_step`) or attached manually. Faults:

      nan_field + nan_iteration   — poison the named field's pencil
          slice with NaN after completing iteration N (the next health
          probe sees a non-finite state). With `nan_member` set and an
          EnsembleSolver as the target, only that member's slice of the
          (N, G, S) fleet state is poisoned — the per-member drop/rewind
          machinery (core/ensemble.py) must absorb it without stopping
          the batch.
      fail_checkpoint_write       — raise a transient OSError (EIO) on
          the Nth durable checkpoint write (1-based), succeeding on
          retry.
      sigterm_iteration           — deliver a real SIGTERM to this
          process after completing iteration N.
      hang_iteration + hang_sec   — sleep `hang_sec` seconds after
          completing iteration N, BEFORE the loop's step hook runs: from
          the serving watchdog's point of view this is a hung JAX
          dispatch (no step progress), driven deterministically.
      flip_bit_iteration          — flip ONE seed-chosen mantissa bit of
          one element of the state after completing iteration N: silent
          data corruption (finite, plausible, invisible to the health
          probe) that only the SDC sentinel's redundant re-execution can
          detect. With `flip_bit_member` and a 3-D fleet state, the flip
          lands in that member's shard.
      lose_device + lose_iteration — EnsembleSolver targets: overwrite
          device `lose_device`'s member block with NaN (its shard is
          gone/garbage) and deliver the loss notification
          (`notify_device_loss`) that triggers fleet re-sharding onto
          the surviving devices before the next dispatch.
      torn_shard_write + torn_after_shards — kill the Nth sharded
          checkpoint write (1-based) after K shard files have landed,
          BEFORE the manifest commits (a crash/disk-full mid-write; the
          manifest-last protocol must make the torn directory invisible
          to restore). Requires `wire_checkpointer(ckpt)` — the
          ResilientLoop wires it automatically when built with chaos.
      slow_shard_sec              — sleep after every shard file write:
          stretches checkpoint IO so async overrun barriers and
          kill-mid-write windows are deterministic, not timing luck.

    `fired` records what fired and when, for test assertions.
    """

    def __init__(self, seed=0, nan_field=None, nan_iteration=None,
                 fail_checkpoint_write=None, sigterm_iteration=None,
                 nan_member=None, hang_iteration=None, hang_sec=None,
                 flip_bit_iteration=None, flip_bit_member=None,
                 lose_device=None, lose_iteration=None,
                 torn_shard_write=None, torn_after_shards=1,
                 slow_shard_sec=None):
        self.seed = int(seed)
        self.nan_field = nan_field
        self.nan_iteration = nan_iteration
        self.nan_member = nan_member
        self.fail_checkpoint_write = fail_checkpoint_write
        self.sigterm_iteration = sigterm_iteration
        self.hang_iteration = hang_iteration
        self.hang_sec = hang_sec
        self.flip_bit_iteration = flip_bit_iteration
        self.flip_bit_member = flip_bit_member
        self.lose_device = lose_device
        self.lose_iteration = lose_iteration
        self.torn_shard_write = torn_shard_write
        self.torn_after_shards = int(torn_after_shards)
        self.slow_shard_sec = slow_shard_sec
        self.fired = []
        self._checkpoint_writes = 0
        self._shard_writes = 0
        self._armed = set()
        if nan_field is not None and nan_iteration is not None:
            self._armed.add("nan")
        if sigterm_iteration is not None:
            self._armed.add("sigterm")
        if fail_checkpoint_write is not None:
            self._armed.add("io")
        if hang_iteration is not None and hang_sec is not None:
            self._armed.add("hang")
        if flip_bit_iteration is not None:
            self._armed.add("flip")
        if lose_device is not None and lose_iteration is not None:
            self._armed.add("lose")
        if torn_shard_write is not None:
            self._armed.add("torn")

    def attach(self, loop):
        """Wire the IO fault into the loop's checkpoint path: the Nth
        write attempt raises a transient OSError BEFORE touching the
        file (retry then finds clean ground)."""
        if "io" not in self._armed:
            return
        handler_write = loop.write_checkpoint

        def chaotic_write():
            self._checkpoint_writes += 1
            if ("io" in self._armed
                    and self._checkpoint_writes == self.fail_checkpoint_write):
                self._armed.discard("io")
                self._fire("io", attempt=self._checkpoint_writes)
                raise OSError(errno.EIO, "chaos: injected transient IO fault")
            return handler_write()

        loop.write_checkpoint = chaotic_write

    def wire_checkpointer(self, checkpointer):
        """Wire the sharded-write faults into a
        dcheckpoint.ShardedCheckpointer: the per-shard hook tears the
        `torn_shard_write`-th checkpoint after `torn_after_shards` files
        (the manifest never commits) and/or sleeps `slow_shard_sec` per
        shard. Called by ResilientLoop/EnsembleSolver when built with a
        chaos injector."""
        if "torn" not in self._armed and self.slow_shard_sec is None:
            return

        state = {"write": 0, "shards": 0}

        def hook(shards_written):
            if shards_written == 1:
                state["write"] += 1
            state["shards"] = shards_written
            if self.slow_shard_sec:
                time.sleep(float(self.slow_shard_sec))
            if ("torn" in self._armed
                    and state["write"] == self.torn_shard_write
                    and shards_written >= self.torn_after_shards):
                self._armed.discard("torn")
                self._fire("torn_shard", write=state["write"],
                           shards=shards_written)
                # NOT an OSError: a crash mid-write is not retryable, so
                # the fault must bypass the transient-IO RetryPolicy and
                # leave the directory exactly as the crash would
                raise RuntimeError("chaos: writer died mid-checkpoint "
                                   "(torn sharded write)")

        checkpointer.shard_hook = hook

    def _fire(self, kind, **info):
        info["kind"] = kind
        self.fired.append(info)
        logger.warning(f"chaos: fired {info}")

    # ------------------------------------------------------- loop hooks

    def before_step(self, solver):
        """No pre-step faults currently; hook kept so injectors can be
        subclassed without touching the loop."""

    def after_step(self, solver):
        it = int(solver.iteration)
        if "nan" in self._armed and it >= self.nan_iteration:
            self._armed.discard("nan")
            self.poison_field(solver, self.nan_field)
            self._fire("nan", iteration=it, field=self.nan_field,
                       member=self.nan_member)
        if "sigterm" in self._armed and it >= self.sigterm_iteration:
            self._armed.discard("sigterm")
            self._fire("sigterm", iteration=it)
            os.kill(os.getpid(), signal.SIGTERM)
        if "hang" in self._armed and it >= self.hang_iteration:
            self._armed.discard("hang")
            self._fire("hang", iteration=it, hang_sec=self.hang_sec)
            time.sleep(float(self.hang_sec))
        if "flip" in self._armed and it >= self.flip_bit_iteration:
            self._armed.discard("flip")
            index, bit = self.flip_bit(solver)
            self._fire("flip_bit", iteration=it, index=index, bit=bit)
        if "lose" in self._armed and it >= self.lose_iteration:
            self._armed.discard("lose")
            members = self.kill_device(solver, self.lose_device)
            self._fire("lose_device", iteration=it,
                       device=self.lose_device, members=members)

    # ----------------------------------------------------- fault bodies

    def poison_field(self, solver, name):
        """Overwrite the named field's slice of the gathered state with
        NaN — a pure device-side update (no host sync), exactly what a
        diverging nonlinearity produces. A 3-D (members, G, S) fleet
        state (core/ensemble.EnsembleSolver) poisons only `nan_member`'s
        slice."""
        import jax.numpy as jnp
        offset, size = _field_slice(solver, name)
        X = solver.X
        if X.ndim == 3:
            m = int(self.nan_member or 0)
            # JAX scatter silently drops out-of-bounds indices — a typo'd
            # member would record a fired fault that never happened
            if not 0 <= m < X.shape[0]:
                raise ValueError(
                    f"nan_member={m} out of range for a {X.shape[0]}-member "
                    f"fleet")
            solver.X = X.at[m, :, offset:offset + size].set(jnp.nan)
            return
        solver.X = X.at[:, offset:offset + size].set(jnp.nan)
        # the fields' lazy pulls still reference the clean X; re-install
        # against the poisoned state so harness code sees what the
        # solver sees
        solver.defer_scatter(solver.X)
        solver.snapshot_versions()

    def flip_bit(self, solver):
        """Flip one seed-chosen mantissa bit of one element of the state
        — in place, finite, and invisible to the NaN/growth health probe:
        the canonical silent data corruption. The element and bit come
        from the injector seed; a 3-D fleet state with `flip_bit_member`
        set flips inside that member's shard. Returns (index, bit) for
        test assertions. (The one-scalar host pull here is test
        machinery, never a production path.)"""
        X = solver.X
        rng = np.random.default_rng(self.seed)
        shape = X.shape
        if X.ndim == 3 and self.flip_bit_member is not None:
            m = int(self.flip_bit_member)
            if not 0 <= m < shape[0]:
                raise ValueError(f"flip_bit_member={m} out of range for a "
                                 f"{shape[0]}-member fleet")
            index = (m,) + tuple(int(rng.integers(s)) for s in shape[1:])
        else:
            index = tuple(int(rng.integers(s)) for s in shape)
        itemsize = np.dtype(X.dtype).itemsize
        if np.issubdtype(X.dtype, np.complexfloating):
            itemsize //= 2
        bit = int(rng.integers({4: 23, 8: 52}[itemsize]))
        value = np.asarray(X[index])
        flipped = _flip_mantissa_bit(value, bit)
        solver.X = X.at[index].set(flipped)
        if hasattr(solver, "defer_scatter"):
            solver.defer_scatter(solver.X)
            solver.snapshot_versions()
        return index, bit

    def kill_device(self, ens, device_index):
        """Simulate losing device `device_index` of an EnsembleSolver's
        member mesh: its member block of the fleet state is overwritten
        with NaN (the shard's data is gone — recovery must NOT read it
        back) and the fleet gets the loss notification an
        XlaRuntimeError-catching dispatch wrapper would deliver in
        production. Returns the affected member indices."""
        import jax.numpy as jnp
        d = int(device_index)
        members = ens.members_on_device(d)
        if members:
            ens.X = ens.X.at[members[0]:members[-1] + 1].set(jnp.nan)
        ens.notify_device_loss(d)
        return members


def poison_fleet_member(fleet, template, seat, field_name):
    """Overwrite ONE seat's slice of a serving fleet's (N, G, S) state
    with NaN — the batch-targeted `nan_member`: a served request's own
    chaos block poisons its own member, and the per-member health probe
    at the next block boundary must detach it without perturbing any
    other member's bits (service/batching.py applies this for run-header
    chaos on a `--chaos` daemon). Value-operand masked write: no seat
    index is baked into a compiled program, and no retrace."""
    import jax.numpy as jnp
    offset, size = _field_slice(template, field_name)
    n_pad, _G, S = fleet.X.shape
    seat_mask = np.zeros(n_pad, dtype=bool)
    seat_mask[int(seat)] = True
    col_mask = np.zeros(S, dtype=bool)
    col_mask[offset:offset + size] = True
    fleet.X = jnp.where(jnp.asarray(seat_mask)[:, None, None]
                        & jnp.asarray(col_mask)[None, None, :],
                        jnp.nan, fleet.X)
    logger.warning(f"chaos: poisoned fleet seat {seat} field "
                   f"{field_name!r} (cols {offset}:{offset + size})")


# --------------------------------------------------------- service faults
#
# Misbehaving clients aimed at a live `dedalus_tpu serve` daemon. Each
# helper is synchronous and deterministic: it returns once the fault has
# been delivered (and, where the daemon replies, returns the reply), so
# tests assert the daemon's reaction without sleeps-and-hope. None of
# these import the solver stack.

def slow_loris(port, host="127.0.0.1", hold_sec=2.0, drip=b"x"):
    """Hold a connection open dribbling a header that never completes —
    the classic slow-loris. Returns the daemon's reply header (the
    structured `bad-frame` produced when [service] IDLE_TIMEOUT_SEC
    expires the read), or None if the daemon just closed the socket."""
    deadline = time.monotonic() + float(hold_sec)
    with socket.create_connection((host, port), timeout=hold_sec + 30) as c:
        while time.monotonic() < deadline:
            try:
                c.sendall(drip)       # never a "\n": the frame never ends
            except OSError:
                break                 # daemon gave up on us already
            time.sleep(min(0.05, hold_sec / 10))
        logger.warning(f"chaos: slow-loris held port {port} for "
                       f"{hold_sec}s")
        try:
            line = c.makefile("rb").readline()
            return json.loads(line) if line else None
        except (OSError, ValueError):
            return None


def half_frame(port, host="127.0.0.1", claim_bytes=4096):
    """Send a header that PROMISES a payload, then disconnect — a frame
    torn exactly where a crashing client tears it. Returns immediately;
    the daemon must treat the truncation as a structured protocol error
    and survive."""
    header = json.dumps({"kind": "run", "payload_bytes": claim_bytes})
    with socket.create_connection((host, port), timeout=30) as c:
        c.sendall(header.encode() + b"\nonly-a-few-bytes")
    logger.warning(f"chaos: half-written frame (claimed {claim_bytes} "
                   f"payload bytes) delivered to port {port}")


def vanish_client(port, header, payload=None, host="127.0.0.1",
                  read_frames=0, linger_sec=0.0):
    """Submit a real frame, optionally read `read_frames` reply frames
    (e.g. 1 to consume the ack so the run is definitely in flight), then
    close the socket without warning. Returns the frames read."""
    from ..service import protocol
    frames = []
    with socket.create_connection((host, port), timeout=60) as c:
        wfile = c.makefile("wb")
        rfile = c.makefile("rb")
        protocol.send_frame(wfile, header, payload=payload)
        for _ in range(int(read_frames)):
            frame, _ = protocol.recv_frame(rfile)
            if frame is None:
                break
            frames.append(frame)
        if linger_sec:
            time.sleep(float(linger_sec))
    logger.warning(f"chaos: client vanished mid-stream on port {port} "
                   f"(after {len(frames)} frame(s))")
    return frames


def sigkill_client(port, spec, dt, stop_iteration, host="127.0.0.1",
                   after_progress_frames=1, timeout=120.0):
    """Spawn a real `python -m dedalus_tpu submit` subprocess streaming
    progress frames and SIGKILL it once `after_progress_frames` progress
    lines have appeared on its stderr — the OS-level client vanish (no
    FIN from a cooperative close(); the daemon discovers the dead peer
    only when a send fails). Returns the killed subprocess (already
    waited on)."""
    import subprocess
    import sys
    cmd = [sys.executable, "-m", "dedalus_tpu", "submit",
           "--host", host, "--port", str(port),
           "--spec", json.dumps(spec), "--dt", str(dt),
           "--stop-iteration", str(stop_iteration),
           "--progress-every", "5"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    seen = 0
    deadline = time.monotonic() + float(timeout)
    while seen < int(after_progress_frames):
        if time.monotonic() > deadline:
            proc.kill()
            proc.wait()
            raise RuntimeError("chaos: submit client produced no "
                               "progress frames before the timeout")
        line = proc.stderr.readline()
        if not line:
            break
        if line.startswith("progress:"):
            seen += 1
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    logger.warning(f"chaos: SIGKILLed submit client pid {proc.pid} after "
                   f"{seen} progress frame(s)")
    return proc


def late_join_storm(port, headers, payloads=None, stagger_sec=0.15,
                    host="127.0.0.1", timeout=300.0):
    """Staggered concurrent run requests against a `--batch` daemon: the
    first request anchors a micro-batch, each later one is submitted
    `stagger_sec` after the previous — landing mid-run, so it must JOIN
    the live batch at a block boundary (its ack's `batch.late_join`
    says whether it did). Each request carries its OWN header, so
    per-member deadline skew (`deadline_sec` varying across headers)
    and member-targeted chaos blocks ride the same storm. Returns one
    outcome dict per request, in submission order: {"ok", "code",
    "ack", "result", "fields", "records", "retry_after_sec",
    "wall_sec"}."""
    from ..service import protocol
    results = [None] * len(headers)
    payloads = payloads or [None] * len(headers)

    def one(i):
        t0 = time.perf_counter()
        out = {"ok": False, "code": None, "ack": None, "result": None,
               "fields": {}, "records": [], "retry_after_sec": None,
               "wall_sec": None}
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout) as c:
                wfile = c.makefile("wb")
                rfile = c.makefile("rb")
                protocol.send_frame(wfile, dict(headers[i]),
                                    payload=payloads[i])
                while True:
                    frame, frame_payload = protocol.recv_frame(rfile)
                    if frame is None:
                        out["code"] = out["code"] or "closed"
                        break
                    kind = frame.get("kind")
                    if kind == "ack":
                        out["ack"] = frame
                    elif kind == "progress":
                        pass
                    elif kind == "error":
                        out["code"] = frame.get("code")
                        out["retry_after_sec"] = frame.get(
                            "retry_after_sec")
                        break
                    elif kind == "result":
                        out["ok"] = True
                        out["result"] = frame
                        if frame_payload:
                            out["fields"] = protocol.decode_fields(
                                frame_payload)
                        break
                    else:
                        out["records"].append(frame)
        except OSError as exc:
            out["code"] = f"oserror:{exc.errno}"
        out["wall_sec"] = round(time.perf_counter() - t0, 4)
        results[i] = out

    threads = []
    for i in range(len(headers)):
        thread = threading.Thread(target=one, args=(i,), daemon=True)
        threads.append(thread)
        thread.start()
        if i + 1 < len(headers) and stagger_sec:
            time.sleep(float(stagger_sec))
    for thread in threads:
        thread.join(timeout=timeout)
    late = sum(1 for r in results
               if r and ((r.get("ack") or {}).get("batch") or {})
               .get("late_join"))
    logger.warning(f"chaos: late-join storm of {len(headers)} requests "
                   f"-> {sum(1 for r in results if r and r['ok'])} "
                   f"served, {late} late joins")
    return results


def queue_storm(port, header, payload=None, n=8, host="127.0.0.1",
                timeout=300.0):
    """Fire `n` concurrent run requests at the daemon and collect every
    terminal reply — the admission-control storm. Returns a list of
    result dicts: {"ok": bool, "code": error code or None, "frames": N,
    "retry_after_sec": hint or None, "wall_sec": request wall}. With n
    above the daemon's queue depth (+1 executing), the excess must come
    back as structured `overloaded` refusals."""
    from ..service import protocol
    results = [None] * int(n)

    def one(i):
        t0 = time.perf_counter()
        out = {"ok": False, "code": None, "frames": 0,
               "retry_after_sec": None, "wall_sec": None}
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout) as c:
                wfile = c.makefile("wb")
                rfile = c.makefile("rb")
                protocol.send_frame(wfile, dict(header),
                                    payload=payload)
                while True:
                    frame, _ = protocol.recv_frame(rfile)
                    if frame is None:
                        break
                    out["frames"] += 1
                    kind = frame.get("kind")
                    if kind == "error":
                        out["code"] = frame.get("code")
                        out["retry_after_sec"] = frame.get(
                            "retry_after_sec")
                        break
                    if kind == "result":
                        out["ok"] = True
                        break
        except OSError as exc:
            out["code"] = f"oserror:{exc.errno}"
        out["wall_sec"] = round(time.perf_counter() - t0, 4)
        results[i] = out

    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(int(n))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    logger.warning(
        f"chaos: queue storm of {n} requests -> "
        f"{sum(1 for r in results if r and r['ok'])} served, "
        f"{sum(1 for r in results if r and r['code'] == 'overloaded')} "
        "shed")
    return results


# --------------------------------------------------------- replica faults
#
# Fleet-level faults aimed at a `dedalus_tpu route` deployment
# (service/fleet.py ReplicaSupervisor). Each targets ONE named replica
# through the supervisor's own snapshot/endpoint surface and fires once;
# the router must absorb the fault invisibly (failover/replay: the
# client still sees one bit-identical result) and the supervisor must
# recover the replica. Expected client-visible outcomes are documented
# per fault in docs/serving.md#replica-fleet.

def kill_replica(fleet, name):
    """SIGKILL one replica's process — the abrupt replica crash. A run
    in flight there dies mid-stream; the router re-dispatches it (same
    request id, chaos stripped) to the next ring replica, and the
    supervisor restarts the casualty with backoff. Returns the killed
    pid."""
    pid = fleet.pid_of(name)
    if pid is None:
        raise KeyError(f"chaos: replica {name!r} has no live process")
    os.kill(pid, signal.SIGKILL)
    logger.warning(f"chaos: SIGKILLed replica {name} (pid {pid})")
    return pid


def wedge_replica(fleet, name):
    """SIGSTOP one replica indefinitely — alive to the OS, dead to the
    protocol. Its stats probes time out until the supervisor's
    `wedge_misses` threshold declares it wedged, SIGKILLs it, and
    restarts it. Returns the stopped pid (the supervisor delivers the
    SIGKILL; no SIGCONT is ever sent)."""
    pid = fleet.pid_of(name)
    if pid is None:
        raise KeyError(f"chaos: replica {name!r} has no live process")
    os.kill(pid, signal.SIGSTOP)
    logger.warning(f"chaos: wedged replica {name} (pid {pid} SIGSTOPped "
                   "until the supervisor kills it)")
    return pid


def slow_replica_sec(fleet, name, sec):
    """SIGSTOP one replica for `sec` seconds, then SIGCONT — a transient
    stall (GC pause, CPU-starved neighbor, NFS hiccup), NOT a wedge:
    `sec` must sit below the supervisor's wedge threshold so the replica
    rejoins the ring unrestarted. A routed run with a `deadline_sec`
    bound fails over under the router's deadline-derived read timeout.
    Returns the timer delivering the SIGCONT (armed; already started)."""
    pid = fleet.pid_of(name)
    if pid is None:
        raise KeyError(f"chaos: replica {name!r} has no live process")
    os.kill(pid, signal.SIGSTOP)

    def _resume():
        try:
            os.kill(pid, signal.SIGCONT)
            logger.warning(f"chaos: replica {name} (pid {pid}) resumed "
                           f"after {sec}s stall")
        except OSError:
            pass   # supervisor already replaced it

    timer = threading.Timer(float(sec), _resume)
    timer.daemon = True
    timer.start()
    logger.warning(f"chaos: stalled replica {name} (pid {pid}) for "
                   f"{sec}s")
    return timer


def partition(fleet, name, host="127.0.0.1"):
    """Repoint one replica's endpoint at a dead port — the network
    partition: the process is healthy but unreachable, so probes miss
    and forwards fail with connection-refused faults. Returns a `heal()`
    callable restoring the real endpoint."""
    with socket.socket() as probe:
        probe.bind((host, 0))
        dead_port = probe.getsockname()[1]
    # the socket is closed again: nothing listens on dead_port
    previous = fleet.set_endpoint(name, host=host, port=dead_port)
    logger.warning(f"chaos: partitioned replica {name} "
                   f"({previous[0]}:{previous[1]} -> dead port "
                   f"{dead_port})")

    def heal():
        fleet.set_endpoint(name, host=previous[0], port=previous[1])
        logger.warning(f"chaos: healed partition of replica {name}")

    return heal
