"""
Array helpers: host-side sparse utilities and device-side axis-wise matrix
application (reference: dedalus/tools/array.py).

Host functions use numpy/scipy.sparse and run only at problem-setup time.
Device functions are pure jnp and safe to trace under jit.
"""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp


# -------------------------------------------------------------- device side

def zeropad(x, pad_width):
    """`jnp.pad(x, pad_width)` for zero padding, lowered as
    concatenations with zero broadcasts instead of an HLO `pad` op.
    XLA's SPMD partitioner (jaxlib 0.4.37) hard-crashes
    (hlo_sharding_util CHECK `IsManualSubgroup`) propagating shardings
    through `pad` inside the GSPMD-auto region of a partially-manual
    shard_map — the region every per-member op of the 2-D batch x pencil
    fleet composition lives in (core/ensemble.py). Concatenation
    partitions cleanly and is bitwise-identical zero padding, so the
    traced transform/solve bodies use this form. `pad_width` is the
    jnp.pad spec: one non-negative (before, after) pair per dim."""
    for axis, (before, after) in enumerate(pad_width):
        parts = []
        if before:
            shp = x.shape[:axis] + (before,) + x.shape[axis + 1:]
            parts.append(jnp.zeros(shp, x.dtype))
        parts.append(x)
        if after:
            shp = x.shape[:axis] + (after,) + x.shape[axis + 1:]
            parts.append(jnp.zeros(shp, x.dtype))
        if len(parts) > 1:
            x = jnp.concatenate(parts, axis=axis)
    return x


# ---------------------------------------------------------------- host side

def kron(*factors):
    """Sparse Kronecker product of several factors (reference: tools/array.py:325)."""
    out = factors[0]
    for f in factors[1:]:
        out = sp.kron(out, f, format="csr")
    return sp.csr_matrix(out)


def sparsify(dense, cutoff=1e-14):
    """
    Convert a dense matrix to CSR, dropping entries below `cutoff` relative
    to the max magnitude. Used to recover exact band structure from
    quadrature-built matrices. Sparse input passes through as CSR.
    """
    if sp.issparse(dense):
        return dense.tocsr()
    dense = np.asarray(dense)
    scale = np.max(np.abs(dense)) if dense.size else 0.0
    if scale == 0.0:
        return sp.csr_matrix(dense.shape)
    clipped = np.where(np.abs(dense) >= cutoff * scale, dense, 0.0)
    return sp.csr_matrix(clipped)


def perm_matrix(perm, M=None, source_index=False, dtype=None):
    """
    Sparse permutation matrix (reference: tools/array.py:356).

    With ``source_index=False`` (default), ``perm[i]`` gives the source row
    placed at destination row i: ``(P @ x)[i] = x[perm[i]]``.
    """
    perm = np.asarray(perm)
    N = perm.size
    if M is None:
        M = N
    data = np.ones(N, dtype=dtype or np.float64)
    if source_index:
        # perm[j] = destination of source j
        return sp.csr_matrix((data, (perm, np.arange(N))), shape=(M, N))
    return sp.csr_matrix((data, (np.arange(N), perm)), shape=(N, M))


def interleave_matrices(matrices):
    """
    Combine identically-shaped matrices into a block matrix acting on
    interleaved vectors (reference: tools/array.py:447). Entry (i, j) of each
    input lands at (i*K + k, j*K + k) for input k of K.
    """
    K = len(matrices)
    if K == 1:
        return sp.csr_matrix(matrices[0])
    rows, cols = matrices[0].shape
    out = sp.lil_matrix((rows * K, cols * K))
    for k, mat in enumerate(matrices):
        coo = sp.coo_matrix(mat)
        out[coo.row * K + k, coo.col * K + k] = coo.data
    return sp.csr_matrix(out)


def sparse_block_diag(blocks, shape=None):
    """Sparse block-diagonal (reference: tools/array.py:300)."""
    return sp.csr_matrix(sp.block_diag(blocks))


def apply_matrix(matrix, array, axis, out=None):
    """Host-side: contract `matrix` with `array` along `axis` (numpy)."""
    matrix = np.asarray(matrix.todense()) if sp.issparse(matrix) else np.asarray(matrix)
    moved = np.moveaxis(np.asarray(array), axis, -1)
    result = np.moveaxis(moved @ matrix.T, -1, axis)
    if out is not None:
        out[...] = result
        return out
    return result


def scipy_sparse_eigs(A, B, N, target, matsolver=None, left=False, **kw):
    """
    Shift-invert sparse eigensolve for the generalized problem
    A.x = λ B.x around `target` (reference: tools/array.py:398-444).
    """
    import scipy.sparse.linalg as spla
    A = sp.csc_matrix(A)
    B = sp.csc_matrix(B)
    C = A - target * B
    solver = spla.factorized(C)

    def matvec(x):
        return solver(B @ x)

    op = spla.LinearOperator(dtype=np.complex128, shape=A.shape, matvec=matvec)
    evals, evecs = spla.eigs(op, k=N, which="LM", sigma=None, **kw)
    # Rayleigh-quotient style un-shift: λ = target + 1/μ
    evals = target + 1.0 / evals
    if left:
        solver_H = spla.factorized(C.conj().T)

        def matvec_H(x):
            return B.conj().T @ solver_H(x)

        op_H = spla.LinearOperator(dtype=np.complex128, shape=A.shape, matvec=matvec_H)
        evals_left, evecs_left = spla.eigs(op_H, k=N, which="LM", **kw)
        evals_left = target + 1.0 / np.conj(evals_left)
        return evals, evecs, evals_left, evecs_left
    return evals, evecs


def csr_to_banded(matrix, cutoff=1e-14):
    """
    Detect band structure of a sparse/dense matrix. Returns (lower, upper)
    bandwidths such that all entries outside the band are (numerically) zero.
    """
    coo = sp.coo_matrix(sparsify(matrix.toarray() if sp.issparse(matrix) else matrix, cutoff))
    if coo.nnz == 0:
        return 0, 0
    d = coo.col - coo.row
    return int(max(0, -d.min())), int(max(0, d.max()))


# -------------------------------------------------------------- device side

def match_precision(matrix, data_dtype):
    """
    Cast a (host f64/c128) operator matrix DOWN to the working precision of
    `data_dtype`, preserving complexness. Keeps float32 problems in float32
    on device (TPU: c128 unsupported, f64 emulated) instead of silently
    promoting through f64 constants.

    Host (numpy) matrices above a small size are routed through the
    device-constant registry so compiled programs receive them as runtime
    ARGUMENTS: this JAX version inlines every non-splat constant into the
    program text, and transform stacks reach hundreds of MB
    (tools/jitlift.py has the full story).
    """
    low = (jnp.dtype(data_dtype).itemsize <= 4
           or data_dtype in (jnp.float32, jnp.complex64))

    def target(dt):
        if low:
            return np.complex64 if np.issubdtype(dt, np.complexfloating) \
                else np.float32
        return dt

    if sp.issparse(matrix):
        # interned by the sparse object's identity (producers cache these)
        tdt = target(matrix.dtype)
        from .jitlift import device_constant
        if np.prod(matrix.shape) * np.dtype(tdt).itemsize > 16384:
            return device_constant(matrix, dtype=tdt)
        return jnp.asarray(matrix.toarray(), dtype=tdt)
    if isinstance(matrix, np.ndarray):
        tdt = target(matrix.dtype)
        if matrix.size * np.dtype(tdt).itemsize > 16384:
            from .jitlift import device_constant
            return device_constant(matrix, dtype=tdt)
        return jnp.asarray(matrix, dtype=tdt)
    matrix = jnp.asarray(matrix)
    if low and jnp.issubdtype(matrix.dtype, jnp.complexfloating):
        return matrix.astype(jnp.complex64)
    if low:
        return matrix.astype(jnp.float32)
    return matrix


def apply_matrix_jax(matrix, array, axis):
    """
    Device-side: contract ``matrix`` (m_out, m_in) with ``array`` along
    ``axis``. Pure jnp; jit/vmap safe. Complex matrices acting on real
    arrays promote (and vice versa); matrix precision follows the data.
    """
    matrix = match_precision(matrix, array.dtype)
    arr = jnp.moveaxis(array, axis, -1)
    out = jnp.matmul(arr, matrix.T)
    return jnp.moveaxis(out, -1, axis)


def expand_pattern(pattern, array):
    """Broadcast a static numpy mask/pattern against a traced array."""
    return jnp.broadcast_to(jnp.asarray(pattern), array.shape)
