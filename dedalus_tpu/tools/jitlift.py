"""
Device-constant lifting for compiled programs.

This JAX version inlines every non-splat array constant into the lowered
MLIR (verified: a 100 MB transform-matrix stack adds ~400 MB of program
text). Spectral kernels are built from exactly such constants — MMT
matrices, per-m SWSH/Zernike stacks, NCC matrices — so naive jit produces
multi-GB programs that overwhelm the TPU compiler (and remote-compile
transports). The reference never hits this because FFTW plans live outside
the compiler (libraries/fftw/fftw_wrappers.pyx); a TPU-native design needs
the matrices INSIDE the program boundary but OUTSIDE the program text.

`lifted_jit(fn)` compiles fn with every `device_constant(arr)` the trace
touches passed as a runtime ARGUMENT:

  1. discovery: `jax.eval_shape` traces fn abstractly; each
     `device_constant` call resolves to its concrete device array and
     records its registry index;
  2. execution: the recorded constants are bound as leading arguments of a
     wrapped `jax.jit`, inside which `device_constant` resolves to the
     traced argument value.

Producers keep returning plain numpy (host assembly reads them directly);
only device-use funnels (`tools.array.match_precision` and the transform
matmul helpers) route through `device_constant`.
"""

import logging
import threading

import numpy as np
import jax
import jax.numpy as jnp

from . import retrace as retrace_mod

__all__ = ["device_constant", "lifted_jit", "tracing_active",
           "tracing_state_known"]


def _probe_public():
    """Public trace-state probe (jax.core has exported trace_state_clean
    across recent majors)."""
    from jax.core import trace_state_clean
    trace_state_clean()  # verify callable before committing to it
    return lambda: not trace_state_clean()


def _probe_private():
    """Legacy fallback on jax internals; kept only for JAX builds whose
    public surface predates/renames trace_state_clean."""
    # the one sanctioned private-API fallback, guarded by _resolve below
    from jax._src.core import trace_ctx, EvalTrace  # dedalus-lint: disable=DTL005
    isinstance(trace_ctx.trace, EvalTrace)  # verify the attributes exist
    return lambda: not isinstance(trace_ctx.trace, EvalTrace)


def _resolve_tracing_probe(candidates=(_probe_public, _probe_private)):
    """Resolve a () -> bool tracing probe, trying public JAX surfaces
    before private ones. When every candidate fails (API drift across a
    JAX upgrade), degrade to a constant-False probe with ONE warning
    instead of raising: callers lose the inline-instead-of-cache guard
    (device_value) and the eager GeneralFunction fast path, both safe
    fallbacks, rather than the whole import."""
    for candidate in candidates:
        try:
            return candidate()
        except Exception:
            continue
    logging.getLogger(__name__).warning(
        "jitlift: no usable JAX trace-state API (public and private probes "
        "both failed); assuming never-tracing. device_constant caching and "
        "GeneralFunction dispatch fall back to conservative behavior.")
    return _degraded_probe


def _degraded_probe():
    """Distinguished never-tracing probe: callers that need to know
    whether the answer is trustworthy check tracing_state_known()."""
    return False


_tracing_probe = None


def tracing_active():
    """True when called under a jax trace (jit/vmap/grad/eval_shape).
    Resolved lazily against the running JAX version; see
    _resolve_tracing_probe for the degradation contract."""
    global _tracing_probe
    if _tracing_probe is None:
        _tracing_probe = _resolve_tracing_probe()
    return _tracing_probe()


def tracing_state_known():
    """False when the trace-state probe degraded to constant-False (every
    candidate API failed): tracing_active() is then a guess, and callers
    with a safe conservative branch (e.g. GeneralFunction's io_callback
    path) should take it."""
    global _tracing_probe
    if _tracing_probe is None:
        _tracing_probe = _resolve_tracing_probe()
    return _tracing_probe is not _degraded_probe


# historical internal spelling (device_value below predates the public name)
_tracing_active = tracing_active


class _Registry:
    """
    Constants are interned by CONTENT (shape/dtype/digest), with a
    source-object-identity fast path that skips hashing for producer-cached
    arrays. Producers that rebuild equal arrays per trace therefore still
    dedupe correctly — they just pay a hash per call.
    """

    def __init__(self):
        self.arrays = []            # numpy or device arrays by index
        self.by_id = {}             # (id(src), dtype) -> index
        self.by_content = {}        # (shape, dtype, digest) -> index
        self.keepalive = {}         # id(src) -> src (guards id reuse)

    def intern(self, array, convert, dtype):
        import hashlib
        fast = (id(array), str(np.dtype(dtype)) if dtype is not None else None)
        idx = self.by_id.get(fast)
        if idx is not None:
            return idx
        # stored as NUMPY: device conversion must happen outside any trace
        # (under a trace jnp.asarray yields a tracer, which must never be
        # cached)
        converted = convert()
        digest = hashlib.blake2b(
            np.ascontiguousarray(converted).tobytes(),
            digest_size=16).digest()
        key = (converted.shape, str(converted.dtype), digest)
        idx = self.by_content.get(key)
        if idx is None:
            idx = len(self.arrays)
            self.arrays.append(converted)
            self.by_content[key] = idx
        self.by_id[fast] = idx
        self.keepalive[id(array)] = array
        return idx

    def device_value(self, idx):
        """The constant as a device array; caches the transfer only when
        called outside a trace."""
        val = self.arrays[idx]
        if isinstance(val, np.ndarray):
            converted = jnp.asarray(val)
            # never cache a tracer: belt (probe) AND suspenders (type
            # check), so a degraded never-tracing probe cannot poison the
            # process-global registry from inside a foreign trace
            if _tracing_active() or isinstance(converted, jax.core.Tracer):
                return converted   # foreign trace: inline, no cache
            val = self.arrays[idx] = converted
        return val


_registry = _Registry()
_local = threading.local()


def device_constant(array, dtype=None):
    """
    Mark a host array (numpy or scipy sparse) as a large device constant
    of compiled programs. Outside lifted tracing this returns the interned
    device array (eager use); during a lifted trace it resolves to the
    constant's traced argument (recording it during discovery).

    Interning is by the SOURCE object's identity: callers must pass cached
    host arrays (fresh per-call arrays defeat the lift and leak registry
    entries — the fallback below warns when that happens).
    """
    def convert():
        a = array.toarray() if hasattr(array, "toarray") else array
        if dtype is not None and np.dtype(dtype) != np.asarray(a).dtype:
            return np.asarray(a, dtype=dtype)
        return np.asarray(a)

    idx = _registry.intern(array, convert, dtype)
    mode = getattr(_local, "mode", None)
    if mode is None:
        return _registry.device_value(idx)
    if mode[0] == "discover":
        mode[1].add(idx)
        return _registry.arrays[idx]
    # substitution: traced argument values by index
    sub = mode[1].get(idx)
    if sub is not None:
        return sub
    # A constant first touched during the jit trace but not discovery:
    # the source object was rebuilt between traces (unstable identity),
    # so the lift silently degrades to inlining — make that visible.
    import logging
    logging.getLogger(__name__).warning(
        f"device_constant: unstable source identity for a "
        f"{np.shape(_registry.arrays[idx])} constant; inlining into the "
        "program (the producer should cache this array).")
    return _registry.arrays[idx]


class _Mode:
    def __init__(self, tag, payload):
        self.state = (tag, payload)

    def __enter__(self):
        self.prev = getattr(_local, "mode", None)
        _local.mode = self.state
        return self.state[1]

    def __exit__(self, *exc):
        _local.mode = self.prev


def _signature(tree):
    leaves, treedef = jax.tree.flatten(tree)
    sig = tuple((np.shape(l), str(getattr(l, "dtype", type(l))))
                for l in leaves)
    return (treedef, sig)


class lifted_jit:
    """jax.jit with device-constant lifting; supports static_argnums and
    donate_argnums (original-fn positions; the fused step programs donate
    their history buffers so XLA rolls them in place — callers own the
    invalidation contract for outstanding references, see
    core/fusedstep.py DONATE_STEP)."""

    def __init__(self, fn, static_argnums=(), donate_argnums=()):
        self.fn = fn
        self.static_argnums = tuple(static_argnums)
        self.donate_argnums = tuple(donate_argnums)
        overlap = set(self.static_argnums) & set(self.donate_argnums)
        if overlap:
            raise ValueError(f"cannot donate static argnums {overlap}")
        self._cache = {}
        # retrace sentinel: the jit bodies below note every trace of THIS
        # wrapper, so post-warmup recompiles surface as structured
        # warnings + the dedalus/retrace metric (tools/retrace.py)
        self._retrace_state = retrace_mod.TraceCount(
            getattr(fn, "__qualname__", None) or repr(fn))

    def _donate_positions(self, n_args):
        """Donated original positions -> wrapped positions (the consts
        list occupies wrapped slot 0; dynamic arg j sits at 1 + j)."""
        dyn_index = {}
        j = 0
        for i in range(n_args):
            if i not in self.static_argnums:
                dyn_index[i] = j
                j += 1
        return tuple(1 + dyn_index[i] for i in self.donate_argnums)

    def __call__(self, *args):
        static = tuple(args[i] for i in self.static_argnums)
        dynamic = [a for i, a in enumerate(args) if i not in self.static_argnums]
        key = (static, _signature(dynamic))
        entry = self._cache.get(key)
        if entry is None:
            touched = set()
            with _Mode("discover", touched):
                jax.eval_shape(lambda *d: self._call_fn(static, d), *dynamic)
            idxs = tuple(sorted(touched))

            def wrapped(consts, *d):
                # trace-time side effect: runs per (re)trace, not per call
                retrace_mod.sentinel.note(self._retrace_state)
                with _Mode("substitute", dict(zip(idxs, consts))):
                    return self._call_fn(static, d)

            donate = self._donate_positions(len(args)) \
                if self.donate_argnums else ()
            entry = self._cache[key] = (
                idxs, jax.jit(wrapped, donate_argnums=donate))
        idxs, jfn = entry
        return jfn([_registry.device_value(i) for i in idxs], *dynamic)

    def _call_fn(self, static, dynamic):
        args = list(dynamic)
        for pos, val in sorted(zip(self.static_argnums, static)):
            args.insert(pos, val)
        return self.fn(*args)

    def jaxpr(self, *args):
        """ClosedJaxpr of the lifted program body (device constants
        resolve to their interned device arrays, so they appear as jaxpr
        constants). Inspection surface for the program contract checker
        (tools/lint/progcheck.py): primitive-level contracts — forbidden
        solve/callback primitives, pads inside partial-auto shard_map
        regions — read the program from here."""
        static = tuple(args[i] for i in self.static_argnums)
        dynamic = [a for i, a in enumerate(args)
                   if i not in self.static_argnums]
        return jax.make_jaxpr(lambda *d: self._call_fn(static, d))(*dynamic)

    def lower(self, *args):
        """Lower the lifted program (for inspection/testing). The fresh
        jit carries the wrapper's donate_argnums, so inspection sees the
        SAME input_output_alias contract the executing program compiles
        with — the donation-honored program contract
        (tools/lint/progcheck.py) reads it from exactly this text, and a
        lower() that silently dropped donation would report every
        donating program as a regression (and, worse, hide a real one)."""
        static = tuple(args[i] for i in self.static_argnums)
        dynamic = [a for i, a in enumerate(args)
                   if i not in self.static_argnums]
        touched = set()
        with _Mode("discover", touched):
            jax.eval_shape(lambda *d: self._call_fn(static, d), *dynamic)
        idxs = tuple(sorted(touched))

        def wrapped(consts, *d):
            with _Mode("substitute", dict(zip(idxs, consts))):
                return self._call_fn(static, d)

        donate = self._donate_positions(len(args)) \
            if self.donate_argnums else ()
        # cold inspection path: a fresh jit per lower() is the point here
        return jax.jit(wrapped, donate_argnums=donate).lower(  # dedalus-lint: disable=DTL003
            [_registry.device_value(i) for i in idxs], *dynamic)
