"""
Persistent pencil-matrix assembly cache (the on-disk tier of tools/cache.py).

Cold starts pay a host-side symbolic walk (`expression_matrices` + scipy
kron folds) plus the banded structural analysis for every solver build,
even when the problem is byte-identical to the last run. This module
content-addresses the OUTPUTS of `core.solvers.SolverBase.
_build_pencil_system` — the shared-pattern COO store, or the banded
arrays + permutations + Woodbury pin data — under a key derived from
everything that determines them:

  * the equation expression TREES (class names, scalars, operator
    parameters — not just the equation strings, which would alias
    different parameter values),
  * non-variable (NCC/background) field DATA feeding the LHS matrices
    (hashed bytes, so parameter continuation and Newton rebuilds can
    never alias),
  * variable names/dtypes/tensor signatures and per-basis specs
    (class, size, bounds/radii, dealias, k, ...),
  * the solver class, matrix names, matsolver spec and the [linear
    algebra] knobs that steer the structural path,
  * the package version and a cache format version.

Entries are single `.npb` array bundles (magic + JSON meta line + raw
`np.save` members — no zip/CRC pass, which dominated warm load time;
`allow_pickle=False` end-to-end) under `[caching] ASSEMBLY_CACHE`,
mirroring the persistent XLA cache layout next door. Writes are atomic
(tmp file + `os.replace`, fsync'd) following the torn-file discipline of
tools/resilience.py; loads validate the payload (format/key/shape
checks, full parse) and fall back to fresh assembly on ANY corruption,
quarantining the bad entry. Eviction is LRU by mtime under
`ASSEMBLY_CACHE_MAX_MB` (hits touch their entry).
"""

import hashlib
import json
import logging
import os
import pathlib
import tempfile

import numpy as np
import scipy.sparse as sp

from .config import config

logger = logging.getLogger(__name__)

__all__ = ["AssemblyCache", "pool_key", "resolve", "solver_key", "clear",
           "store_tuning", "load_tuning"]

FORMAT_VERSION = 2

# Config keys (outside [caching]) whose values steer which representation
# is assembled; they ride into the key so a knob flip cannot alias.
_KEYED_CONFIG = (
    ("linear algebra", "MATRIX_SOLVER"),
    ("linear algebra", "BANDED_CUTOFF_BYTES"),
    ("linear algebra", "BAND_DETECT_CUTOFF"),
    ("linear algebra", "BANDED_MAX_DIAGS"),
)


# ------------------------------------------------------------ fingerprints

class Unfingerprintable(Exception):
    """Expression/field graph contains something we cannot hash safely."""


def _fp_update(h, *parts):
    for part in parts:
        h.update(repr(part).encode())
        h.update(b"\x00")


# Constructor-parameter attributes that define a basis. An explicit
# allowlist, NOT the whole __dict__: interned bases grow lazily-cached
# attributes over a session (CachedAttribute materializes on first
# access), which would make the fingerprint depend on what OTHER code
# already touched the basis.
_BASIS_ATTRS = (
    "size", "shape", "bounds", "radii", "radius", "dealias", "a", "b",
    "a0", "b0", "k", "alpha", "dtype", "library", "colatitude_library",
    "radius_library", "kappa", "rho", "length", "dR", "Lmax", "Nr",
    "Ntheta", "ell_separable", "complex",
)


def _fp_basis(h, basis, seen):
    if basis is None:
        _fp_update(h, "basis:None")
        return
    if id(basis) in seen:
        _fp_update(h, "basis-ref", seen[id(basis)])
        return
    seen[id(basis)] = len(seen)
    _fp_update(h, "basis", type(basis).__name__)
    for key in _BASIS_ATTRS:
        val = basis.__dict__.get(key)
        if val is None:
            continue
        if isinstance(val, (int, float, complex, str, bool, np.integer,
                            np.floating)):
            _fp_update(h, key, val)
        elif isinstance(val, tuple) and all(
                isinstance(v, (int, float, str, bool)) for v in val):
            _fp_update(h, key, val)
        elif isinstance(val, np.dtype):
            _fp_update(h, key, val.str)
        elif isinstance(val, type):
            _fp_update(h, key, val.__name__)
    coord = getattr(basis, "coord", None) or getattr(basis, "coordsystem",
                                                     None)
    _fp_update(h, "first_axis", basis.first_axis, "dim", basis.dim,
               "coord", getattr(coord, "name", None))
    # derived size invariants, in case a basis class stores a shape
    # parameter under a name outside the allowlist
    try:
        _fp_update(h, "sizes", tuple(int(basis.coeff_size(sub))
                                     for sub in range(basis.dim)))
    except Exception:
        pass


def _fp_domain(h, domain, seen):
    _fp_update(h, "domain", len(domain.bases))
    for basis in domain.bases:
        _fp_basis(h, basis, seen)


def _fp_field(h, field, variables, seen):
    from ..core.subsystems import state_key
    _fp_update(h, "field", field.name, np.dtype(field.dtype).str,
               tuple(type(cs).__name__ for cs in field.tensorsig),
               tuple(cs.dim for cs in field.tensorsig))
    _fp_domain(h, field.domain, seen)
    if field in variables:
        # variables enter symbolically: identified by position/name only
        _fp_update(h, "variable", [state_key(v) for v in variables].index(
            state_key(field)))
    else:
        # NCC / parameter field: the DATA is baked into the matrices
        data = np.asarray(field.coeff_data())
        _fp_update(h, "data", data.shape, data.dtype.str)
        h.update(np.ascontiguousarray(data).tobytes())


def _fp_expr(h, expr, variables, seen):
    from ..core.field import Field
    from ..core.future import Future
    from ..core.coords import CoordinateSystem
    from ..core.basis import Basis
    if expr is None:
        _fp_update(h, "none")
        return
    if np.isscalar(expr):
        _fp_update(h, "scalar", expr)
        return
    if isinstance(expr, CoordinateSystem):
        # operator parameters (Differentiate's coordinate, Gradient's cs):
        # the interning token names the coordsystem + distributor axes
        _fp_update(h, "coords", type(expr).__name__, expr._cache_token)
        return
    if isinstance(expr, Basis):
        # Lift/Convert target bases in args
        _fp_basis(h, expr, seen)
        return
    if isinstance(expr, Field):
        _fp_field(h, expr, variables, seen)
        return
    if not isinstance(expr, Future):
        raise Unfingerprintable(f"unhashable node {type(expr).__name__}")
    _fp_update(h, "op", type(expr).__name__)
    # Operator parameters living outside .args: Lift/Convert TARGET BASES
    # (`basis`, `basis_in`, `target_bases`), interpolation positions,
    # scalar multipliers, coordinate systems, component indices, ... —
    # anything of an unrecognized type FAILS CLOSED (Unfingerprintable ->
    # no caching) rather than silently dropping out of the key, which
    # would let distinct problems collide on one cache entry.
    for key in sorted(expr.__dict__):
        if key in ("args", "domain", "tensorsig", "dtype", "dist") or \
                key.startswith("_"):
            continue
        _fp_value(h, key, expr.__dict__[key], variables, seen)
    for arg in expr.args:
        _fp_expr(h, arg, variables, seen)
    _fp_update(h, "end")


def _fp_value(h, key, val, variables, seen):
    """Fingerprint one operator attribute/parameter value (fails closed
    on unrecognized types)."""
    from ..core.field import Field
    from ..core.future import Future
    from ..core.coords import CoordinateSystem
    from ..core.basis import Basis
    if val is None or isinstance(val, (int, float, complex, str, bool,
                                       np.integer, np.floating)):
        _fp_update(h, key, val)
    elif isinstance(val, np.dtype):
        _fp_update(h, key, val.str)
    elif isinstance(val, Basis):
        _fp_update(h, key)
        _fp_basis(h, val, seen)
    elif isinstance(val, CoordinateSystem):
        _fp_update(h, key, type(val).__name__, val._cache_token)
    elif isinstance(val, (Field, Future)):
        _fp_update(h, key)
        _fp_expr(h, val, variables, seen)
    elif isinstance(val, np.ndarray):
        _fp_update(h, key, val.shape, val.dtype.str)
        h.update(np.ascontiguousarray(val).tobytes())
    elif isinstance(val, (tuple, list)):
        _fp_update(h, key, len(val))
        for i, item in enumerate(val):
            _fp_value(h, f"{key}[{i}]", item, variables, seen)
    else:
        raise Unfingerprintable(
            f"operator attribute {key} of type {type(val).__name__}")


def solver_key(solver, names):
    """Content hash for one solver's pencil system, or None when the
    problem graph cannot be fingerprinted safely."""
    from .. import __version__
    try:
        h = hashlib.blake2b(digest_size=20)
        _fp_update(h, "format", FORMAT_VERSION, "version", __version__,
                   "solver", type(solver).__name__, "names", tuple(names))
        for section, key in _KEYED_CONFIG:
            _fp_update(h, key, config[section].get(key, ""))
        # fused-step composition (core/fusedstep.py): the RESOLVED fusion
        # token rides into the key so a [fusion] flag flip (or an `auto`
        # landing differently on another backend) can never serve a
        # payload whose precomposed fused matrices were built under
        # another composition. The host-assembly matrices themselves are
        # fusion-independent, but this key seeds pool_key — the serving
        # warm pool holds COMPILED step programs, which do depend on the
        # composition — and the fused-composite entries, so a flip
        # invalidates all three together. Cost: a rare flag flip re-runs
        # host assembly once; the safe direction. The solver's
        # build-start plan is preferred so the key always tokens the
        # composition the build actually compiles under.
        plan = getattr(solver, "_fusion_plan", None)
        if plan is None:
            from ..core.fusedstep import cache_token
            _fp_update(h, "fusion", cache_token())
        else:
            _fp_update(h, "fusion", plan.token())
        # resolved [distributed] transpose chunking: the chunk structure
        # shapes every compiled sharded walk, and this key seeds
        # pool_key — pooled entries hold COMPILED step programs, so two
        # chunk configs must never alias one warm entry (the host
        # matrices themselves are chunk-independent; same safe-direction
        # trade as the fusion token above)
        chunks = getattr(solver, "_transpose_chunks", None)
        if chunks is None:
            from ..parallel.transposes import resolve_transpose_chunks
            chunks = resolve_transpose_chunks()
        _fp_update(h, "transpose_chunks", int(chunks))
        # resolved solve composition + precision ladder (libraries/
        # solvecomp.py): the composition restructures the compiled
        # substitution programs and the ladder changes the factor-store
        # dtype — pooled compiled solvers and fused-composite payloads
        # must never alias across either (same safe-direction trade as
        # the fusion/chunk tokens above)
        splan = getattr(solver, "_solve_plan", None)
        if splan is None:
            from ..libraries.solvecomp import solve_plan_token
            _fp_update(h, "solve_plan", solve_plan_token())
        else:
            _fp_update(h, "solve_plan", splan.token())
        spec = solver.matsolver
        _fp_update(h, "matsolver",
                   spec if isinstance(spec, str) else getattr(
                       spec, "__name__", type(spec).__name__))
        # layout coupling: a matrix_coupling override (or NCC forcing)
        # changes which axes are separable without changing the equation
        # trees — equal-sized alternate couplings must not collide on one
        # entry
        layout = solver.layout
        _fp_update(h, "coupled_axes", tuple(layout.coupled_axes),
                   "sep_widths", tuple(sorted(layout.sep_widths.items())))
        seen = {}
        variables = list(solver.variables)
        _fp_update(h, "nvars", len(variables))
        for v in variables:
            _fp_field(h, v, variables, seen)
        _fp_update(h, "neqs", len(solver.equations))
        for eq in solver.equations:
            members = eq["members"] if "members" in eq else [(eq, None)]
            _fp_update(h, "block", len(members))
            _fp_domain(h, eq["domain"], seen)
            _fp_update(h, "tsig", tuple(cs.dim for cs in eq["tensorsig"]))
            for member, _cond in members:
                _fp_update(h, "cond", member.get("condition"))
                for name in names:
                    _fp_expr(h, member.get(name), variables, seen)
        return h.hexdigest()
    except Unfingerprintable as exc:
        logger.debug(f"assembly cache: unfingerprintable problem ({exc})")
        return None
    except Exception as exc:
        logger.debug(f"assembly cache: fingerprint failed ({exc!r})")
        return None


def pool_key(solver):
    """Warm-pool identity of a BUILT solver — the key the service tier
    (dedalus_tpu/service/pool.py) stores live compiled solvers under.

    It is the assembly-cache content key (reusing the key stashed at
    build time as `solver.assembly_key` when the persistent cache
    computed one, recomputing otherwise) composed with everything else
    that makes two LIVE solvers interchangeable but that the assembly
    key deliberately excludes (M/L matrices are scheme-independent, so
    cached matrices shard across these):

      * the timestepper scheme — the compiled step programs and
        factorizations a pooled entry holds are scheme-specific;
      * the run-behavior knobs (`warmup_iterations`,
        `enforce_real_cadence`) — two specs that build identical
        matrices but different Hermitian-projection cadences would
        produce DIFFERENT trajectories from one shared entry.

    Returns None when the problem graph cannot be fingerprinted; the
    pool then falls back to its normalized-spec digest."""
    key = getattr(solver, "assembly_key", None)
    if key is None:
        key = solver_key(solver, solver.matrices)
    if key is None:
        return None
    ts = getattr(solver, "timestepper", None)
    h = hashlib.blake2b(digest_size=20)
    _fp_update(h, "pool", key,
               "scheme", type(ts).__name__ if ts is not None else None,
               "warmup", getattr(solver, "warmup_iterations", None),
               "enforce_real", getattr(solver, "enforce_real_cadence",
                                       None))
    return h.hexdigest()


# ------------------------------------------------------------- disk store

class AssemblyCache:
    """One on-disk cache directory of raw array-bundle payloads.

    Entry format (`.npb`): a magic line, one JSON meta line (which names
    the arrays in order), then each array appended via `np.save` — NOT a
    zip/npz, whose per-member CRC pass costs ~0.3 s on a warm RB 256x64
    load and would eat most of the cache's win."""

    MAGIC = b"DTASM\n"

    def __init__(self, directory, max_mb=2048):
        self.directory = pathlib.Path(os.path.expanduser(str(directory)))
        self.max_bytes = int(float(max_mb) * 1e6)

    def _path(self, key):
        return self.directory / f"asm-{key}.npb"

    def load(self, key):
        """Validated payload {meta: dict, arrays: dict} or None. Any
        corruption (torn write, truncation, stale format) quarantines the
        entry and reports a miss."""
        path = self._path(key)
        if not path.exists():
            return None
        try:
            with open(path, "rb") as f:
                if f.readline() != self.MAGIC:
                    raise ValueError("bad magic")
                meta = json.loads(f.readline().decode())
                if meta.get("format") != FORMAT_VERSION:
                    raise ValueError(f"format {meta.get('format')}")
                if meta.get("key") != key:
                    raise ValueError("key mismatch")
                arrays = {name: np.load(f, allow_pickle=False)
                          for name in meta["array_names"]}
                if f.read(1):
                    raise ValueError("trailing bytes")
        except OSError as exc:
            # transient access failure (EIO/EINTR, NFS hiccup): the entry
            # on disk may be intact — report a miss but do NOT quarantine
            logger.warning(
                f"assembly cache entry {path.name} unreadable "
                f"({exc!r}); falling back to fresh assembly")
            return None
        except Exception as exc:
            logger.warning(
                f"assembly cache entry {path.name} unusable "
                f"({exc!r}); falling back to fresh assembly")
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)   # LRU touch
        except OSError:
            # read-only cache dir (shared prebuilt warm cache): the entry
            # parsed cleanly, so it is a hit — only the recency stamp is
            # lost
            pass
        return {"meta": meta, "arrays": arrays}

    def discard(self, key):
        """Quarantine one entry (best-effort removal: a payload that
        parsed but failed to install must not poison every future build)."""
        try:
            os.remove(self._path(key))
        except OSError:
            pass

    def store(self, key, meta, arrays):
        """Atomic write (tmp + replace): a crash mid-write can never leave
        a half-visible entry, only an orphaned tmp file."""
        meta = dict(meta)
        meta["format"] = FORMAT_VERSION
        meta["key"] = key
        meta["array_names"] = sorted(arrays)
        path = self._path(key)
        try:
            self.directory.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=str(self.directory),
                                       prefix=".asm-tmp-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(self.MAGIC)
                    f.write(json.dumps(meta).encode() + b"\n")
                    for name in meta["array_names"]:
                        np.save(f, np.asarray(arrays[name]),
                                allow_pickle=False)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._evict()
            return True
        except OSError as exc:
            logger.warning(f"assembly cache write failed: {exc}")
            return False

    def _evict(self):
        """Drop oldest entries (mtime LRU) above the size budget."""
        try:
            paths = list(self.directory.glob("asm-*.np[bz]"))
        except OSError:
            return
        entries = []
        for p in paths:
            try:
                st = p.stat()
            except OSError:
                # concurrently removed by another process: skip it, keep
                # enforcing the budget over the rest
                continue
            entries.append((st.st_mtime, st.st_size, p))
        total = sum(size for _, size, _ in entries)
        if total <= self.max_bytes:
            return
        for _, size, path in sorted(entries):
            try:
                os.remove(path)
                total -= size
            except OSError:
                pass
            if total <= self.max_bytes:
                break

    def clear(self):
        for path in self.directory.glob("asm-*.np[bz]"):
            try:
                os.remove(path)
            except OSError:
                pass


def resolve():
    """The configured cache, or None when disabled. The
    DEDALUS_TPU_ASSEMBLY_CACHE environment variable overrides the
    [caching] ASSEMBLY_CACHE directory ('' disables), so subprocesses
    (tests, benchmarks) can redirect it without a config file."""
    directory = os.environ.get("DEDALUS_TPU_ASSEMBLY_CACHE")
    if directory is None:
        directory = config["caching"].get("ASSEMBLY_CACHE", "").strip() \
            if config.has_section("caching") else ""
    if not directory:
        return None
    max_mb = config["caching"].getfloat("ASSEMBLY_CACHE_MAX_MB",
                                        fallback=2048.0) \
        if config.has_section("caching") else 2048.0
    return AssemblyCache(directory, max_mb=max_mb)


def clear():
    cache = resolve()
    if cache is not None:
        cache.clear()


# --------------------------------------------------- tuning payload codec

def store_tuning(cache, signature, record):
    """Persist one autotune decision record (tools/autotune.py) as a
    `tuning` payload under the tuner's shape signature. The record is
    pure JSON riding the meta line (no arrays), but it gets the same
    atomic-write + LRU + quarantine machinery as every matrix payload —
    and the same cross-process reach, so one replica's tuning decision
    warms every solver build (and the whole serving fleet) that shares
    the cache directory."""
    meta = {"kind": "tuning", "tuning": record}
    try:
        return cache.store(signature, meta, {})
    except TypeError:
        # non-JSON-serializable evidence must not break solver builds:
        # the decision simply does not persist (memo still serves it
        # in-process)
        logger.warning(
            f"assembly cache: tuning record {str(signature)[:12]} not "
            "serializable; decision not persisted")
        return False


def load_tuning(cache, signature):
    """The persisted tuning record for one shape signature, or None.
    Structural corruption quarantines at load (AssemblyCache.load);
    a parseable entry of the wrong kind quarantines here. SEMANTIC
    validation of the record belongs to the caller
    (tools/autotune.load_decision), which quarantines via discard."""
    payload = cache.load(signature)
    if payload is None:
        return None
    meta = payload["meta"]
    if meta.get("kind") != "tuning" or not isinstance(
            meta.get("tuning"), dict):
        logger.warning(
            f"assembly cache entry {str(signature)[:12]} is not a "
            "tuning payload; quarantined")
        cache.discard(signature)
        return None
    return meta["tuning"]


# -------------------------------------------------- solver payload codecs

def export_payload(solver, names):
    """(meta, arrays) snapshot of a freshly built pencil system, or None
    when the representation is not worth persisting."""
    G, S = solver.pencil_shape
    meta = {"kind": None, "names": list(names), "G": int(G), "S": int(S)}
    arrays = {}
    if solver.structure is not None:
        st = solver.structure
        meta["kind"] = "banded"
        meta["structure"] = {
            "S": int(st.S), "NB": int(st.NB), "q": int(st.q),
            "kl": int(st.kl), "ku": int(st.ku), "t_pins": int(st.t_pins),
            "n_modes": int(getattr(st, "n_modes", 0)),
            "n_caxes": int(getattr(st, "n_caxes", 1)),
        }
        for attr in ("row_perm", "col_perm", "row_pos", "pinned_rows",
                     "pinned_positions"):
            arrays[f"st_{attr}"] = np.asarray(getattr(st, attr))
        for name in names:
            store = solver._matrices[name]
            arrays[f"bands_{name}"] = store["bands"]
            arrays[f"Vt_{name}"] = store["Vt"]
            if "dsel" in store:
                arrays[f"dsel_{name}"] = np.asarray(store["dsel"], dtype=int)
        return meta, arrays
    if solver._batched is not None:
        pr, pc, vals, row_valid, col_valid = solver._batched
        meta["kind"] = "coo"
        arrays["pattern_rows"] = np.asarray(pr)
        arrays["pattern_cols"] = np.asarray(pc)
        arrays["row_valid"] = np.asarray(row_valid)
        arrays["col_valid"] = np.asarray(col_valid)
        for name in names:
            arrays[f"vals_{name}"] = np.asarray(vals[name])
        return meta, arrays
    # per-group dense fallback: persist the dense store below a size cap
    # (rare path: unbatchable expression trees with small G)
    total = sum(solver._matrices[name].nbytes for name in names)
    if total > 256e6:
        return None
    meta["kind"] = "dense"
    for name in names:
        arrays[f"dense_{name}"] = solver._matrices[name]
    return meta, arrays


def install_payload(solver, names, payload):
    """Rebuild solver._matrices/structure/ops from a cache payload.
    Returns True on success; False (clean miss) on any inconsistency."""
    from ..core.subsystems import MatrixStructure
    from ..libraries import pencilops
    meta, arrays = payload["meta"], payload["arrays"]
    G, S = solver.pencil_shape
    if (meta.get("names") != list(names) or meta.get("G") != G
            or meta.get("S") != S):
        return False
    kind = meta.get("kind")
    if kind == "banded":
        state = {k: int(v) for k, v in meta["structure"].items()}
        for attr in ("row_perm", "col_perm", "row_pos", "pinned_rows",
                     "pinned_positions"):
            state[attr] = arrays[f"st_{attr}"]
        state["n_interior"] = state["S"]
        st = MatrixStructure.from_state(state, solver.layout)
        mats = {}
        for name in names:
            store = {"bands": arrays[f"bands_{name}"],
                     "Vt": arrays[f"Vt_{name}"]}
            if f"dsel_{name}" in arrays:
                store["dsel"] = tuple(int(d) for d in arrays[f"dsel_{name}"])
            mats[name] = store
        solver._batched = None
        solver._matrices = mats
        solver.structure = st
        solver.ops = pencilops.BandedOps(
            st, fusion=getattr(solver, "_fusion_plan", None),
            solve_plan=getattr(solver, "_solve_plan", None))
        return True
    if kind == "coo":
        vals = {name: arrays[f"vals_{name}"] for name in names}
        solver._batched = (arrays["pattern_rows"], arrays["pattern_cols"],
                           vals, arrays["row_valid"], arrays["col_valid"])
        solver._matrices = solver._dense_from_batched(names)
        solver.structure = None
        solver.ops = pencilops.DenseOps(
            solver._dense_matsolver(),
            solve_plan=getattr(solver, "_solve_plan", None))
        return True
    if kind == "dense":
        solver._batched = None
        solver._matrices = {name: arrays[f"dense_{name}"] for name in names}
        solver.structure = None
        solver.ops = pencilops.DenseOps(
            solver._dense_matsolver(),
            solve_plan=getattr(solver, "_solve_plan", None))
        return True
    return False
