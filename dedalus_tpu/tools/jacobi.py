"""
Orthonormal Jacobi polynomial toolbox (reference: dedalus/tools/jacobi.py and
dedalus/libraries/dedalus_sphere/jacobi.py — same capabilities, different
construction).

Design: instead of the reference's lazy sparse operator algebra, every
operator matrix (conversion, differentiation, multiplication-by-NCC,
interpolation, integration) is built **by Gauss-Jacobi quadrature** against
orthonormal polynomials evaluated with the stable three-term recurrence.
Quadrature of sufficient degree makes these matrices exact to roundoff, and
known analytic band structures are enforced by masking. All of this runs on
host (numpy, float64) once at setup; results ship to device as constants.

Conventions:
  * Native interval x in [-1, 1], weight (1-x)^a (1+x)^b, a,b > -1.
  * Polynomials are orthonormal: integral(w p_m p_n) = delta_{mn}.
  * ChebyshevT = Jacobi(a=b=-1/2), Legendre = Jacobi(a=b=0),
    Ultraspherical C^(k) used for k-th derivative bases (a+k, b+k).
"""

import numpy as np
from scipy import special

from .cache import cached_function


def mass(a, b):
    """Total measure: integral of (1-x)^a (1+x)^b over [-1, 1]."""
    return np.exp((a + b + 1) * np.log(2.0)
                  + special.gammaln(a + 1) + special.gammaln(b + 1)
                  - special.gammaln(a + b + 2))


@cached_function
def recurrence(N, a, b):
    """
    Three-term recurrence coefficients for orthonormal Jacobi polynomials:
        x p_n = beta[n] p_{n+1} + alpha[n] p_n + beta[n-1] p_{n-1}
    Returns (alpha[0..N-1], beta[0..N-1]).
    """
    n = np.arange(N, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        alpha = (b**2 - a**2) / ((2*n + a + b) * (2*n + a + b + 2))
        beta = (2.0 / (2*n + a + b + 2)) * np.sqrt(
            (n + 1) * (n + a + 1) * (n + b + 1) * (n + a + b + 1)
            / ((2*n + a + b + 1) * (2*n + a + b + 3)))
    # n = 0 entries hit degenerate denominators when a+b in {0, -1}; use limits.
    alpha[0] = (b - a) / (a + b + 2)
    beta[0] = (2.0 / (a + b + 2)) * np.sqrt((a + 1) * (b + 1) / (a + b + 3))
    return alpha, beta


def build_polynomials(N, a, b, grid):
    """
    Evaluate orthonormal Jacobi polynomials p_0..p_{N-1} at `grid`.
    Returns array of shape (N, len(grid)).
    """
    grid = np.asarray(grid, dtype=np.float64)
    alpha, beta = recurrence(max(N, 2), a, b)
    P = np.zeros((N, grid.size))
    if N == 0:
        return P
    P[0] = 1.0 / np.sqrt(mass(a, b))
    if N > 1:
        P[1] = (grid - alpha[0]) * P[0] / beta[0]
    for n in range(1, N - 1):
        P[n + 1] = ((grid - alpha[n]) * P[n] - beta[n - 1] * P[n - 1]) / beta[n]
    return P


def build_polynomial_derivatives(N, a, b, grid):
    """
    Evaluate d p_n / dx at `grid` by differentiating the recurrence.
    Returns array of shape (N, len(grid)).
    """
    grid = np.asarray(grid, dtype=np.float64)
    alpha, beta = recurrence(max(N, 2), a, b)
    P = build_polynomials(N, a, b, grid)
    D = np.zeros((N, grid.size))
    if N > 1:
        D[1] = P[0] / beta[0]
    for n in range(1, N - 1):
        D[n + 1] = ((grid - alpha[n]) * D[n] + P[n] - beta[n - 1] * D[n - 1]) / beta[n]
    return D


@cached_function
def build_grid(N, a, b):
    """Gauss-Jacobi quadrature nodes for weight (1-x)^a (1+x)^b (ascending)."""
    if N == 1:
        # Single-node Gauss rule: node at the weight's mean.
        alpha, _ = recurrence(2, a, b)
        return np.array([alpha[0]])
    x, _ = special.roots_jacobi(N, a, b)
    return x


@cached_function
def build_weights(N, a, b):
    """Gauss-Jacobi quadrature weights matching `build_grid`."""
    if N == 1:
        return np.array([mass(a, b)])
    _, w = special.roots_jacobi(N, a, b)
    return w


@cached_function
def forward_matrix(N, a, b, Ng=None):
    """
    Forward transform matrix: grid values on the Ng-point (a,b) Gauss grid
    -> first N orthonormal coefficients. Exact for polynomials of degree
    < 2*Ng - N. Shape (N, Ng).
    """
    if Ng is None:
        Ng = N
    x = build_grid(Ng, a, b)
    w = build_weights(Ng, a, b)
    P = build_polynomials(N, a, b, x)
    return P * w  # row n: p_n(x_i) w_i


@cached_function
def backward_matrix(N, a, b, Ng=None):
    """Backward transform matrix: N coefficients -> Ng grid values. (Ng, N)."""
    if Ng is None:
        Ng = N
    x = build_grid(Ng, a, b)
    return build_polynomials(N, a, b, x).T


def _quadrature_inner(Nrows, arow, brow, colvals_fn, Nq, aq, bq):
    """
    Generic quadrature assembly: M[m, n] = <q_m^(arow,brow), f_n>_(aq,bq)
    where f_n values come from `colvals_fn(x)` (shape (Ncols, Nq)).
    """
    x = build_grid(Nq, aq, bq)
    w = build_weights(Nq, aq, bq)
    Q = build_polynomials(Nrows, arow, brow, x)
    F = colvals_fn(x)
    return (Q * w) @ F.T


@cached_function
def conversion_matrix(N, a, b, da=0, db=0):
    """
    Connection matrix from (a, b) to (a+da, b+db), shape (N, N), upper
    triangular with bandwidth da+db (banded structure enforced).
    (reference: dedalus/tools/jacobi.py:229 conversion_matrix)
    """
    da, db = int(da), int(db)
    if da < 0 or db < 0:
        raise ValueError("Conversion only defined for nonnegative increments.")
    a2, b2 = a + da, b + db
    M = _quadrature_inner(N, a2, b2, lambda x: build_polynomials(N, a, b, x), N, a2, b2)
    # Exact structure: upper triangular, bandwidth da+db.
    mask = np.zeros((N, N), dtype=bool)
    for d in range(0, da + db + 1):
        mask |= np.eye(N, N, k=d, dtype=bool)
    return M * mask


@cached_function
def differentiation_matrix(N, a, b):
    """
    d/dx : coeffs in (a,b) -> coeffs in (a+1,b+1). Single superdiagonal.
    (reference: dedalus/tools/jacobi.py:247)
    """
    M = _quadrature_inner(N, a + 1, b + 1,
                          lambda x: build_polynomial_derivatives(N, a, b, x),
                          N, a + 1, b + 1)
    mask = np.eye(N, N, k=1, dtype=bool)
    return M * mask


@cached_function
def jacobi_matrix(N, a, b):
    """
    Multiplication by x in the (a,b) basis: tridiagonal (N, N) truncation of
    the Jacobi operator (reference: dedalus/tools/jacobi.py:250).
    """
    alpha, beta = recurrence(N, a, b)
    return (np.diag(alpha) + np.diag(beta[:N-1], 1) + np.diag(beta[:N-1], -1))


def multiplication_matrix(N_out, a_out, b_out, N_in, a_in, b_in, f_coeffs, a_f, b_f):
    """
    NCC multiplication matrix: maps coeffs of u in (a_in, b_in) to coeffs of
    (f u) in (a_out, b_out), where f has coefficients `f_coeffs` in
    (a_f, b_f). Built by quadrature of sufficient degree — replaces the
    reference's Clenshaw assembly (dedalus/tools/clenshaw.py:24).
    """
    f_coeffs = np.asarray(f_coeffs)
    Nf = f_coeffs.shape[-1]
    # integrand degree <= (N_out-1) + (N_in-1) + (Nf-1); Gauss with Nq nodes
    # is exact to degree 2*Nq - 1.
    Nq = (N_out + N_in + Nf) // 2 + 2

    def colvals(x):
        fvals = f_coeffs @ build_polynomials(Nf, a_f, b_f, x)
        return build_polynomials(N_in, a_in, b_in, x) * fvals

    return _quadrature_inner(N_out, a_out, b_out, colvals, Nq, a_out, b_out)


@cached_function
def integration_vector(N, a, b):
    """
    Row vector of integrals: I[n] = integral of p_n(x) dx over [-1, 1].
    Computed with Gauss-Legendre (exact: p_n are polynomials).
    (reference: dedalus/tools/jacobi.py:253)
    """
    NL = N // 2 + 1
    xl, wl = special.roots_legendre(NL)
    P = build_polynomials(N, a, b, xl)
    return P @ wl


def interpolation_vector(N, a, b, x0):
    """Row vector: p_n(x0), for boundary/point interpolation."""
    return build_polynomials(N, a, b, np.array([float(x0)]))[:, 0]
