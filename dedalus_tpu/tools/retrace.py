"""
Retrace sentinel: runtime counterpart of the DTL003 lint rule.

A compiled step loop should trace each program once during warmup and
never again; a post-warmup retrace means something in the hot path is
producing fresh signatures (shape/dtype drift, unstable static args,
rebuilt wrappers) and the loop is silently paying compile time per step.
The static analyzer cannot see that — it is a runtime property — so the
traced functions carry a trace-time side effect: their Python bodies only
execute while JAX is tracing, so a counter bump there counts compiles,
not calls.

Wiring: `tools.jitlift.lifted_jit` notes every trace of every instance
(covering the solver step/factor/eval programs), and `noted()` wraps raw
`jax.jit` users (the health probe). The solver arms the sentinel at
warmup end; an armed retrace logs a structured warning, records an
event, and bumps a `dedalus/retrace` counter on every subscribed Metrics
instance — so it lands in the JSONL telemetry next to steps/sec and is
assertable in tests (`sentinel.post_arm_retraces == 0`).

Counting granularity is the WRAPPER INSTANCE, deliberately: the first
trace of a fresh wrapper (e.g. the step_many scan block compiled after
warmup) is a compile, not a retrace — but within one wrapper, every
post-warmup trace counts, including "new signature" traces. Under jax a
recompile is ALWAYS a new signature (identical signatures hit the cache),
so counting per cache key instead would make per-step shape/static-arg
drift — the exact hazard — invisible as an endless stream of "first
compiles". Corollary: a driver that varies step_many block sizes
post-warmup is flagged, correctly — each new block length pays a full
trace+compile; fix the driver to use fixed block sizes.
"""

import collections
import logging
import threading
import weakref

logger = logging.getLogger(__name__)

__all__ = ["TraceCount", "RetraceSentinel", "sentinel", "noted"]

# bounded accounting: a per-step retrace storm (the exact pathology the
# sentinel exists to catch) must not itself leak memory or flood the log
EVENT_RING_SIZE = 256
WARNINGS_PER_LABEL = 5


class TraceCount:
    """Per-wrapper trace counter (one per lifted_jit / noted() wrapper)."""

    __slots__ = ("label", "count")

    def __init__(self, label):
        self.label = str(label)
        self.count = 0


class RetraceSentinel:
    """Process-wide trace accounting. Counts are per wrapper instance (a
    fresh solver's first traces never look like retraces), the armed flag
    is global (once any solver is past warmup, a retrace anywhere in the
    process is a hygiene event)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = weakref.WeakSet()
        self._warned = {}   # label -> warnings emitted (rate limit)
        self.armed = False
        self.total_traces = 0
        self.retraces = 0
        self.post_arm_retraces = 0
        self.events = collections.deque(maxlen=EVENT_RING_SIZE)

    def subscribe(self, metrics):
        """Register a Metrics instance to receive `dedalus/retrace`
        counter bumps on armed retraces (held weakly)."""
        # under the lock: note() snapshots the set while holding it, and a
        # solver can be constructed while another thread is mid-trace
        with self._lock:
            self._metrics.add(metrics)

    def arm(self):
        """Mark warmup complete: from now on retraces warn and count."""
        self.armed = True

    def reset(self):
        """Test hook: disarm and zero the global accounting. Per-wrapper
        counts live on the wrappers and are NOT cleared — an old wrapper
        retracing after a reset is still a retrace."""
        with self._lock:
            self.armed = False
            self.total_traces = 0
            self.retraces = 0
            self.post_arm_retraces = 0
            self.events = collections.deque(maxlen=EVENT_RING_SIZE)
            self._warned = {}

    def note(self, state):
        """Record one trace of the wrapper owning `state`. Called from
        inside traced bodies: runs at trace time only."""
        with self._lock:
            state.count += 1
            self.total_traces += 1
            if state.count <= 1:
                return
            self.retraces += 1
            if not self.armed:
                return
            self.post_arm_retraces += 1
            event = {"kind": "retrace", "label": state.label,
                     "trace_number": state.count,
                     "post_arm_index": self.post_arm_retraces}
            self.events.append(event)
            warned = self._warned.get(state.label, 0)
            self._warned[state.label] = warned + 1
            metrics_instances = list(self._metrics)
        # outside the lock: logging/metrics must not deadlock a nested note
        if warned < WARNINGS_PER_LABEL:
            tail = ("; further retraces of this program will be counted "
                    "but not logged" if warned == WARNINGS_PER_LABEL - 1
                    else "")
            logger.warning(
                f"post-warmup retrace of '{state.label}' (trace "
                f"#{state.count}): a hot-path program recompiled after "
                "warmup — check for changing shapes/dtypes or unstable "
                f"static arguments (DTL003 territory){tail}")
        for m in metrics_instances:
            try:
                m.inc("dedalus/retrace")
            except Exception:
                pass


sentinel = RetraceSentinel()


def noted(fn, label=None):
    """Wrap a function destined for `jax.jit` (or another tracer) with the
    trace-time sentinel side effect. The wrapper must only be called under
    tracing (e.g. `jax.jit(noted(probe, "health/probe"))`); calling it
    eagerly would count executions as traces."""
    state = TraceCount(label or getattr(fn, "__qualname__", "traced_fn"))

    def wrapper(*args, **kwargs):
        sentinel.note(state)
        return fn(*args, **kwargs)

    wrapper.__name__ = getattr(fn, "__name__", "noted")
    wrapper._retrace_state = state
    return wrapper
