"""
Host/environment fingerprint for trajectory rows.

Every row appended to benchmarks/results.jsonl (bench headlines, ledger
rows, probe history, served telemetry routed through the bench sink)
carries one `env` dict from `env_fingerprint()` so the perfwatch
sentinel (tools/perfwatch.py) can separate host drift from real
regressions — the PR-16 wall-clock caveat (±15% suite drift on a noisy
shared host) is exactly the ambiguity this resolves: when a number
moves, `env` says whether the machine changed under it.

Two hard rules, both load-bearing:

* **Never initialize the JAX backend.** `jax.devices()` /
  `jax.default_backend()` would spin up the platform as a side effect,
  and the bench parent process deliberately stays uninitialized (its
  wedge defense: a hung TPU runtime must wedge a probed subprocess, not
  the driver). Backend fields are reported only when the backend is
  ALREADY live in this process, detected through a guarded private
  check; otherwise they are null — absence is explicit, never forced.
* **Every field degrades independently.** A missing /proc, an
  unimportable jaxlib, or a renamed private attribute nulls that one
  field; the fingerprint itself always comes back.
"""

import hashlib
import os
import platform
import socket
import sys

__all__ = ["env_fingerprint", "stamp_env"]


def _backend_fields():
    """backend / device_count / device_kind — null unless the JAX
    backend is already initialized in this process (reading them must
    never BE the initialization)."""
    fields = {"backend": None, "device_count": None, "device_kind": None}
    try:
        jax = sys.modules.get("jax")
        if jax is None:
            return fields
        # Peek at the bridge through sys.modules rather than importing
        # it: an import could pull private machinery in itself, and a
        # renamed module on a JAX upgrade degrades this to null fields
        # instead of an ImportError.
        xla_bridge = sys.modules.get("jax._src.xla_bridge")
        if xla_bridge is None \
                or not getattr(xla_bridge, "_backends", None):
            return fields
        devices = jax.devices()
        fields["backend"] = str(jax.default_backend())
        fields["device_count"] = len(devices)
        if devices:
            fields["device_kind"] = str(
                getattr(devices[0], "device_kind", None) or None)
    except Exception:
        pass
    return fields


def _version_of(module_name):
    """Version of an already-importable module; importing jax/jaxlib is
    side-effect-safe (only backend *use* initializes platforms)."""
    try:
        module = __import__(module_name)
        return str(getattr(module, "__version__", None) or None)
    except Exception:
        return None


def env_fingerprint():
    """One flat dict describing the host this row was measured on.

    Keys (any may be null): `backend`, `device_count`, `device_kind`,
    `cpu_count`, `loadavg_1m`, `jax`, `jaxlib`, `python`, `host` (a
    short blake2b hash of the hostname — joinable, not identifying),
    plus `env_version` for forward evolution.
    """
    env = {"env_version": 1}
    env.update(_backend_fields())
    try:
        env["cpu_count"] = os.cpu_count()
    except Exception:
        env["cpu_count"] = None
    try:
        env["loadavg_1m"] = round(os.getloadavg()[0], 2)
    except (OSError, AttributeError):
        env["loadavg_1m"] = None
    env["jax"] = _version_of("jax")
    env["jaxlib"] = _version_of("jaxlib")
    try:
        env["python"] = platform.python_version()
    except Exception:
        env["python"] = None
    try:
        name = socket.gethostname().encode()
        env["host"] = hashlib.blake2b(name, digest_size=6).hexdigest()
    except Exception:
        env["host"] = None
    return env


def stamp_env(record):
    """setdefault an `env` fingerprint onto one result row (in place,
    also returned). Rows that already carry one keep it — a re-reported
    row keeps the fingerprint of the host that MEASURED it."""
    if isinstance(record, dict):
        record.setdefault("env", env_fingerprint())
    return record
