"""
General-purpose helpers (reference: dedalus/tools/general.py).
"""

import collections.abc


def unify(objects):
    """Check that all objects in a collection are equal and return one."""
    it = iter(objects)
    first = next(it)
    for obj in it:
        if obj != first:
            raise ValueError("Objects are not all equal.")
    return first


def unify_attributes(objects, attr, require=True):
    """Unify an attribute across a collection of objects."""
    attrs = []
    for obj in objects:
        try:
            attrs.append(getattr(obj, attr))
        except AttributeError:
            if require:
                raise
    return unify(attrs)


class OrderedSet(collections.abc.MutableSet):
    """Set preserving insertion order (dict-backed)."""

    def __init__(self, iterable=()):
        self._d = dict.fromkeys(iterable)

    def __contains__(self, item):
        return item in self._d

    def __iter__(self):
        return iter(self._d)

    def __len__(self):
        return len(self._d)

    def add(self, item):
        self._d[item] = None

    def discard(self, item):
        self._d.pop(item, None)

    def update(self, iterable):
        for item in iterable:
            self.add(item)


def replace(data, selectors, replacement):
    """Return a tuple with entries matching `selectors` replaced."""
    return tuple(replacement if d in selectors else d for d in data)


def is_real_dtype(dtype):
    import numpy as np
    return np.issubdtype(np.dtype(dtype), np.floating)


def is_complex_dtype(dtype):
    import numpy as np
    return np.issubdtype(np.dtype(dtype), np.complexfloating)
