"""
Sharded, asynchronous, elastically-restorable checkpoints.

The PR-4 durable-checkpoint path is a synchronous full-state HDF5 write:
the step loop gathers every field to host, transposes to grid or
coefficient layout, and blocks until h5py has flushed — a stall that
grows with state size and with device count (the gather is exactly the
all-to-host collective the sharded step avoids). At fleet scale the
dominant faults are preemption, device loss, and silent corruption, and
the durability layer has to follow the data: per-device, asynchronous,
and verifiable. This module is that layer.

Format (`dedalus-sharded-v1`): one checkpoint = one directory

    ckpt_<seq>_i<iteration>/
        <name>.shard0000.npy     raw np.save of ONE device shard's block
        <name>.shard0001.npy     ...
        MANIFEST.json            written LAST, atomically

  * **Per-shard files.** Each array is written as its device shards:
    `shard_blocks(arr)` walks `arr.addressable_shards` and host-copies
    one shard at a time (`_copy_out`, a module-level hook so tests can
    assert the no-full-gather property) — the global array is never
    materialized on host. Replicated shards are deduplicated by index.
  * **blake2b checksums.** The manifest records a blake2b digest, the
    byte count, and the global index of every shard; restore verifies
    each shard before installing it, so silent media corruption (bit
    rot, torn DMA) is caught at the only moment it can still be routed
    around.
  * **Manifest-written-last commit.** Shard files are fsync'd, then the
    manifest is committed with the `assembly_cache` tmp+fsync+replace
    discipline, then the directory entry is fsync'd. A directory
    without a valid manifest is torn by definition and is quarantined
    (renamed `quarantine_*`) at restore — a crash at ANY byte of a
    write leaves the previous checkpoint untouched and discoverable.
  * **Asynchronous writes.** JAX device arrays are immutable, so a
    checkpoint "capture" is a dict of references; `ShardedCheckpointer`
    in async mode enqueues that dict and returns, and the host copy-out
    + IO run on a background writer thread. The queue has a bounded
    in-flight budget: a submit beyond it blocks (the overrun barrier),
    and the blocked time is the only step-loop stall — recorded as
    `checkpoint_stall_sec`.
  * **Elastic restore.** Shards carry global indices, so restore
    assembles the exact global array regardless of how many devices
    wrote it; the caller re-places it on whatever mesh the restoring
    process has. A checkpoint taken on 8 devices restores onto 4 or 1
    (and vice versa) bit-identically — resharding is a placement
    decision, not a data transformation.

Consumers: `tools/resilience.ResilientLoop` (`[resilience]
CHECKPOINT_FORMAT = sharded`, `CHECKPOINT_ASYNC`) for single solvers,
`core/ensemble.EnsembleSolver.evolve(checkpoint_dir=...)` for fleets
(including the device-loss restore path). Chaos coverage:
`tools/chaos.py` `torn_shard` + `corrupt_shard` drive the quarantine
and fallback branches deterministically in tests/test_dcheckpoint.py.
"""

import hashlib
import json
import logging
import os
import pathlib
import re
import threading
import time

import numpy as np

from . import tracing
from .exceptions import CheckpointError
from .lint.threadcheck import named_lock

logger = logging.getLogger(__name__)

__all__ = ["FORMAT", "ShardedCheckpointer", "list_checkpoints",
           "load_checkpoint", "read_manifest", "restore_latest",
           "shard_blocks", "write_checkpoint"]

FORMAT = "dedalus-sharded-v1"
MANIFEST = "MANIFEST.json"
_CKPT_RE = re.compile(r"^ckpt_(\d+)(?:_i\d+)?$")
_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


def _copy_out(block):
    """Host copy of ONE device shard. Module-level on purpose: the
    zero-full-state-gather test (tests/test_collectives.py) spies on this
    hook and asserts every copied block is shard-sized, never
    global-sized."""
    return np.ascontiguousarray(np.asarray(block))


def _digest(arr):
    """blake2b of a C-contiguous array's raw bytes."""
    return hashlib.blake2b(arr.data, digest_size=16).hexdigest()


def shard_blocks(arr):
    """
    Yield `(index, host_block)` for each unique addressable shard of
    `arr`: `index` is a per-dimension `(start, stop)` tuple into the
    global shape, `host_block` the shard's data copied to host. Host
    values (np arrays, scalars) yield one full-extent block. Replicated
    device shards (same index on several devices) are deduplicated, so a
    replicated array is written once, not once per device.
    """
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        a = np.ascontiguousarray(np.asarray(arr))
        yield tuple((0, s) for s in a.shape), a
        return
    shape = arr.shape
    seen = set()
    for sh in shards:
        index = tuple(
            (0 if sl.start is None else int(sl.start),
             shape[d] if sl.stop is None else int(sl.stop))
            for d, sl in enumerate(sh.index))
        if index in seen:
            continue
        seen.add(index)
        yield index, _copy_out(sh.data)


def _fsync_dir(path):
    try:
        fd = os.open(str(path), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass   # not all filesystems support directory fsync


def list_checkpoints(directory):
    """Committed-or-torn checkpoint directories under `directory`,
    oldest first by sequence number (quarantined ones excluded)."""
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return []
    out = []
    for entry in directory.iterdir():
        m = _CKPT_RE.match(entry.name)
        if m is not None and entry.is_dir():
            out.append((int(m.group(1)), entry))
    return [path for _, path in sorted(out)]


def read_manifest(path):
    """Parse and structurally validate one checkpoint's manifest. Raises
    CheckpointError on a missing/torn/garbage manifest (= an uncommitted
    write: the manifest is written last)."""
    path = pathlib.Path(path)
    mpath = path / MANIFEST
    try:
        manifest = json.loads(mpath.read_text())
    except OSError as exc:
        raise CheckpointError(
            f"checkpoint {path} has no readable manifest (torn write?): "
            f"{exc}", path=path) from exc
    except ValueError as exc:
        raise CheckpointError(
            f"checkpoint {path} manifest is not valid JSON: {exc}",
            path=path) from exc
    if not isinstance(manifest, dict) \
            or manifest.get("format") != FORMAT \
            or not isinstance(manifest.get("arrays"), dict):
        raise CheckpointError(
            f"checkpoint {path} manifest is not a {FORMAT} manifest",
            path=path)
    return manifest


def write_checkpoint(directory, arrays, meta=None, shard_hook=None):
    """
    Write one sharded checkpoint under `directory` (created if needed)
    and commit it manifest-last. `arrays` maps names to device/host
    arrays (device arrays are walked shard-by-shard); `meta` is an
    arbitrary JSON-able dict stored in the manifest. `shard_hook`, when
    given, is called as `shard_hook(shards_written)` after each shard
    file lands — the chaos harness uses it to tear or slow a write
    deterministically. Returns the committed checkpoint path.
    """
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    existing = list_checkpoints(directory)
    seq = 1
    if existing:
        seq = int(_CKPT_RE.match(existing[-1].name).group(1)) + 1
    iteration = int((meta or {}).get("iteration", 0))
    path = directory / f"ckpt_{seq:08d}_i{iteration:08d}"
    path.mkdir()
    manifest = {"format": FORMAT, "seq": seq, "ts": round(time.time(), 3),
                "meta": dict(meta or {}), "arrays": {}}
    shards_written = 0
    for name, arr in arrays.items():
        if not _NAME_RE.match(name):
            raise ValueError(f"unsafe checkpoint array name {name!r}")
        entry = {"shape": [int(s) for s in np.shape(arr)],
                 "dtype": str(np.dtype(getattr(arr, "dtype", type(arr)))),
                 "shards": []}
        for k, (index, block) in enumerate(shard_blocks(arr)):
            fname = f"{name}.shard{k:04d}.npy"
            with open(path / fname, "wb") as f:
                np.save(f, block)
                f.flush()
                os.fsync(f.fileno())
            entry["shards"].append({
                "file": fname,
                "index": [[int(a), int(b)] for a, b in index],
                "blake2b": _digest(block),
                "nbytes": int(block.nbytes),
            })
            shards_written += 1
            if shard_hook is not None:
                shard_hook(shards_written)
        manifest["arrays"][name] = entry
    # commit: manifest written last, atomically (tmp + fsync + replace,
    # the assembly_cache torn-file discipline), then the dir entry synced
    tmp = path / (MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path / MANIFEST)
    _fsync_dir(path)
    _fsync_dir(directory)
    return path


def load_checkpoint(path):
    """
    Load one committed checkpoint: validates the manifest, then every
    shard's blake2b checksum and block shape before assembling the
    global arrays. Returns `(arrays, meta)` with `arrays` mapping names
    to host np arrays. Raises CheckpointError naming the first bad
    shard — the caller (restore_latest) quarantines and falls back.
    """
    path = pathlib.Path(path)
    manifest = read_manifest(path)
    arrays = {}
    for name, entry in manifest["arrays"].items():
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        # zeros, not empty: an undetected coverage gap must never hand
        # back heap garbage — and the element count below catches the
        # gap itself (a manifest whose shards do not tile the global
        # shape, e.g. one written per-process on a multi-process mesh,
        # would otherwise pass every per-shard checksum)
        out = np.zeros(shape, dtype)
        covered = 0
        for shard in entry["shards"]:
            fpath = path / shard["file"]
            try:
                block = np.load(fpath)
            except (OSError, ValueError) as exc:
                raise CheckpointError(
                    f"checkpoint {path}: shard {shard['file']} unreadable "
                    f"(truncated/corrupt?): {exc}", path=path) from exc
            block = np.ascontiguousarray(block)
            if _digest(block) != shard["blake2b"]:
                raise CheckpointError(
                    f"checkpoint {path}: shard {shard['file']} checksum "
                    f"mismatch (silent corruption)", path=path)
            index = tuple(slice(a, b) for a, b in shard["index"])
            expect = tuple(b - a for a, b in shard["index"])
            if block.shape != expect or block.dtype != dtype:
                raise CheckpointError(
                    f"checkpoint {path}: shard {shard['file']} "
                    f"shape/dtype {block.shape}/{block.dtype} does not "
                    f"match its manifest entry {expect}/{dtype}",
                    path=path)
            out[index] = block
            covered += block.size
        if covered != out.size:
            raise CheckpointError(
                f"checkpoint {path}: array {name!r} shards cover "
                f"{covered} of {out.size} elements — incomplete "
                f"coverage (multi-process write? missing shard entry?)",
                path=path)
        arrays[name] = out
    return arrays, manifest.get("meta", {})


def _quarantine(path):
    """Move a torn/corrupt checkpoint aside (forensic evidence, excluded
    from future candidate walks). Best-effort: an un-renameable directory
    is simply skipped on later walks by its recorded rejection."""
    target = path.parent / f"quarantine_{path.name}"
    n = 0
    while target.exists():
        n += 1
        target = path.parent / f"quarantine_{path.name}_{n}"
    try:
        path.rename(target)
        return target
    except OSError as exc:
        logger.warning(f"could not quarantine {path}: {exc}")
        return None


def restore_latest(directory, quarantine=True):
    """
    Load the newest valid checkpoint under `directory`: walks the
    sequence newest-first, quarantining torn (manifest-less) and
    checksum-failed checkpoints and falling back to the previous
    manifest. Returns an event dict `{"path", "seq", "arrays", "meta",
    "fallbacks", "validated"}`, or None when the directory holds no
    checkpoints at all (fresh start). Raises CheckpointError when
    checkpoints exist but none are loadable.
    """
    directory = pathlib.Path(directory)
    candidates = list_checkpoints(directory)
    if not candidates:
        return None
    rejected = []
    validated = 0
    for path in reversed(candidates):
        validated += 1
        try:
            arrays, meta = load_checkpoint(path)
        except CheckpointError as exc:
            logger.warning(f"sharded checkpoint {path} rejected: {exc}")
            entry = {"path": str(path), "reason": str(exc)}
            if quarantine:
                moved = _quarantine(path)
                if moved is not None:
                    entry["quarantined"] = str(moved)
            rejected.append(entry)
            continue
        seq = int(_CKPT_RE.match(path.name).group(1))
        logger.info(
            f"restored sharded checkpoint {path} (seq {seq})"
            + (f" after skipping {len(rejected)} bad checkpoint(s)"
               if rejected else ""))
        return {"path": str(path), "seq": seq, "arrays": arrays,
                "meta": meta, "fallbacks": rejected, "validated": validated}
    raise CheckpointError(
        f"no loadable sharded checkpoint under {directory} "
        f"({len(rejected)} rejected: "
        f"{'; '.join(r['reason'] for r in rejected)})", path=directory)


class ShardedCheckpointer:
    """
    Write-side driver: sequential sharded checkpoints under one
    directory, synchronous or asynchronous, with bounded retention.

    Async mode: `save(arrays, meta)` snapshots the (immutable) device
    references, enqueues the job, and returns — host copy-out and IO run
    on the daemon writer thread. The in-flight budget bounds device
    memory pinned by pending checkpoints: a `save` beyond it blocks
    until the writer catches up (the overrun barrier), and that blocked
    time is the step loop's only stall. `stall_sec` accumulates the
    wall time every `save` call held the caller (in sync mode: the whole
    write); `max_inflight` records the deepest pending queue observed.

    Failures: a write that dies (IO error, injected tear) leaves an
    uncommitted manifest-less directory — harmless by the commit
    protocol — and is recorded in `errors`; `drain()` waits for the
    queue to empty and returns the errors accumulated so far. Writer
    exceptions never propagate into the step loop.

    `io_retry` (a tools/resilience.RetryPolicy) wraps each whole
    checkpoint commit, so transient IO faults retry with backoff under
    the [resilience] IO_RETRIES budget like the HDF5 path's writes.
    """

    def __init__(self, directory, async_write=False, inflight=2, keep=2,
                 io_retry=None, shard_hook=None):
        self.directory = pathlib.Path(directory)
        self.async_write = bool(async_write)
        self.inflight = max(int(inflight), 1)
        self.keep = max(int(keep), 1)
        self.io_retry = io_retry
        # chaos hook: called after every shard file write (see
        # tools/chaos.ChaosInjector.wire_checkpointer)
        self.shard_hook = shard_hook
        self.written = 0
        self.submitted = 0
        self.stall_sec = 0.0
        self.max_inflight = 0
        self.errors = []
        # the two Conditions wait on the SAME underlying lock, so every
        # `with self._not_full:` / `with self._drained:` is an alias for
        # `with self._lock:` (the threadcheck catalog records this)
        self._lock = named_lock(
            "tools/dcheckpoint.py:ShardedCheckpointer._lock")
        self._not_full = threading.Condition(self._lock)
        self._drained = threading.Condition(self._lock)
        self._pending = []
        self._closed = False
        self._thread = None

    # ------------------------------------------------------------- write

    def _commit(self, arrays, meta):
        def write():
            return write_checkpoint(self.directory, arrays, meta,
                                    shard_hook=self.shard_hook)
        try:
            if self.io_retry is not None:
                path = self.io_retry.call(write, label="sharded checkpoint")
            else:
                path = write()
        except Exception as exc:
            # the torn directory left behind is invisible to restore by
            # the manifest-last protocol; record and keep going
            logger.error(f"sharded checkpoint write failed: {exc}")
            self.errors.append(exc)
            return None
        self.written += 1
        self._prune()
        return path

    def _prune(self):
        """Retention: keep the newest `keep` committed checkpoints (the
        previous manifest must survive for torn-newest fallback, so keep
        is floored at 1 and defaults to 2). Uncommitted (manifest-less)
        directories older than the newest committed one are removed too."""
        import shutil
        committed = [p for p in list_checkpoints(self.directory)
                     if (p / MANIFEST).exists()]
        for path in committed[:-self.keep] if self.keep else committed:
            shutil.rmtree(path, ignore_errors=True)
        if committed:
            newest = committed[-1].name
            for path in list_checkpoints(self.directory):
                if not (path / MANIFEST).exists() and path.name < newest:
                    shutil.rmtree(path, ignore_errors=True)

    def _worker(self):
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._drained.wait(timeout=0.5)
                if not self._pending:
                    if self._closed:
                        return
                    continue
                arrays, meta = self._pending[0]
            self._commit(arrays, meta)
            with self._lock:
                self._pending.pop(0)
                self._not_full.notify_all()
                self._drained.notify_all()

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            # daemon: a process killed mid-write leaves a torn directory,
            # which the manifest-last protocol makes invisible to restore
            with self._lock:
                self._closed = False   # save() after close() re-opens
            self._thread = threading.Thread(
                target=self._worker, name="dcheckpoint-writer", daemon=True)
            self._thread.start()

    def save(self, arrays, meta=None):
        """Write (sync) or enqueue (async) one checkpoint. `arrays` holds
        immutable device references, so async capture is sync-free; the
        returned value is the committed path in sync mode, None in async
        mode (use drain() before trusting durability)."""
        arrays = dict(arrays)
        meta = dict(meta or {})
        t0 = time.perf_counter()
        self.submitted += 1
        if not self.async_write:
            path = self._commit(arrays, meta)
            stall = time.perf_counter() - t0
            self.stall_sec += stall
            if tracing.enabled():
                tracing.add_span("checkpoint/submit", stall,
                                 attrs={"mode": "sync"})
            if path is None and self.errors:
                # synchronous callers must SEE the failure (the HDF5 path
                # raises; the resilient loop's final-checkpoint retry and
                # escalation depend on it) — async callers get the same
                # errors from drain()/close()
                raise self.errors[-1]
            return path
        self._ensure_thread()
        waited = False
        with self._not_full:
            while len(self._pending) >= self.inflight:
                waited = True
                self._not_full.wait()   # the overrun barrier
            self._pending.append((arrays, meta))
            self.max_inflight = max(self.max_inflight, len(self._pending))
            self._drained.notify_all()
        stall = time.perf_counter() - t0
        self.stall_sec += stall
        if tracing.enabled():
            tracing.add_span("checkpoint/submit", stall,
                             attrs={"mode": "async", "stalled": waited})
        return None

    def drain(self, timeout=60.0):
        """Block until every enqueued checkpoint has committed (or
        `timeout` expires). Returns the list of accumulated WRITE errors
        (empty = nothing failed); a drain timeout is logged and left
        visible via `pending` — it is the caller's wait giving up, not a
        write failing, so it must not poison later error reporting."""
        deadline = time.monotonic() + float(timeout)
        with self._drained:
            while self._pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        f"checkpoint drain timed out with "
                        f"{len(self._pending)} write(s) still pending")
                    break
                self._drained.wait(timeout=min(remaining, 0.5))
        return list(self.errors)

    def close(self, timeout=60.0):
        """Drain and stop the writer thread."""
        errors = self.drain(timeout=timeout)
        with self._lock:
            self._closed = True
            self._drained.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return errors

    @property
    def pending(self):
        with self._lock:
            return len(self._pending)

    def summary(self):
        """Compact stats block for telemetry records."""
        return {
            "format": "sharded",
            "async": self.async_write,
            "written": self.written,
            "submitted": self.submitted,
            "stall_sec": round(self.stall_sec, 6),
            "max_inflight": self.max_inflight,
            "errors": len(self.errors),
        }
