"""
Resilient solve loop: snapshot rewind + dt backoff, preemption-safe
checkpointing, and transient-IO retry classification.

PR 2's health monitor turned a divergence into a graceful halt with a
flight recorder; this module turns it into a *recoverable* event. A
`ResilientLoop` (surfaced as `solver.evolve_resilient(...)`) wraps the
stepping loop with four layers of protection:

  1. **Snapshot ring** — a rolling in-memory ring of last-known-good
     state snapshots, captured every `SNAPSHOT_CADENCE` iterations. JAX
     device arrays are immutable, so a snapshot is a tuple of
     *references* (the gathered pencil state `solver.X`, the multistep
     history arrays, `sim_time`/`iteration`/`dt`, and the evaluator
     scheduling counters): capture costs a few Python attribute reads and
     **never syncs the device** — the hot path stays async.

  2. **Rewind + dt backoff** — on a `SolverHealthError` (NaN/Inf state,
     growth-bound violation, or a non-finite timestep) the loop rewinds
     to the newest snapshot whose state is finite, shrinks the effective
     timestep by `DT_BACKOFF`, waits an exponential wall-clock backoff,
     and retries — up to `MAX_RETRIES` consecutive failures before
     escalating to the existing post-mortem path (the flight recorder of
     every attempt is preserved; dump directories are collision-proof).
     The dt cap relaxes by `DT_RECOVERY` per clean snapshot cadence, so a
     transient stiff patch does not permanently slow the run.

  3. **Preemption safety** — SIGTERM/SIGINT request a *graceful* stop:
     the current step completes, a final durable checkpoint is written
     through the evaluator file-handler path, telemetry is flushed, and
     `run()` returns with `stopped_by` set. `resume_latest(...)` locates
     the newest checkpoint set, validates its integrity (crash-truncated
     or torn newest writes are detected) and falls back write-by-write
     and set-by-set to the previous good data.

  4. **Transient-IO retry** — checkpoint writes and telemetry flushes go
     through a `RetryPolicy` that classifies host/IO faults: transient
     `OSError`s (EIO, EAGAIN, NFS hiccups) are retried with exponential
     backoff; structural ones (ENOENT, EACCES, EISDIR) escalate
     immediately.

Everything is observable: rewinds, retries, dt backoffs, checkpoints
written/validated and resume events are counted under the
`resilience/...` metrics scope (tools/metrics.py), ride in every flushed
telemetry record and bench row, and surface in
`python -m dedalus_tpu report`.

The chaos harness (tools/chaos.py) drives every branch of this machinery
deterministically in tests/test_resilience.py.
"""

import errno
import json
import logging
import os
import pathlib
import signal
import time

import numpy as np

from .config import config
from .exceptions import CheckpointError, SolverHealthError

logger = logging.getLogger(__name__)

__all__ = ["ResilientLoop", "RetryPolicy", "Snapshot", "SnapshotRing",
           "resume_latest", "validate_checkpoint"]


# --------------------------------------------------------------- IO retry

# errnos that indicate a *structural* problem retrying cannot fix
_PERSISTENT_ERRNOS = frozenset({
    errno.ENOENT, errno.EACCES, errno.EPERM, errno.EISDIR, errno.ENOTDIR,
    errno.EROFS, errno.ENAMETOOLONG,
})


class RetryPolicy:
    """
    Retry-with-backoff classification for transient host/IO faults.

    `call(fn)` runs `fn`, retrying on *transient* failures (OSError whose
    errno is not structurally persistent) with exponential wall-clock
    backoff, up to `max_attempts` total attempts. Non-transient
    exceptions — and transient ones past the attempt budget — propagate.
    `on_retry(attempt, exc)` observes each retry (the metrics hook).

    `jitter` (a fraction, default 0: deterministic) spreads each delay
    uniformly over [d*(1-jitter), d*(1+jitter)] — the service client
    uses it so a fleet of retrying clients does not re-stampede a
    recovering daemon in lockstep.
    """

    def __init__(self, max_attempts=3, base_delay=0.05, max_delay=2.0,
                 on_retry=None, jitter=0.0):
        self.max_attempts = max(int(max_attempts), 1)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.on_retry = on_retry
        self.jitter = float(jitter)

    @staticmethod
    def is_transient(exc):
        """Classify one exception: worth retrying?"""
        if isinstance(exc, OSError):
            return exc.errno not in _PERSISTENT_ERRNOS
        return False

    def delay(self, attempt):
        """Backoff before retry `attempt` (1-based): base * 2^(attempt-1),
        capped, jittered."""
        return self.jittered(
            min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay))

    def jittered(self, seconds):
        """Apply this policy's jitter fraction to a delay (used directly
        for server-suggested retry_after_sec hints)."""
        if self.jitter <= 0:
            return seconds
        import random
        return max(seconds * (1.0 + random.uniform(-self.jitter,
                                                   self.jitter)), 0.0)

    def call(self, fn, label="io"):
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except Exception as exc:
                if attempt >= self.max_attempts or not self.is_transient(exc):
                    raise
                delay = self.delay(attempt)
                logger.warning(
                    f"transient {label} fault (attempt {attempt}/"
                    f"{self.max_attempts}): {exc}; retrying in {delay:.3g}s")
                if self.on_retry is not None:
                    self.on_retry(attempt, exc)
                time.sleep(delay)


# -------------------------------------------------------------- snapshots

class Snapshot:
    """
    One last-known-good state capture. Device arrays are held by
    *reference* (JAX arrays are immutable), so capture is sync-free and
    O(1); the arrays stay alive on device for the lifetime of the ring
    slot. Host metadata: sim_time/iteration/dt, the timestepper's
    multistep bookkeeping, and the evaluator scheduling counters.
    """

    __slots__ = ("X", "sim_time", "iteration", "dt", "timestepper_state",
                 "evaluator_state", "dd_X", "wall_ts", "_finite")

    def __init__(self, X, sim_time, iteration, dt, timestepper_state,
                 evaluator_state, dd_X=None):
        self.X = X
        self.sim_time = sim_time
        self.iteration = iteration
        self.dt = dt
        self.timestepper_state = timestepper_state
        self.evaluator_state = evaluator_state
        self.dd_X = dd_X
        self.wall_ts = time.time()
        self._finite = None

    def is_finite(self):
        """Whether the captured state is fully finite. Host-syncs the
        snapshot array on first call — only ever invoked on the recovery
        path, never in the stepping loop."""
        if self._finite is None:
            self._finite = bool(np.all(np.isfinite(np.asarray(self.X))))
        return self._finite


def capture_snapshot(solver):
    """Capture the solver's current state as a Snapshot (sync-free)."""
    ts = solver.timestepper
    ts_state = {"iteration": int(ts.iteration)}
    if hasattr(ts, "F_hist"):
        ts_state.update(
            F_hist=ts.F_hist, MX_hist=ts.MX_hist, LX_hist=ts.LX_hist,
            dt_hist=list(ts.dt_hist))
    ev_state = [h.schedule_state() for h in solver.evaluator.handlers]
    dd = getattr(solver, "_dd", None)
    return Snapshot(
        X=solver.X,
        sim_time=float(solver.sim_time),
        iteration=int(solver.iteration),
        dt=float(solver.dt) if solver.dt is not None else None,
        timestepper_state=ts_state,
        evaluator_state=ev_state,
        dd_X=dd.X if dd is not None else None)


def restore_snapshot(solver, snap):
    """Rewind the solver to a snapshot: state, clocks, timestepper
    history, and evaluator scheduling counters. The LHS factorization is
    invalidated (the retry dt differs anyway) and the health monitor's
    failure latch is cleared so the run can proceed."""
    solver.X = snap.X
    solver.sim_time = snap.sim_time
    solver.iteration = snap.iteration
    solver.dt = snap.dt
    solver.problem.sim_time = snap.sim_time
    ts = solver.timestepper
    st = snap.timestepper_state
    ts.iteration = st["iteration"]
    if "F_hist" in st:
        ts.F_hist = st["F_hist"]
        ts.MX_hist = st["MX_hist"]
        ts.LX_hist = st["LX_hist"]
        ts.dt_hist = list(st["dt_hist"])
    # drop the (possibly poisoned-era) factorization; the next step
    # refactors for its own dt
    ts._lhs_key = None
    ts._lhs_aux = None
    dd = getattr(solver, "_dd", None)
    if dd is not None and snap.dd_X is not None:
        dd.X = snap.dd_X
        dd.reset_history(snap.sim_time)
    for handler, state in zip(solver.evaluator.handlers,
                              snap.evaluator_state):
        handler.restore_schedule_state(state)
    # make the fields see the rewound state (lazy pulls, version-synced)
    solver.defer_scatter(snap.X)
    solver.snapshot_versions()
    solver.health.reset_failure()


class SnapshotRing:
    """Bounded ring of Snapshots, newest last."""

    def __init__(self, size=4):
        self.size = max(int(size), 1)
        self._ring = []

    def __len__(self):
        return len(self._ring)

    @property
    def newest(self):
        return self._ring[-1] if self._ring else None

    def push(self, snap):
        self._ring.append(snap)
        del self._ring[:-self.size]

    def pop_newest_finite(self):
        """Pop and return the newest snapshot whose state is finite,
        discarding poisoned ones (a snapshot taken between the true onset
        and the probe's detection can already carry NaNs). None when the
        whole ring is poisoned or empty."""
        while self._ring:
            snap = self._ring.pop()
            if snap.is_finite():
                return snap
            logger.warning(
                f"snapshot at iteration {snap.iteration} is non-finite; "
                "discarding and rewinding further")
        return None


# -------------------------------------------------- checkpoint validation

def validate_checkpoint(path):
    """
    Integrity-check one checkpoint set file. Returns (n_valid_writes,
    reason): n_valid_writes is the number of trailing-consistent writes
    (0 = unusable), reason explains a rejection. Detects crash-truncated
    files (h5py cannot open them) and torn writes (task datasets shorter
    than the scales cursor — the write died between resizes).
    """
    import h5py
    try:
        with h5py.File(path, "r") as f:
            if "scales/write_number" not in f:
                return 0, "no scales/write_number"
            n = len(f["scales/write_number"])
            if n == 0:
                return 0, "empty write index"
            if "tasks" not in f or not len(f["tasks"]):
                return 0, "no task datasets"
            n_tasks = min(len(f["tasks"][name]) for name in f["tasks"])
            if n_tasks < n:
                return n_tasks, (f"torn write: scales cursor at {n}, "
                                 f"shortest task at {n_tasks}")
            return n, None
    except OSError as exc:
        return 0, f"unreadable (truncated/corrupt?): {exc}"


def resume_latest(solver, base_path, metrics=None):
    """
    Restore the solver from the newest valid checkpoint under
    `base_path` (a FileHandler output directory). Walks the numbered set
    files newest-first, validating each (`validate_checkpoint`) and
    falling back write-by-write within a set (`load_state(...,
    fallback=True)`), so a crash-truncated or torn newest write resumes
    from the previous good one. Returns a resume-event dict, or None
    when no checkpoint directory/sets exist (fresh start). Raises
    CheckpointError when sets exist but none are loadable.
    """
    from .post import get_assigned_sets
    base_path = pathlib.Path(base_path)
    if not base_path.is_dir():
        return None
    sets = get_assigned_sets(base_path)
    if not sets:
        return None
    rejected = []
    for path in reversed(sets):
        n_valid, reason = validate_checkpoint(path)
        if metrics is not None:
            metrics.inc("resilience/checkpoints_validated")
        if n_valid == 0:
            logger.warning(f"checkpoint {path} rejected: {reason}")
            rejected.append({"path": str(path), "reason": reason})
            continue
        try:
            # index clamped to the validated prefix: a torn final write
            # is skipped even though its scales row exists
            write, dt = solver.load_state(path, index=n_valid - 1,
                                          fallback=True)
        except CheckpointError as exc:
            logger.warning(f"checkpoint {path} unloadable: {exc}")
            rejected.append({"path": str(path), "reason": str(exc)})
            continue
        event = {
            "path": str(path),
            "write": int(write),
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "dt": dt,
            "fallbacks": rejected,
        }
        if reason is not None:
            event["validation"] = reason
        logger.info(
            f"resumed from {path} (write {write}, iteration "
            f"{solver.iteration}, sim_time {solver.sim_time:.6e})"
            + (f" after skipping {len(rejected)} bad set(s)"
               if rejected else ""))
        return event
    raise CheckpointError(
        f"no loadable checkpoint under {base_path} "
        f"({len(rejected)} set(s) rejected: "
        f"{'; '.join(r['reason'] for r in rejected)})",
        path=str(base_path))


# ---------------------------------------------------------- the main loop

def _cfg(key, fallback):
    section = config["resilience"] if config.has_section("resilience") else {}
    try:
        return section.get(key, fallback) or fallback
    except AttributeError:
        return fallback


def io_retry_policy(on_retry=None):
    """The [resilience]-configured transient-IO RetryPolicy — the single
    construction point for checkpoint writes AND telemetry-sink emits
    (tools/metrics.py), so IO_RETRIES/IO_BASE_DELAY govern both."""
    return RetryPolicy(max_attempts=int(_cfg("IO_RETRIES", "3")),
                       base_delay=float(_cfg("IO_BASE_DELAY", "0.05")),
                       on_retry=on_retry)


class ResilientLoop:
    """
    Driver wrapping `solver.step` with snapshot rewind, dt backoff,
    preemption-safe checkpointing, and transient-IO retry. Build one via
    `solver.evolve_resilient(...)` (which constructs and runs it) or
    directly for finer control; `run()` returns a summary dict.

    Parameters (None pulls the [resilience] config default):
      timestep_function — adaptive dt callable (e.g. CFL.compute_timestep);
          its output is capped by the post-rewind backoff limit.
      dt — constant timestep when no timestep_function is given.
      snapshot_cadence — iterations between ring captures.
      ring_size — snapshots retained.
      max_retries — consecutive recoveries before escalating.
      dt_backoff — dt shrink factor per recovery (< 1).
      dt_recovery — dt cap growth factor per clean snapshot cadence (> 1).
      retry_base_delay — wall backoff base between recoveries (doubles
          per consecutive retry).
      checkpoint_dir — durable checkpoint directory (None disables
          durable checkpoints AND resume; preemption then stops without
          a final write).
      checkpoint_iter — iterations between durable checkpoints (0: only
          the final preemption/completion write).
      resume — locate/validate/load the newest checkpoint before
          starting (ignored without checkpoint_dir).
      chaos — a tools/chaos.ChaosInjector exercised by tests.
      install_signal_handlers — trap SIGTERM/SIGINT for the run (the
          previous handlers are restored on exit). The warm-pool service
          passes False and drives `request_stop` from its own drain path.
      step_hook — callable(solver) invoked after every successfully
          completed step (never after a failed/rewound one). The serving
          layer uses it to stamp time-to-first-step and stream progress
          frames; it must not mutate the solver.
      flush_telemetry — flush one telemetry record when the loop exits
          (default). The warm-pool service passes False because it owns
          the run's single flush (stamping the served-latency fields on
          it); two records per request would double-count every run.
    """

    def __init__(self, solver, timestep_function=None, dt=None,
                 snapshot_cadence=None, ring_size=None, max_retries=None,
                 dt_backoff=None, dt_recovery=None, retry_base_delay=None,
                 checkpoint_dir=None, checkpoint_iter=None, resume=False,
                 chaos=None, install_signal_handlers=True, step_hook=None,
                 flush_telemetry=True):
        self.solver = solver
        self.timestep_function = timestep_function
        self.dt = float(dt) if dt is not None else None
        self.snapshot_cadence = int(snapshot_cadence
                                    if snapshot_cadence is not None
                                    else _cfg("SNAPSHOT_CADENCE", "50"))
        self.max_retries = int(max_retries if max_retries is not None
                               else _cfg("MAX_RETRIES", "3"))
        self.dt_backoff = float(dt_backoff if dt_backoff is not None
                                else _cfg("DT_BACKOFF", "0.5"))
        self.dt_recovery = float(dt_recovery if dt_recovery is not None
                                 else _cfg("DT_RECOVERY", "2.0"))
        self.retry_base_delay = float(
            retry_base_delay if retry_base_delay is not None
            else _cfg("RETRY_BASE_DELAY", "0.05"))
        self.ring = SnapshotRing(int(ring_size if ring_size is not None
                                     else _cfg("RING_SNAPSHOTS", "4")))
        self.io_retry = io_retry_policy(
            on_retry=lambda attempt, exc:
                solver.metrics.inc("resilience/io_retries"))
        self.checkpoint_dir = (pathlib.Path(checkpoint_dir)
                               if checkpoint_dir else None)
        self.checkpoint_iter = int(checkpoint_iter
                                   if checkpoint_iter is not None
                                   else _cfg("CHECKPOINT_ITER", "0"))
        self.resume = bool(resume)
        self.chaos = chaos
        self.install_signal_handlers = bool(install_signal_handlers)
        self.step_hook = step_hook
        self.flush_telemetry = bool(flush_telemetry)
        # recovery bookkeeping
        self.rewinds = 0
        self.retries = 0
        self.snapshots_captured = 0
        self.dt_limit = None          # post-rewind dt cap (None: unlimited)
        self._consecutive = 0
        self._last_failure_iter = None
        self.lineage = []             # one entry per recovery attempt
        self.resume_event = None
        self.stopped_by = None
        self._stop_signal = None
        self._checkpoint_handler = None
        solver.resilience = self
        if chaos is not None:
            chaos.attach(self)

    # ------------------------------------------------------- checkpoints

    def _ensure_checkpoint_handler(self):
        """The durable-checkpoint FileHandler: one write per set file
        (a crash can at worst truncate the newest set — exactly what
        resume_latest validates), append-mode numbering across restarts,
        coefficient-layout tasks so restore is bitwise."""
        if self._checkpoint_handler is None:
            handler = self.solver.evaluator.add_file_handler(
                self.checkpoint_dir, max_writes=1, mode="append",
                iter=self.checkpoint_iter or None)
            handler.io_retry = self.io_retry
            for var in self.solver.state:
                handler.add_task(var, layout="c", name=var.name)
            self._checkpoint_handler = handler
        return self._checkpoint_handler

    def write_checkpoint(self):
        """Force one durable checkpoint write now (the preemption and
        end-of-run path; periodic writes ride the evaluator schedule).
        Refuses a known-poisoned state: a checkpoint is a promise of
        restartability. Retry is the CALLER's job here (_final_checkpoint
        wraps this whole call), so the handler's own per-write retry is
        suspended to keep the attempt budget single-layered."""
        if self.checkpoint_dir is None:
            return None
        solver = self.solver
        if solver.health_error is not None:
            raise SolverHealthError(
                f"refusing durable checkpoint of a poisoned state: "
                f"{solver.health_error.reason}",
                iteration=int(solver.iteration),
                sim_time=float(solver.sim_time))
        handler = self._ensure_checkpoint_handler()
        saved, handler.io_retry = handler.io_retry, None
        try:
            handler.process(
                iteration=int(solver.iteration),
                wall_time=time.time() - solver.start_time,
                sim_time=float(solver.sim_time),
                timestep=float(solver.dt) if solver.dt is not None else None)
        finally:
            handler.io_retry = saved
        solver.metrics.inc("resilience/checkpoints_written")
        return handler.current_file

    # ----------------------------------------------------------- signals

    def _handle_stop_signal(self, signum, frame):
        """SIGTERM/SIGINT: request a graceful stop. The loop notices at
        the next step boundary; nothing solver-side happens here (the
        handler can interrupt a step mid-dispatch)."""
        self._stop_signal = signum
        logger.warning(
            f"received {signal.Signals(signum).name}: finishing the "
            "current step, writing a final checkpoint, and stopping")

    def _install_signals(self):
        if not self.install_signal_handlers:
            return {}
        previous = {}
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[signum] = signal.signal(
                    signum, self._handle_stop_signal)
            except (ValueError, OSError):
                # non-main thread or unsupported platform: degrade to
                # cooperative stops (request_stop) only
                pass
        return previous

    # ---------------------------------------------------------- recovery

    def _recover(self, err):
        """Rewind to the newest finite snapshot, tighten the dt cap, and
        wait the exponential backoff. Raises the original error when the
        retry budget or the snapshot ring is exhausted (the flight
        recorder of every attempt is already on disk)."""
        solver = self.solver
        self.retries += 1
        self._consecutive += 1
        solver.metrics.inc("resilience/retries")
        entry = {
            "failure_iteration": int(solver.iteration),
            "reason": getattr(err, "reason", str(err)),
            "postmortem": getattr(err, "postmortem_dir", None),
            "attempt": self._consecutive,
        }
        if self._consecutive > self.max_retries:
            entry["outcome"] = "escalated: retry budget exhausted"
            self.lineage.append(entry)
            logger.error(
                f"resilience: {self.max_retries} consecutive recoveries "
                "exhausted; escalating")
            raise err
        snap = self.ring.pop_newest_finite()
        if snap is None:
            entry["outcome"] = "escalated: no finite snapshot"
            self.lineage.append(entry)
            logger.error("resilience: snapshot ring exhausted (no finite "
                         "state to rewind to); escalating")
            raise err
        # dt backoff: cap future timesteps below the dt that failed
        failed_dt = solver.dt or snap.dt or self.dt
        if failed_dt:
            base = self.dt_limit if self.dt_limit is not None else failed_dt
            self.dt_limit = min(base, failed_dt) * self.dt_backoff
            solver.metrics.inc("resilience/dt_backoffs")
        restore_snapshot(solver, snap)
        self.rewinds += 1
        self._last_failure_iter = entry["failure_iteration"]
        solver.metrics.inc("resilience/rewinds")
        entry.update({
            "outcome": "rewound",
            "rewind_iteration": snap.iteration,
            "dt_limit": self.dt_limit,
        })
        self.lineage.append(entry)
        delay = self.retry_base_delay * (2.0 ** (self._consecutive - 1))
        logger.warning(
            f"resilience: rewound iteration "
            f"{entry['failure_iteration']} -> {snap.iteration}, dt capped "
            f"at {self.dt_limit}, retry {self._consecutive}/"
            f"{self.max_retries} in {delay:.3g}s")
        if delay > 0:
            time.sleep(delay)

    def _effective_dt(self):
        dt = (self.timestep_function() if self.timestep_function
              else (self.solver.dt or self.dt))
        if dt is None:
            raise ValueError(
                "evolve_resilient() requires dt=..., a timestep_function, "
                "or a prior solver.step(dt)")
        if self.dt_limit is not None:
            dt = min(float(dt), self.dt_limit)
        return dt

    def _capture(self):
        solver = self.solver
        if solver.fields_dirty():
            # user edits (initial conditions, checkpoint restore) not yet
            # gathered: the anchor snapshot must hold the state the next
            # step will actually use, not the stale X
            solver.X = solver.gather_fields()
        self.ring.push(capture_snapshot(solver))
        self.snapshots_captured += 1
        solver.metrics.inc("resilience/snapshots")
        # a clean cadence past the last failure: relax the dt cap and
        # reset the consecutive-failure budget
        if (self._last_failure_iter is None
                or solver.iteration > self._last_failure_iter):
            self._consecutive = 0
            if self.dt_limit is not None:
                self.dt_limit *= self.dt_recovery
                # with a constant dt the cap clears once it stops binding;
                # under a timestep_function there is no base to compare
                # against, so the cap keeps doubling until min() makes it
                # moot — an effective un-cap
                if self.dt is not None and self.dt_limit >= self.dt:
                    self.dt_limit = None

    def request_stop(self, why="requested"):
        """Cooperative stop request (the signal handler's path, also
        callable directly): honored at the next step boundary."""
        if self._stop_signal is None:
            self._stop_signal = why

    # ---------------------------------------------------------- the loop

    def run(self, log_cadence=100):
        """Drive the solver to completion (or preemption). Returns a
        summary dict (also available as `self.summary()`)."""
        solver = self.solver
        previous_handlers = self._install_signals()
        try:
            if self.resume and self.checkpoint_dir is not None:
                self.resume_event = resume_latest(
                    solver, self.checkpoint_dir, metrics=solver.metrics)
                if self.resume_event is not None:
                    solver.metrics.inc("resilience/resumes")
                    if self.dt is None and self.resume_event["dt"]:
                        self.dt = self.resume_event["dt"]
            if self.checkpoint_dir is not None:
                self._ensure_checkpoint_handler()
            self._capture()   # iteration-0 (or resume-point) anchor
            next_snapshot = solver.iteration + self.snapshot_cadence
            while True:
                # recovery BEFORE the stop check: a preemption landing on
                # the same step as a divergence must rewind first, so the
                # final checkpoint is written from a good state, never
                # the poisoned one
                if solver.health_error is not None:
                    self._recover(solver.health_error)
                    next_snapshot = solver.iteration + self.snapshot_cadence
                    continue
                if self._stop_signal is not None:
                    self._graceful_stop()
                    break
                if not solver.proceed:
                    self.stopped_by = "completed"
                    break
                dt = self._effective_dt()
                try:
                    if self.chaos is not None:
                        self.chaos.before_step(solver)
                    solver.step(dt)
                except SolverHealthError as err:
                    # the raising path (invalid dt): state is unpoisoned
                    # but dt production is broken — same rewind + backoff
                    self._recover(err)
                    next_snapshot = solver.iteration + self.snapshot_cadence
                    continue
                if self.chaos is not None:
                    self.chaos.after_step(solver)
                if self.step_hook is not None \
                        and solver.health_error is None:
                    self.step_hook(solver)
                if solver.health_error is None \
                        and solver.iteration >= next_snapshot:
                    self._capture()
                    next_snapshot = solver.iteration + self.snapshot_cadence
                if log_cadence and solver.iteration % log_cadence == 0:
                    logger.info(
                        f"Iteration={solver.iteration}, "
                        f"Time={solver.sim_time:.6e}, dt={dt:.6e}")
            if self.stopped_by == "completed" and self.checkpoint_dir:
                self._final_checkpoint()
        finally:
            for signum, handler in previous_handlers.items():
                try:
                    signal.signal(signum, handler)
                except (ValueError, OSError):
                    pass
            if self.flush_telemetry:
                try:
                    solver.flush_metrics()
                except Exception as exc:
                    logger.warning(f"final telemetry flush failed: {exc}")
        return self.summary()

    def _graceful_stop(self):
        solver = self.solver
        sig = self._stop_signal
        self.stopped_by = (signal.Signals(sig).name
                           if isinstance(sig, int) else str(sig))
        logger.info(f"resilience: graceful stop ({self.stopped_by}) at "
                    f"iteration {solver.iteration}")
        # last-chance integrity check: preemption can land between a
        # divergence and its cadenced detection — the final checkpoint is
        # a promise of restartability, so probe now and rewind first if
        # the state is poisoned
        if solver.health.enabled and solver.health_error is None:
            try:
                solver.health.check()
            except Exception as exc:
                logger.warning(f"pre-checkpoint health check failed: {exc}")
        if solver.health_error is not None:
            try:
                self._recover(solver.health_error)
            except SolverHealthError:
                logger.error(
                    "resilience: state unrecoverable at preemption; "
                    "skipping the final checkpoint (the flight recorder "
                    "holds the forensic state)")
                return
        self._final_checkpoint()

    def _final_checkpoint(self):
        if self.checkpoint_dir is None:
            return
        try:
            path = self.io_retry.call(self.write_checkpoint,
                                      label="final checkpoint")
            logger.info(f"final checkpoint written: {path}")
        except Exception as exc:
            logger.error(f"final checkpoint failed: {exc}")

    # ----------------------------------------------------------- summary

    def summary(self):
        """Compact record of this loop's resilience activity — attached
        to telemetry flushes (solver.flush_metrics), bench rows, and
        post-mortem dumps (retry lineage)."""
        out = {
            "rewinds": self.rewinds,
            "retries": self.retries,
            "snapshots": self.snapshots_captured,
            "dt_limit": self.dt_limit,
            "stopped_by": self.stopped_by,
        }
        if self.lineage:
            out["lineage"] = list(self.lineage)
        if self.resume_event is not None:
            out["resumed_from"] = self.resume_event["path"]
            out["resume_write"] = self.resume_event["write"]
        return out


def jsonable_summary(summary):
    """Strict-JSON view of a summary (non-finite floats stringified)."""
    return json.loads(json.dumps(summary, default=str))
