"""
Resilient solve loop: snapshot rewind + dt backoff, preemption-safe
checkpointing, and transient-IO retry classification.

PR 2's health monitor turned a divergence into a graceful halt with a
flight recorder; this module turns it into a *recoverable* event. A
`ResilientLoop` (surfaced as `solver.evolve_resilient(...)`) wraps the
stepping loop with four layers of protection:

  1. **Snapshot ring** — a rolling in-memory ring of last-known-good
     state snapshots, captured every `SNAPSHOT_CADENCE` iterations. JAX
     device arrays are immutable, so a snapshot is a tuple of
     *references* (the gathered pencil state `solver.X`, the multistep
     history arrays, `sim_time`/`iteration`/`dt`, and the evaluator
     scheduling counters): capture costs a few Python attribute reads and
     **never syncs the device** — the hot path stays async.

  2. **Rewind + dt backoff** — on a `SolverHealthError` (NaN/Inf state,
     growth-bound violation, or a non-finite timestep) the loop rewinds
     to the newest snapshot whose state is finite, shrinks the effective
     timestep by `DT_BACKOFF`, waits an exponential wall-clock backoff,
     and retries — up to `MAX_RETRIES` consecutive failures before
     escalating to the existing post-mortem path (the flight recorder of
     every attempt is preserved; dump directories are collision-proof).
     The dt cap relaxes by `DT_RECOVERY` per clean snapshot cadence, so a
     transient stiff patch does not permanently slow the run.

  3. **Preemption safety** — SIGTERM/SIGINT request a *graceful* stop:
     the current step completes, a final durable checkpoint is written
     through the evaluator file-handler path, telemetry is flushed, and
     `run()` returns with `stopped_by` set. `resume_latest(...)` locates
     the newest checkpoint set, validates its integrity (crash-truncated
     or torn newest writes are detected) and falls back write-by-write
     and set-by-set to the previous good data.

  4. **Transient-IO retry** — checkpoint writes and telemetry flushes go
     through a `RetryPolicy` that classifies host/IO faults: transient
     `OSError`s (EIO, EAGAIN, NFS hiccups) are retried with exponential
     backoff; structural ones (ENOENT, EACCES, EISDIR) escalate
     immediately.

  5. **Sharded + asynchronous durable checkpoints** — `[resilience]
     CHECKPOINT_FORMAT = sharded` swaps the synchronous full-state HDF5
     gather for the per-shard blake2b-checksummed manifest-last format
     (tools/dcheckpoint.py); `CHECKPOINT_ASYNC = True` moves host
     copy-out and IO onto a background writer with a bounded in-flight
     budget, so the step loop's only checkpoint cost is the submit (and
     the overrun barrier when the writer falls behind). The stall is
     measured per write (`resilience/checkpoint_stall_sec`); restores
     are elastic — a checkpoint written under any device layout
     restores bit-identically under any other.

  6. **Silent-corruption (SDC) sentinel** — every `SDC_CADENCE`
     iterations the loop captures an anchor snapshot, steps, then
     redundantly re-executes that step from the anchor and compares
     against the live state value-exactly (NaN-aware). A mismatch means
     the bits changed without the math changing — flipped DRAM/HBM bit,
     torn DMA — and raises a structured `SilentCorruptionError` with a
     flight-recorder postmortem; under the resilient loop it recovers
     by rewinding to the anchor (no dt backoff: the numerics were never
     wrong). The sentinel SAMPLES: each check covers corruption landing
     between its anchor capture and its comparison (~one step window);
     corruption in an unchecked window is absorbed into the next anchor
     and never detected — raise the cadence for more coverage. Cost per
     check is ~one extra step (+ an LHS refactor); scheduled outputs
     are suppressed during the re-execution so replays never
     double-write.

Everything is observable: rewinds, retries, dt backoffs, checkpoints
written/validated, checkpoint stall seconds, SDC checks/detections and
resume events are counted under the `resilience/...` metrics scope
(tools/metrics.py), ride in every flushed telemetry record and bench
row, and surface in `python -m dedalus_tpu report`.

The chaos harness (tools/chaos.py) drives every branch of this machinery
deterministically in tests/test_resilience.py.
"""

import errno
import json
import logging
import os
import pathlib
import signal
import time

import numpy as np

from .config import config
from .exceptions import (CheckpointError, SilentCorruptionError,
                         SolverHealthError)
from . import dcheckpoint
from . import metrics as metrics_mod
from . import tracing

logger = logging.getLogger(__name__)

__all__ = ["ResilientLoop", "RetryPolicy", "SilentCorruptionError",
           "Snapshot", "SnapshotRing", "resume_latest",
           "validate_checkpoint"]


# --------------------------------------------------------------- IO retry

# errnos that indicate a *structural* problem retrying cannot fix
_PERSISTENT_ERRNOS = frozenset({
    errno.ENOENT, errno.EACCES, errno.EPERM, errno.EISDIR, errno.ENOTDIR,
    errno.EROFS, errno.ENAMETOOLONG,
})


class RetryPolicy:
    """
    Retry-with-backoff classification for transient host/IO faults.

    `call(fn)` runs `fn`, retrying on *transient* failures (OSError whose
    errno is not structurally persistent) with exponential wall-clock
    backoff, up to `max_attempts` total attempts. Non-transient
    exceptions — and transient ones past the attempt budget — propagate.
    `on_retry(attempt, exc)` observes each retry (the metrics hook).

    `jitter` (a fraction, default 0: deterministic) spreads each delay
    uniformly over [d*(1-jitter), d*(1+jitter)] — the service client
    uses it so a fleet of retrying clients does not re-stampede a
    recovering daemon in lockstep.
    """

    def __init__(self, max_attempts=3, base_delay=0.05, max_delay=2.0,
                 on_retry=None, jitter=0.0):
        self.max_attempts = max(int(max_attempts), 1)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.on_retry = on_retry
        self.jitter = float(jitter)

    @staticmethod
    def is_transient(exc):
        """Classify one exception: worth retrying?"""
        if isinstance(exc, OSError):
            return exc.errno not in _PERSISTENT_ERRNOS
        return False

    def delay(self, attempt):
        """Backoff before retry `attempt` (1-based): base * 2^(attempt-1),
        capped, jittered."""
        return self.jittered(
            min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay))

    def jittered(self, seconds):
        """Apply this policy's jitter fraction to a delay (used directly
        for server-suggested retry_after_sec hints)."""
        if self.jitter <= 0:
            return seconds
        import random
        return max(seconds * (1.0 + random.uniform(-self.jitter,
                                                   self.jitter)), 0.0)

    def call(self, fn, label="io"):
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except Exception as exc:
                if attempt >= self.max_attempts or not self.is_transient(exc):
                    raise
                delay = self.delay(attempt)
                logger.warning(
                    f"transient {label} fault (attempt {attempt}/"
                    f"{self.max_attempts}): {exc}; retrying in {delay:.3g}s")
                if self.on_retry is not None:
                    self.on_retry(attempt, exc)
                time.sleep(delay)


# -------------------------------------------------------------- snapshots

class Snapshot:
    """
    One last-known-good state capture. Device arrays are held by
    *reference* (JAX arrays are immutable), so capture is sync-free and
    O(1); the arrays stay alive on device for the lifetime of the ring
    slot. Host metadata: sim_time/iteration/dt, the timestepper's
    multistep bookkeeping, and the evaluator scheduling counters.
    """

    __slots__ = ("X", "sim_time", "iteration", "dt", "timestepper_state",
                 "evaluator_state", "dd_X", "wall_ts", "_finite", "_probe")

    def __init__(self, X, sim_time, iteration, dt, timestepper_state,
                 evaluator_state, dd_X=None, probe=None):
        self.X = X
        self.sim_time = sim_time
        self.iteration = iteration
        self.dt = dt
        self.timestepper_state = timestepper_state
        self.evaluator_state = evaluator_state
        self.dd_X = dd_X
        self.wall_ts = time.time()
        self._finite = None
        self._probe = probe

    def is_finite(self):
        """Whether the captured state is fully finite. Routed through the
        HealthMonitor's fused jitted non-finite probe (`probe` at
        capture): the reduction runs ON DEVICE and only one scalar comes
        back — never a full state gather. Only ever invoked on the
        recovery path, never in the stepping loop."""
        if self._finite is None:
            if self._probe is not None:
                self._finite = self._probe(self.X) == 0
            else:
                # standalone snapshots (no monitor wired): an eager
                # device-side reduction, still a single-scalar pull
                import jax
                import jax.numpy as jnp
                self._finite = bool(jax.device_get(
                    jnp.all(jnp.isfinite(self.X))))
        return self._finite


def capture_snapshot(solver):
    """Capture the solver's current state as a Snapshot (sync-free). The
    attached HealthMonitor's fused value probe rides along so a later
    `is_finite()` costs one device-side reduction, not a state gather."""
    ts = solver.timestepper
    ts_state = {"iteration": int(ts.iteration)}
    if hasattr(ts, "F_hist"):
        # the ring holds cross-step references: copy under donation
        # (core/fusedstep.py guard_histories owns the contract)
        from ..core.fusedstep import guard_histories
        hists = guard_histories(ts)
        ts_state.update(
            F_hist=hists[0], MX_hist=hists[1], LX_hist=hists[2],
            dt_hist=list(ts.dt_hist))
    ev_state = [h.schedule_state() for h in solver.evaluator.handlers]
    dd = getattr(solver, "_dd", None)
    health = getattr(solver, "health", None)
    return Snapshot(
        X=solver.X,
        sim_time=float(solver.sim_time),
        iteration=int(solver.iteration),
        dt=float(solver.dt) if solver.dt is not None else None,
        timestepper_state=ts_state,
        evaluator_state=ev_state,
        dd_X=dd.X if dd is not None else None,
        probe=health.nonfinite_count if health is not None else None)


def restore_snapshot(solver, snap):
    """Rewind the solver to a snapshot: state, clocks, timestepper
    history, and evaluator scheduling counters. The LHS factorization is
    invalidated (the retry dt differs anyway) and the health monitor's
    failure latch is cleared so the run can proceed."""
    solver.X = snap.X
    solver.sim_time = snap.sim_time
    solver.iteration = snap.iteration
    solver.dt = snap.dt
    solver.problem.sim_time = snap.sim_time
    ts = solver.timestepper
    st = snap.timestepper_state
    ts.iteration = st["iteration"]
    if "F_hist" in st:
        # install COPIES under donation: the next (donating) step
        # consumes its history inputs, and a second rewind to this same
        # ring slot must still find live arrays
        from ..core.fusedstep import guard_histories
        ts.F_hist, ts.MX_hist, ts.LX_hist = guard_histories(
            ts, (st["F_hist"], st["MX_hist"], st["LX_hist"]))
        ts.dt_hist = list(st["dt_hist"])
    # drop the (possibly poisoned-era) factorization; the next step
    # refactors for its own dt
    ts._lhs_key = None
    ts._lhs_aux = None
    dd = getattr(solver, "_dd", None)
    if dd is not None and snap.dd_X is not None:
        dd.X = snap.dd_X
        dd.reset_history(snap.sim_time)
    for handler, state in zip(solver.evaluator.handlers,
                              snap.evaluator_state):
        handler.restore_schedule_state(state)
    # make the fields see the rewound state (lazy pulls, version-synced)
    solver.defer_scatter(snap.X)
    solver.snapshot_versions()
    solver.health.reset_failure()


class SnapshotRing:
    """Bounded ring of Snapshots, newest last."""

    def __init__(self, size=4):
        self.size = max(int(size), 1)
        self._ring = []

    def __len__(self):
        return len(self._ring)

    @property
    def newest(self):
        return self._ring[-1] if self._ring else None

    def push(self, snap):
        self._ring.append(snap)
        del self._ring[:-self.size]

    def pop_newest_finite(self):
        """Pop and return the newest snapshot whose state is finite,
        discarding poisoned ones (a snapshot taken between the true onset
        and the probe's detection can already carry NaNs). None when the
        whole ring is poisoned or empty."""
        while self._ring:
            snap = self._ring.pop()
            if snap.is_finite():
                return snap
            logger.warning(
                f"snapshot at iteration {snap.iteration} is non-finite; "
                "discarding and rewinding further")
        return None


# -------------------------------------------------- checkpoint validation

def validate_checkpoint(path):
    """
    Integrity-check one checkpoint set file. Returns (n_valid_writes,
    reason): n_valid_writes is the number of trailing-consistent writes
    (0 = unusable), reason explains a rejection. Detects crash-truncated
    files (h5py cannot open them) and torn writes (task datasets shorter
    than the scales cursor — the write died between resizes).
    """
    import h5py
    try:
        with h5py.File(path, "r") as f:
            if "scales/write_number" not in f:
                return 0, "no scales/write_number"
            n = len(f["scales/write_number"])
            if n == 0:
                return 0, "empty write index"
            if "tasks" not in f or not len(f["tasks"]):
                return 0, "no task datasets"
            n_tasks = min(len(f["tasks"][name]) for name in f["tasks"])
            if n_tasks < n:
                return n_tasks, (f"torn write: scales cursor at {n}, "
                                 f"shortest task at {n_tasks}")
            return n, None
    except OSError as exc:
        return 0, f"unreadable (truncated/corrupt?): {exc}"


def resume_latest(solver, base_path, metrics=None):
    """
    Restore the solver from the newest valid checkpoint under
    `base_path` (a FileHandler output directory). Walks the numbered set
    files newest-first, validating each (`validate_checkpoint`) and
    falling back write-by-write within a set (`load_state(...,
    fallback=True)`), so a crash-truncated or torn newest write resumes
    from the previous good one. Returns a resume-event dict, or None
    when no checkpoint directory/sets exist (fresh start). Raises
    CheckpointError when sets exist but none are loadable.
    """
    from .post import get_assigned_sets
    base_path = pathlib.Path(base_path)
    if not base_path.is_dir():
        return None
    sets = get_assigned_sets(base_path)
    if not sets:
        return None
    rejected = []
    for path in reversed(sets):
        n_valid, reason = validate_checkpoint(path)
        if metrics is not None:
            metrics.inc("resilience/checkpoints_validated")
        if n_valid == 0:
            logger.warning(f"checkpoint {path} rejected: {reason}")
            rejected.append({"path": str(path), "reason": reason})
            continue
        try:
            # index clamped to the validated prefix: a torn final write
            # is skipped even though its scales row exists
            write, dt = solver.load_state(path, index=n_valid - 1,
                                          fallback=True)
        except CheckpointError as exc:
            logger.warning(f"checkpoint {path} unloadable: {exc}")
            rejected.append({"path": str(path), "reason": str(exc)})
            continue
        event = {
            "path": str(path),
            "write": int(write),
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "dt": dt,
            "fallbacks": rejected,
        }
        if reason is not None:
            event["validation"] = reason
        logger.info(
            f"resumed from {path} (write {write}, iteration "
            f"{solver.iteration}, sim_time {solver.sim_time:.6e})"
            + (f" after skipping {len(rejected)} bad set(s)"
               if rejected else ""))
        return event
    raise CheckpointError(
        f"no loadable checkpoint under {base_path} "
        f"({len(rejected)} set(s) rejected: "
        f"{'; '.join(r['reason'] for r in rejected)})",
        path=str(base_path))


# ---------------------------------------------------------- the main loop

def _cfg(key, fallback):
    section = config["resilience"] if config.has_section("resilience") else {}
    try:
        return section.get(key, fallback) or fallback
    except AttributeError:
        return fallback


def _as_bool(value):
    if isinstance(value, str):
        return value.strip().lower() in ("1", "true", "yes", "on")
    return bool(value)


def io_retry_policy(on_retry=None):
    """The [resilience]-configured transient-IO RetryPolicy — the single
    construction point for checkpoint writes AND telemetry-sink emits
    (tools/metrics.py), so IO_RETRIES/IO_BASE_DELAY govern both."""
    return RetryPolicy(max_attempts=int(_cfg("IO_RETRIES", "3")),
                       base_delay=float(_cfg("IO_BASE_DELAY", "0.05")),
                       on_retry=on_retry)


class ResilientLoop:
    """
    Driver wrapping `solver.step` with snapshot rewind, dt backoff,
    preemption-safe checkpointing, and transient-IO retry. Build one via
    `solver.evolve_resilient(...)` (which constructs and runs it) or
    directly for finer control; `run()` returns a summary dict.

    Parameters (None pulls the [resilience] config default):
      timestep_function — adaptive dt callable (e.g. CFL.compute_timestep);
          its output is capped by the post-rewind backoff limit.
      dt — constant timestep when no timestep_function is given.
      snapshot_cadence — iterations between ring captures.
      ring_size — snapshots retained.
      max_retries — consecutive recoveries before escalating.
      dt_backoff — dt shrink factor per recovery (< 1).
      dt_recovery — dt cap growth factor per clean snapshot cadence (> 1).
      retry_base_delay — wall backoff base between recoveries (doubles
          per consecutive retry).
      checkpoint_dir — durable checkpoint directory (None disables
          durable checkpoints AND resume; preemption then stops without
          a final write).
      checkpoint_iter — iterations between durable checkpoints (0: only
          the final preemption/completion write).
      checkpoint_format — "hdf5" (the evaluator FileHandler path) or
          "sharded" (tools/dcheckpoint.py: per-shard files + blake2b
          checksums + manifest-last commit, elastic restore).
      checkpoint_async — sharded format only: host copy-out + IO on a
          background writer thread with a bounded in-flight budget
          (CHECKPOINT_INFLIGHT); the step loop pays only the submit.
      sdc_cadence — iterations between silent-corruption sentinel
          checks (0 disables): each check re-executes the step just
          taken from an anchor snapshot and compares value-exactly.
      resume — locate/validate/load the newest checkpoint before
          starting (ignored without checkpoint_dir; the format is
          auto-detected from what the directory holds, so a run can
          migrate formats across restarts).
      chaos — a tools/chaos.ChaosInjector exercised by tests.
      install_signal_handlers — trap SIGTERM/SIGINT for the run (the
          previous handlers are restored on exit). The warm-pool service
          passes False and drives `request_stop` from its own drain path.
      step_hook — callable(solver) invoked after every successfully
          completed step (never after a failed/rewound one). The serving
          layer uses it to stamp time-to-first-step and stream progress
          frames; it must not mutate the solver.
      flush_telemetry — flush one telemetry record when the loop exits
          (default). The warm-pool service passes False because it owns
          the run's single flush (stamping the served-latency fields on
          it); two records per request would double-count every run.
    """

    def __init__(self, solver, timestep_function=None, dt=None,
                 snapshot_cadence=None, ring_size=None, max_retries=None,
                 dt_backoff=None, dt_recovery=None, retry_base_delay=None,
                 checkpoint_dir=None, checkpoint_iter=None,
                 checkpoint_format=None, checkpoint_async=None,
                 checkpoint_inflight=None, checkpoint_keep=None,
                 sdc_cadence=None, resume=False,
                 chaos=None, install_signal_handlers=True, step_hook=None,
                 flush_telemetry=True):
        self.solver = solver
        self.timestep_function = timestep_function
        self.dt = float(dt) if dt is not None else None
        self.snapshot_cadence = int(snapshot_cadence
                                    if snapshot_cadence is not None
                                    else _cfg("SNAPSHOT_CADENCE", "50"))
        self.max_retries = int(max_retries if max_retries is not None
                               else _cfg("MAX_RETRIES", "3"))
        self.dt_backoff = float(dt_backoff if dt_backoff is not None
                                else _cfg("DT_BACKOFF", "0.5"))
        self.dt_recovery = float(dt_recovery if dt_recovery is not None
                                 else _cfg("DT_RECOVERY", "2.0"))
        self.retry_base_delay = float(
            retry_base_delay if retry_base_delay is not None
            else _cfg("RETRY_BASE_DELAY", "0.05"))
        self.ring = SnapshotRing(int(ring_size if ring_size is not None
                                     else _cfg("RING_SNAPSHOTS", "4")))
        self.io_retry = io_retry_policy(
            on_retry=lambda attempt, exc:
                solver.metrics.inc("resilience/io_retries"))
        self.checkpoint_dir = (pathlib.Path(checkpoint_dir)
                               if checkpoint_dir else None)
        self.checkpoint_iter = int(checkpoint_iter
                                   if checkpoint_iter is not None
                                   else _cfg("CHECKPOINT_ITER", "0"))
        self.checkpoint_format = str(
            checkpoint_format if checkpoint_format is not None
            else _cfg("CHECKPOINT_FORMAT", "hdf5")).strip().lower()
        if self.checkpoint_format not in ("hdf5", "sharded"):
            raise ValueError(
                f"checkpoint_format must be 'hdf5' or 'sharded', got "
                f"{self.checkpoint_format!r}")
        self.checkpoint_async = _as_bool(
            checkpoint_async if checkpoint_async is not None
            else _cfg("CHECKPOINT_ASYNC", "False"))
        if self.checkpoint_async and self.checkpoint_format != "sharded":
            raise ValueError(
                "checkpoint_async requires checkpoint_format='sharded' "
                "(the HDF5 FileHandler path is synchronous by design)")
        if self.checkpoint_format == "sharded" \
                and getattr(solver, "_dd", None) is not None:
            raise ValueError(
                "sharded checkpoints support the native step path only; "
                "this solver runs the emulated-f64 (double-double) "
                "runner — use checkpoint_format='hdf5' or build with "
                "[execution] EMULATED_F64 = never")
        self.checkpoint_inflight = int(
            checkpoint_inflight if checkpoint_inflight is not None
            else _cfg("CHECKPOINT_INFLIGHT", "2"))
        self.checkpoint_keep = int(
            checkpoint_keep if checkpoint_keep is not None
            else _cfg("CHECKPOINT_KEEP", "2"))
        self.sdc_cadence = int(sdc_cadence if sdc_cadence is not None
                               else _cfg("SDC_CADENCE", "0"))
        self._sdc_gate = metrics_mod.CadenceGate(self.sdc_cadence)
        self._ckpt_gate = metrics_mod.CadenceGate(self.checkpoint_iter)
        self.sdc_checks = 0
        self.sdc_detected = 0
        self.checkpoint_stall_sec = 0.0
        self._checkpointer = None
        self._compare_prog = None
        self.resume = bool(resume)
        self.chaos = chaos
        self.install_signal_handlers = bool(install_signal_handlers)
        self.step_hook = step_hook
        self.flush_telemetry = bool(flush_telemetry)
        # recovery bookkeeping
        self.rewinds = 0
        self.retries = 0
        self.snapshots_captured = 0
        self.dt_limit = None          # post-rewind dt cap (None: unlimited)
        self._consecutive = 0
        self._last_failure_iter = None
        self.lineage = []             # one entry per recovery attempt
        self.resume_event = None
        self.stopped_by = None
        self._stop_signal = None
        self._checkpoint_handler = None
        solver.resilience = self
        if chaos is not None:
            chaos.attach(self)

    # ------------------------------------------------------- checkpoints

    def _ensure_checkpoint_handler(self):
        """The durable-checkpoint FileHandler: one write per set file
        (a crash can at worst truncate the newest set — exactly what
        resume_latest validates), append-mode numbering across restarts,
        coefficient-layout tasks so restore is bitwise."""
        if self._checkpoint_handler is None:
            handler = self.solver.evaluator.add_file_handler(
                self.checkpoint_dir, max_writes=1, mode="append",
                iter=self.checkpoint_iter or None)
            handler.io_retry = self.io_retry
            for var in self.solver.state:
                handler.add_task(var, layout="c", name=var.name)
            self._checkpoint_handler = handler
        return self._checkpoint_handler

    def _ensure_checkpointer(self):
        """The sharded-checkpoint writer (tools/dcheckpoint.py): per-shard
        commit with the transient-IO retry policy inside the writer, so
        async writes retry on their own thread under the same
        IO_RETRIES/IO_BASE_DELAY budget as everything else."""
        if self._checkpointer is None:
            self._checkpointer = dcheckpoint.ShardedCheckpointer(
                self.checkpoint_dir, async_write=self.checkpoint_async,
                inflight=self.checkpoint_inflight, keep=self.checkpoint_keep,
                io_retry=io_retry_policy(on_retry=lambda attempt, exc:
                    self.solver.metrics.inc("resilience/io_retries")))
            if self.chaos is not None:
                wire = getattr(self.chaos, "wire_checkpointer", None)
                if wire is not None:
                    wire(self._checkpointer)
        return self._checkpointer

    def _sharded_state(self):
        """The solver state as named arrays + JSON meta for the sharded
        format. Arrays are device REFERENCES (immutable), so async
        capture is sync-free — the writer thread does the per-shard host
        copies."""
        solver = self.solver
        if solver.fields_dirty():
            solver.X = solver.gather_fields()
        ts = solver.timestepper
        arrays = {"X": solver.X}
        meta = {
            "kind": "ivp",
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "dt": float(solver.dt) if solver.dt is not None else None,
            "ts_iteration": int(ts.iteration),
            "scheme": type(ts).__name__,
            "pencil_shape": [int(s) for s in solver.pencil_shape],
        }
        if hasattr(ts, "F_hist"):
            # async writers copy shards out AFTER submit; a donating
            # step between submit and copy-out would consume these
            # buffers, so the capture owns copies (guard_histories)
            from ..core.fusedstep import guard_histories
            hists = guard_histories(ts)
            arrays.update(F_hist=hists[0], MX_hist=hists[1],
                          LX_hist=hists[2])
            meta["dt_hist"] = [float(v) for v in ts.dt_hist]
        return arrays, meta

    def write_checkpoint(self):
        """Force one durable checkpoint write now (the preemption and
        end-of-run path; periodic writes ride the evaluator schedule for
        HDF5, the loop's own gate for sharded). Refuses a known-poisoned
        state: a checkpoint is a promise of restartability. The wall
        time this call holds the step loop is the measured
        `checkpoint_stall_sec` — for async sharded writes that is just
        the submit (plus any overrun-barrier wait). On the HDF5 path,
        retry is the CALLER's job (_final_checkpoint wraps this whole
        call), so the handler's own per-write retry is suspended to keep
        the attempt budget single-layered."""
        if self.checkpoint_dir is None:
            return None
        solver = self.solver
        if solver.health_error is not None:
            raise SolverHealthError(
                f"refusing durable checkpoint of a poisoned state: "
                f"{solver.health_error.reason}",
                iteration=int(solver.iteration),
                sim_time=float(solver.sim_time))
        t0 = time.perf_counter()
        # span duration == the stall this write holds the step loop for
        # (async sharded: just the submit + any overrun-barrier wait)
        with tracing.span("checkpoint/write",
                          attrs={"format": self.checkpoint_format,
                                 "iteration": int(solver.iteration)}):
            if self.checkpoint_format == "sharded":
                arrays, meta = self._sharded_state()
                result = self._ensure_checkpointer().save(arrays, meta)
            else:
                handler = self._ensure_checkpoint_handler()
                saved, handler.io_retry = handler.io_retry, None
                try:
                    handler.process(
                        iteration=int(solver.iteration),
                        wall_time=time.time() - solver.start_time,
                        sim_time=float(solver.sim_time),
                        timestep=float(solver.dt)
                        if solver.dt is not None else None)
                finally:
                    handler.io_retry = saved
                result = handler.current_file
        stall = time.perf_counter() - t0
        self.checkpoint_stall_sec += stall
        solver.metrics.inc("resilience/checkpoint_stall_sec", stall)
        solver.metrics.inc("resilience/checkpoints_written")
        return result

    # ----------------------------------------------------------- signals

    def _handle_stop_signal(self, signum, frame):
        """SIGTERM/SIGINT: request a graceful stop. The loop notices at
        the next step boundary; nothing solver-side happens here (the
        handler can interrupt a step mid-dispatch)."""
        self._stop_signal = signum
        logger.warning(
            f"received {signal.Signals(signum).name}: finishing the "
            "current step, writing a final checkpoint, and stopping")

    def _install_signals(self):
        if not self.install_signal_handlers:
            return {}
        previous = {}
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[signum] = signal.signal(
                    signum, self._handle_stop_signal)
            except (ValueError, OSError):
                # non-main thread or unsupported platform: degrade to
                # cooperative stops (request_stop) only
                pass
        return previous

    # ---------------------------------------------------------- recovery

    def _recover(self, err):
        """Rewind to the newest finite snapshot, tighten the dt cap, and
        wait the exponential backoff. Raises the original error when the
        retry budget or the snapshot ring is exhausted (the flight
        recorder of every attempt is already on disk)."""
        solver = self.solver
        self.retries += 1
        self._consecutive += 1
        solver.metrics.inc("resilience/retries")
        entry = {
            "failure_iteration": int(solver.iteration),
            "reason": getattr(err, "reason", str(err)),
            "postmortem": getattr(err, "postmortem_dir", None),
            "attempt": self._consecutive,
        }
        if self._consecutive > self.max_retries:
            entry["outcome"] = "escalated: retry budget exhausted"
            self.lineage.append(entry)
            logger.error(
                f"resilience: {self.max_retries} consecutive recoveries "
                "exhausted; escalating")
            raise err
        snap = self.ring.pop_newest_finite()
        if snap is None:
            entry["outcome"] = "escalated: no finite snapshot"
            self.lineage.append(entry)
            logger.error("resilience: snapshot ring exhausted (no finite "
                         "state to rewind to); escalating")
            raise err
        # dt backoff: cap future timesteps below the dt that failed —
        # except for silent corruption, where the numerics were never
        # wrong (the bits were): shrinking dt would slow the run for a
        # fault dt cannot influence
        failed_dt = None if isinstance(err, SilentCorruptionError) \
            else (solver.dt or snap.dt or self.dt)
        if failed_dt:
            base = self.dt_limit if self.dt_limit is not None else failed_dt
            self.dt_limit = min(base, failed_dt) * self.dt_backoff
            solver.metrics.inc("resilience/dt_backoffs")
        restore_snapshot(solver, snap)
        self.rewinds += 1
        self._last_failure_iter = entry["failure_iteration"]
        solver.metrics.inc("resilience/rewinds")
        entry.update({
            "outcome": "rewound",
            "rewind_iteration": snap.iteration,
            "dt_limit": self.dt_limit,
        })
        self.lineage.append(entry)
        delay = self.retry_base_delay * (2.0 ** (self._consecutive - 1))
        logger.warning(
            f"resilience: rewound iteration "
            f"{entry['failure_iteration']} -> {snap.iteration}, dt capped "
            f"at {self.dt_limit}, retry {self._consecutive}/"
            f"{self.max_retries} in {delay:.3g}s")
        if delay > 0:
            time.sleep(delay)

    def _effective_dt(self):
        dt = (self.timestep_function() if self.timestep_function
              else (self.solver.dt or self.dt))
        if dt is None:
            raise ValueError(
                "evolve_resilient() requires dt=..., a timestep_function, "
                "or a prior solver.step(dt)")
        if self.dt_limit is not None:
            dt = min(float(dt), self.dt_limit)
        return dt

    def _capture(self):
        solver = self.solver
        if solver.fields_dirty():
            # user edits (initial conditions, checkpoint restore) not yet
            # gathered: the anchor snapshot must hold the state the next
            # step will actually use, not the stale X
            solver.X = solver.gather_fields()
        self.ring.push(capture_snapshot(solver))
        self.snapshots_captured += 1
        solver.metrics.inc("resilience/snapshots")
        # a clean cadence past the last failure: relax the dt cap and
        # reset the consecutive-failure budget
        if (self._last_failure_iter is None
                or solver.iteration > self._last_failure_iter):
            self._consecutive = 0
            if self.dt_limit is not None:
                self.dt_limit *= self.dt_recovery
                # with a constant dt the cap clears once it stops binding;
                # under a timestep_function there is no base to compare
                # against, so the cap keeps doubling until min() makes it
                # moot — an effective un-cap
                if self.dt is not None and self.dt_limit >= self.dt:
                    self.dt_limit = None

    def request_stop(self, why="requested"):
        """Cooperative stop request (the signal handler's path, also
        callable directly): honored at the next step boundary."""
        if self._stop_signal is None:
            self._stop_signal = why

    # ---------------------------------------------------------- the loop

    def run(self, log_cadence=100):
        """Drive the solver to completion (or preemption). Returns a
        summary dict (also available as `self.summary()`)."""
        solver = self.solver
        previous_handlers = self._install_signals()
        try:
            if self.resume and self.checkpoint_dir is not None:
                self._resume_any()
            if self.checkpoint_dir is not None:
                if self.checkpoint_format == "hdf5":
                    self._ensure_checkpoint_handler()
                else:
                    self._ensure_checkpointer()
                    self._ckpt_gate.reset(int(solver.iteration))
            self._capture()   # iteration-0 (or resume-point) anchor
            next_snapshot = solver.iteration + self.snapshot_cadence
            while True:
                # recovery BEFORE the stop check: a preemption landing on
                # the same step as a divergence must rewind first, so the
                # final checkpoint is written from a good state, never
                # the poisoned one
                if solver.health_error is not None:
                    self._recover(solver.health_error)
                    next_snapshot = solver.iteration + self.snapshot_cadence
                    continue
                if self._stop_signal is not None:
                    self._graceful_stop()
                    break
                if not solver.proceed:
                    self.stopped_by = "completed"
                    break
                dt = self._effective_dt()
                # SDC sentinel anchor: captured BEFORE the step that the
                # sentinel will re-execute; pushed on the ring so a
                # detection rewinds exactly here
                sdc_anchor = None
                if self.sdc_cadence \
                        and self._sdc_gate.due(solver.iteration + 1):
                    if solver.fields_dirty():
                        solver.X = solver.gather_fields()
                    sdc_anchor = capture_snapshot(solver)
                    self.ring.push(sdc_anchor)
                try:
                    if self.chaos is not None:
                        self.chaos.before_step(solver)
                    solver.step(dt)
                except SolverHealthError as err:
                    # the raising path (invalid dt): state is unpoisoned
                    # but dt production is broken — same rewind + backoff
                    self._recover(err)
                    next_snapshot = solver.iteration + self.snapshot_cadence
                    continue
                if self.chaos is not None:
                    self.chaos.after_step(solver)
                if self.step_hook is not None \
                        and solver.health_error is None:
                    self.step_hook(solver)
                if sdc_anchor is not None and solver.health_error is None:
                    err = self._sdc_check(sdc_anchor, dt)
                    if err is not None:
                        self._recover(err)
                        next_snapshot = (solver.iteration
                                         + self.snapshot_cadence)
                        continue
                if solver.health_error is None \
                        and solver.iteration >= next_snapshot:
                    self._capture()
                    next_snapshot = solver.iteration + self.snapshot_cadence
                if solver.health_error is None \
                        and self.checkpoint_dir is not None \
                        and self.checkpoint_format == "sharded" \
                        and self.checkpoint_iter \
                        and self._ckpt_gate.due(solver.iteration):
                    # periodic sharded writes run from the loop (the HDF5
                    # path rides the evaluator schedule instead)
                    try:
                        self.write_checkpoint()
                    except Exception as exc:
                        logger.warning(f"periodic checkpoint failed: {exc}")
                if log_cadence and solver.iteration % log_cadence == 0:
                    logger.info(
                        f"Iteration={solver.iteration}, "
                        f"Time={solver.sim_time:.6e}, dt={dt:.6e}")
            if self.stopped_by == "completed" and self.checkpoint_dir:
                self._final_checkpoint()
        finally:
            for signum, handler in previous_handlers.items():
                try:
                    signal.signal(signum, handler)
                except (ValueError, OSError):
                    pass
            if self._checkpointer is not None:
                for exc in self._checkpointer.close():
                    logger.error(f"async checkpoint write failed: {exc}")
            if self.flush_telemetry:
                try:
                    solver.flush_metrics()
                except Exception as exc:
                    logger.warning(f"final telemetry flush failed: {exc}")
        return self.summary()

    def _newest_sharded_ts(self):
        """Commit timestamp of the newest COMMITTED sharded checkpoint
        under checkpoint_dir (torn, manifest-less directories skipped),
        or None."""
        for path in reversed(dcheckpoint.list_checkpoints(
                self.checkpoint_dir)):
            try:
                return float(dcheckpoint.read_manifest(path).get("ts", 0))
            except CheckpointError:
                continue
        return None

    def _newest_hdf5_ts(self):
        """mtime of the newest HDF5 set file under checkpoint_dir, or
        None."""
        from .post import get_assigned_sets
        base = pathlib.Path(self.checkpoint_dir)
        if not base.is_dir():
            return None
        sets = get_assigned_sets(base)
        if not sets:
            return None
        try:
            return os.path.getmtime(sets[-1])
        except OSError:
            return None

    def _resume_any(self):
        """Resume from whatever the checkpoint directory holds — by
        RECENCY when both formats are present (a run can migrate
        CHECKPOINT_FORMAT in either direction across restarts without
        silently resuming older work), with each format falling back to
        the other when its newest data turns out unloadable (e.g. the
        half-migrated case where the first sharded write tore while
        valid HDF5 sets exist). Only when neither format yields anything
        does a failure escalate: checkpoints existed, and a silent fresh
        start would discard the history the operator asked to resume."""
        solver = self.solver

        def try_sharded():
            return self._sharded_resume()

        def try_hdf5():
            return resume_latest(solver, self.checkpoint_dir,
                                 metrics=solver.metrics)

        sharded_ts = self._newest_sharded_ts()
        hdf5_ts = self._newest_hdf5_ts()
        # torn-only sharded dirs (no committed manifest) still mean "a
        # sharded write was attempted"; try that path first only when a
        # commit exists or there is no HDF5 alternative
        if sharded_ts is not None and (hdf5_ts is None
                                       or sharded_ts >= hdf5_ts):
            order = (try_sharded, try_hdf5)
        elif hdf5_ts is not None:
            order = (try_hdf5, try_sharded)
        elif dcheckpoint.list_checkpoints(self.checkpoint_dir):
            order = (try_sharded,)   # torn sharded dirs only: structured
        else:
            order = (try_hdf5,)      # nothing at all: fresh start (None)
        event = None
        first_error = None
        for attempt in order:
            try:
                event = attempt()
            except CheckpointError as exc:
                if first_error is None:
                    first_error = exc
                logger.warning(f"resume attempt failed ({exc}); trying "
                               f"the other checkpoint format")
                continue
            if event is not None:
                break
        if event is None and first_error is not None:
            raise first_error
        self.resume_event = event
        if self.resume_event is not None:
            solver.metrics.inc("resilience/resumes")
            if self.dt is None and self.resume_event["dt"]:
                self.dt = self.resume_event["dt"]

    def _sharded_resume(self):
        """Restore the solver from the newest valid sharded checkpoint:
        per-shard checksums validated, torn/corrupt checkpoints
        quarantined with fallback to the previous manifest
        (tools/dcheckpoint.restore_latest). The restored global arrays
        are placed on the restoring process's own device layout — a
        checkpoint written under any device count restores under any
        other, bit-identically."""
        import jax.numpy as jnp
        solver = self.solver
        event = dcheckpoint.restore_latest(self.checkpoint_dir)
        if event is None:
            return None
        solver.metrics.inc("resilience/checkpoints_validated",
                           event.pop("validated", 1))
        arrays = event.pop("arrays")
        meta = event["meta"]
        if meta.get("kind") != "ivp":
            raise CheckpointError(
                f"sharded checkpoint {event['path']} holds "
                f"{meta.get('kind')!r} state, not a single-solver IVP",
                path=event["path"])
        # an incompatible checkpoint must fail HERE with a named cause,
        # not as a downstream shape error — or worse, a silently wrong
        # multistep history under a different scheme
        if meta.get("scheme") is not None \
                and meta["scheme"] != type(solver.timestepper).__name__:
            raise CheckpointError(
                f"sharded checkpoint {event['path']} was written by "
                f"scheme {meta['scheme']}, this solver runs "
                f"{type(solver.timestepper).__name__}", path=event["path"])
        if meta.get("pencil_shape") is not None \
                and list(meta["pencil_shape"]) != \
                [int(s) for s in solver.pencil_shape]:
            raise CheckpointError(
                f"sharded checkpoint {event['path']} pencil shape "
                f"{meta['pencil_shape']} does not match this solver's "
                f"{list(solver.pencil_shape)}", path=event["path"])
        solver.X = jnp.asarray(arrays["X"])
        ts = solver.timestepper
        ts.iteration = int(meta.get("ts_iteration", 0))
        if "F_hist" in arrays:
            ts.F_hist = jnp.asarray(arrays["F_hist"])
            ts.MX_hist = jnp.asarray(arrays["MX_hist"])
            ts.LX_hist = jnp.asarray(arrays["LX_hist"])
            ts.dt_hist = [float(v) for v in meta.get("dt_hist", [])]
        ts._lhs_key = None
        ts._lhs_aux = None
        solver.sim_time = solver.initial_sim_time = float(meta["sim_time"])
        solver.iteration = solver.initial_iteration = int(meta["iteration"])
        solver.dt = meta.get("dt")
        solver.problem.sim_time = solver.sim_time
        solver.defer_scatter(solver.X)
        solver.snapshot_versions()
        event.update({
            "write": event.pop("seq"),
            "iteration": int(solver.iteration),
            "sim_time": float(solver.sim_time),
            "dt": solver.dt,
            "format": "sharded",
        })
        logger.info(
            f"resumed from sharded checkpoint {event['path']} (iteration "
            f"{solver.iteration}, sim_time {solver.sim_time:.6e})")
        return event

    # ------------------------------------------------------- SDC sentinel

    def _ensure_compare(self):
        """Memoized jitted state comparison over two lists of device
        arrays: the count of elements that differ, NaN-aware (NaN == NaN
        for this purpose), one scalar back to host. Lists, so the check
        covers the multistep history arrays alongside X — corruption in
        F_hist would leave this step's X intact and poison every later
        one."""
        if self._compare_prog is None:
            import jax
            import jax.numpy as jnp
            from . import retrace as retrace_mod

            def raw(live, replay):
                with metrics_mod.trace_scope("resilience", "sdc_compare"):
                    total = jnp.zeros((), dtype=jnp.int32)
                    for a, b in zip(live, replay):
                        same = (a == b) | (jnp.isnan(a) & jnp.isnan(b))
                        total = total + jnp.sum((~same).astype(jnp.int32))
                    return total

            # memoized on self just above (one wrapper per loop)
            self._compare_prog = jax.jit(  # dedalus-lint: disable=DTL003
                retrace_mod.noted(raw, "resilience/sdc_compare"))
        return self._compare_prog

    def _sdc_check(self, anchor, dt):
        """Redundantly re-execute the step just taken from `anchor` and
        compare against the live state. Returns None on a value-exact
        match (the solver is left on the — identical — re-executed
        state), or a SilentCorruptionError (postmortem already dumped)
        for the caller to route through recovery. Scheduled outputs are
        suppressed during the re-execution so a replayed step can never
        double-write analysis files; the redundant step is subtracted
        from the iteration throughput accounting."""
        import jax
        solver = self.solver
        self.sdc_checks += 1
        solver.metrics.inc("resilience/sdc_checks")
        live = capture_snapshot(solver)
        restore_snapshot(solver, anchor)
        evaluator = solver.evaluator
        saved_eval = evaluator.evaluate_scheduled
        evaluator.evaluate_scheduled = lambda **kw: None
        try:
            solver.step(dt)
        finally:
            evaluator.evaluate_scheduled = saved_eval
        solver.metrics.observe_steps(-1)   # verification, not progress
        live_leaves = [live.X]
        replay_leaves = [solver.X]
        st = live.timestepper_state
        if "F_hist" in st:
            ts = solver.timestepper
            live_leaves += [st["F_hist"], st["MX_hist"], st["LX_hist"]]
            replay_leaves += [ts.F_hist, ts.MX_hist, ts.LX_hist]
        # one scalar pull per SDC_CADENCE iterations — the sentinel IS the
        # cadence gate this rule asks for
        mismatched = int(jax.device_get(  # dedalus-lint: disable=DTL001
            self._ensure_compare()(live_leaves, replay_leaves)))
        if mismatched == 0:
            # bit-for-bit agreement: the solver now holds the (identical)
            # re-executed state; only the evaluator's schedule counters
            # need the live values back (the replay skipped them)
            for handler, state in zip(evaluator.handlers,
                                      live.evaluator_state):
                handler.restore_schedule_state(state)
            return None
        self.sdc_detected += 1
        solver.metrics.inc("resilience/sdc_detected")
        reason = (f"silent corruption detected: re-executing step "
                  f"{anchor.iteration} -> {live.iteration} from the anchor "
                  f"snapshot diverges from the live state in {mismatched} "
                  f"element(s)")
        pm = None
        try:
            pm = solver.health.dump_postmortem(reason)
        except Exception as exc:
            logger.warning(f"SDC flight-recorder dump failed: {exc}")
        logger.error(f"resilience: {reason}"
                     + (f" (post-mortem: {pm})" if pm else ""))
        return SilentCorruptionError(
            reason, mismatched=mismatched,
            anchor_iteration=anchor.iteration,
            iteration=live.iteration, sim_time=live.sim_time,
            postmortem_dir=str(pm) if pm else None)

    def _graceful_stop(self):
        solver = self.solver
        sig = self._stop_signal
        self.stopped_by = (signal.Signals(sig).name
                           if isinstance(sig, int) else str(sig))
        logger.info(f"resilience: graceful stop ({self.stopped_by}) at "
                    f"iteration {solver.iteration}")
        # last-chance integrity check: preemption can land between a
        # divergence and its cadenced detection — the final checkpoint is
        # a promise of restartability, so probe now and rewind first if
        # the state is poisoned
        if solver.health.enabled and solver.health_error is None:
            try:
                solver.health.check()
            except Exception as exc:
                logger.warning(f"pre-checkpoint health check failed: {exc}")
        if solver.health_error is not None:
            try:
                self._recover(solver.health_error)
            except SolverHealthError:
                logger.error(
                    "resilience: state unrecoverable at preemption; "
                    "skipping the final checkpoint (the flight recorder "
                    "holds the forensic state)")
                return
        self._final_checkpoint()

    def _final_checkpoint(self):
        if self.checkpoint_dir is None:
            return
        try:
            if self.checkpoint_format == "sharded":
                # the ShardedCheckpointer already wraps each commit in
                # the io_retry policy (on its writer thread for async);
                # wrapping the call again would square the attempt
                # budget — the exact double-layering the HDF5 branch
                # suspends the handler's retry to avoid
                path = self.write_checkpoint()
            else:
                path = self.io_retry.call(self.write_checkpoint,
                                          label="final checkpoint")
            if path is None:
                # async submit: durability is confirmed (or denied) at
                # the writer drain in run()'s finally — do not log a
                # "written" line the operator could mistake for durable
                logger.info("final checkpoint submitted to the async "
                            "writer; durability confirmed at drain")
            else:
                logger.info(f"final checkpoint written: {path}")
        except Exception as exc:
            logger.error(f"final checkpoint failed: {exc}")

    # ----------------------------------------------------------- summary

    def summary(self):
        """Compact record of this loop's resilience activity — attached
        to telemetry flushes (solver.flush_metrics), bench rows, and
        post-mortem dumps (retry lineage)."""
        out = {
            "rewinds": self.rewinds,
            "retries": self.retries,
            "snapshots": self.snapshots_captured,
            "dt_limit": self.dt_limit,
            "stopped_by": self.stopped_by,
        }
        if self.sdc_cadence:
            out["sdc_checks"] = self.sdc_checks
            out["sdc_detected"] = self.sdc_detected
        if self.checkpoint_dir is not None:
            ckpt = (dict(self._checkpointer.summary())
                    if self._checkpointer is not None else {})
            ckpt["format"] = self.checkpoint_format
            # authoritative stall: the wall the STEP LOOP was held per
            # write_checkpoint (includes the state capture), matching the
            # resilience/checkpoint_stall_sec counter — NOT the writer-
            # internal save() time the checkpointer summary reports
            ckpt["stall_sec"] = round(self.checkpoint_stall_sec, 6)
            out["checkpoint"] = ckpt
        if self.lineage:
            out["lineage"] = list(self.lineage)
        if self.resume_event is not None:
            out["resumed_from"] = self.resume_event["path"]
            out["resume_write"] = self.resume_event["write"]
        return out


def jsonable_summary(summary):
    """Strict-JSON view of a summary (non-finite floats stringified)."""
    return json.loads(json.dumps(summary, default=str))
