"""
Progress logging for long host-side loops
(reference: dedalus/tools/progress.py:13 log_progress).
"""

import logging
import time

default_logger = logging.getLogger(__name__)


def log_progress(iterable, logger=None, level="info", desc="iteration",
                 iter=None, frac=None, dt=None):
    """
    Wrap an iterable, logging progress every `iter` items, every `frac`
    fraction of the total, or every `dt` seconds.
    """
    logger = logger or default_logger
    log = getattr(logger, level)
    try:
        total = len(iterable)
    except TypeError:
        total = None
    if frac is not None and total:
        iter = max(1, int(frac * total))
    start = last = time.time()
    for i, item in enumerate(iterable):
        yield item
        now = time.time()
        due = False
        if iter is not None and (i + 1) % iter == 0:
            due = True
        if dt is not None and now - last >= dt:
            due = True
        if due:
            last = now
            if total:
                done = (i + 1) / total
                rate = (now - start) / done - (now - start)
                log(f"{desc} {i + 1}/{total} ({100 * done:.0f}%), "
                    f"~{rate:.1f} s remaining")
            else:
                log(f"{desc} {i + 1}")
