"""
Post-processing tools (reference: dedalus/tools/post.py).

Single-controller JAX writes one file per output set, so the reference's
distributed-set merging collapses to concatenating sets; the xarray loader
follows load_tasks_to_xarray (reference: tools/post.py:363).
"""

import pathlib

import numpy as np


def get_assigned_sets(base_path):
    """Sorted set files of an output directory
    (reference: tools/post.py:20 visit_writes set enumeration)."""
    base_path = pathlib.Path(base_path)

    def set_number(p):
        tail = p.stem.rsplit("_s", 1)[1]
        return int(tail) if tail.isdigit() else None

    return sorted((p for p in base_path.glob(f"{base_path.name}_s*.h5")
                   if set_number(p) is not None), key=set_number)


def merge_sets(base_path, output=None, cleanup=False):
    """
    Concatenate all output sets of a handler directory into one file
    (reference: tools/post.py:166 merge_analysis for the serial case).
    Returns the merged file path.
    """
    import h5py
    base_path = pathlib.Path(base_path)
    sets = get_assigned_sets(base_path)
    if not sets:
        raise FileNotFoundError(f"No output sets under {base_path}")
    output = pathlib.Path(output) if output else \
        base_path / f"{base_path.name}_joint.h5"
    with h5py.File(output, "w") as out:
        scales = out.create_group("scales")
        tasks = out.create_group("tasks")
        buffers = {}
        for path in sets:
            with h5py.File(path, "r") as f:
                for group in ("scales", "tasks"):
                    for key in f[group]:
                        buffers.setdefault((group, key), []).append(
                            np.asarray(f[group][key]))
        for (group, key), chunks in buffers.items():
            target = scales if group == "scales" else tasks
            target.create_dataset(key, data=np.concatenate(chunks, axis=0))
    if cleanup:
        for path in sets:
            path.unlink()
    return output


def load_tasks_to_xarray(path, tasks=None):
    """
    Load output tasks into xarray DataArrays keyed by name, with sim_time
    and write_number coordinates (reference: tools/post.py:363
    load_tasks_to_xarray). Requires xarray.
    """
    import h5py
    import xarray
    path = pathlib.Path(path)
    out = {}
    with h5py.File(path, "r") as f:
        t = np.asarray(f["scales/sim_time"]) if "scales/sim_time" in f else None
        writes = (np.asarray(f["scales/write_number"]).astype(int)
                  if "scales/write_number" in f else None)
        names = tasks or list(f["tasks"])
        for name in names:
            dset = f["tasks"][name]
            data = np.asarray(dset)
            # dimension names/coordinates from the attached HDF5 scales
            # (written at dataset creation, core/evaluator.py)
            dims = []
            coords = {}
            seen = set()
            for d in range(data.ndim):
                label = dset.dims[d].label or (
                    "t" if d == 0 else f"dim_{d - 1}")
                if label in seen:
                    label = f"{label}_{d}"
                seen.add(label)
                dims.append(label)
                if len(dset.dims[d]) and \
                        dset.dims[d][0].shape[0] == data.shape[d]:
                    coords[label] = (label, np.asarray(dset.dims[d][0]))
            if dims and dims[0] in ("write", "t"):
                dims[0] = "t"
            if t is not None:
                coords["t"] = ("t", t)
            if writes is not None:
                coords["write_number"] = ("t", writes)
            out[name] = xarray.DataArray(data, dims=dims, coords=coords,
                                         name=name)
    return out
