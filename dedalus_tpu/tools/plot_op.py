"""
Expression-tree plotting (reference: dedalus/tools/plot_op.py): render the
Future/Field operator tree of an expression with matplotlib, or dump it as
indented text.
"""

import numpy as np

__all__ = ["format_op_tree", "plot_operator_tree"]


def _label(node):
    from ..core.field import Field
    if isinstance(node, Field):
        return node.name or "Field"
    if np.isscalar(node):
        return repr(node)
    return type(node).__name__


def _children(node):
    args = getattr(node, "args", None)
    if args is None:
        return []
    from ..core.field import Field, Operand
    return [a for a in args if isinstance(a, Operand) or np.isscalar(a)]


def format_op_tree(op, indent=0):
    """Indented text rendering of the expression tree."""
    lines = ["  " * indent + _label(op)]
    for child in _children(op):
        if np.isscalar(child):
            lines.append("  " * (indent + 1) + repr(child))
        else:
            lines.extend(format_op_tree(child, indent + 1))
    return lines if indent else "\n".join(lines)


def _layout(node, depth, x0, positions, edges):
    """Assign (x, y) positions bottom-up; returns subtree width."""
    children = [c for c in _children(node) if not np.isscalar(c)]
    if not children:
        positions[id(node)] = (x0, -depth, _label(node))
        return 1
    width = 0
    xs = []
    for c in children:
        w = _layout(c, depth + 1, x0 + width, positions, edges)
        xs.append(positions[id(c)][0])
        edges.append((id(node), id(c)))
        width += w
    positions[id(node)] = (sum(xs) / len(xs), -depth, _label(node))
    return max(width, 1)


def plot_operator_tree(op, filename=None, figsize=(8, 5)):
    """Draw the expression tree; saves to `filename` or returns the figure
    (reference: tools/plot_op.py Node-walk rendering)."""
    import matplotlib
    if filename:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    positions = {}
    edges = []
    _layout(op, 0, 0, positions, edges)
    fig, ax = plt.subplots(figsize=figsize)
    for parent, child in edges:
        x1, y1, _ = positions[parent]
        x2, y2, _ = positions[child]
        ax.plot([x1, x2], [y1, y2], "-", color="0.6", zorder=1)
    for x, y, label in positions.values():
        ax.annotate(label, (x, y), ha="center", va="center", zorder=2,
                    bbox=dict(boxstyle="round,pad=0.3", fc="w", ec="0.3"))
    ax.axis("off")
    fig.tight_layout()
    if filename:
        fig.savefig(filename, dpi=120)
        plt.close(fig)
        return filename
    return fig
